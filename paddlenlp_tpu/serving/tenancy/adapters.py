"""AdapterRegistry: content-addressed LoRA store + fixed-size device pool.

The reference serves a 120+-model zoo by keeping ONE base model resident and
swapping low-rank adapters around it; this module is the host half of that
design. Three layers:

- **host store** — adapters keyed by ``adapter_id``, each a content-addressed
  set of per-projection A/B pairs (``{proj: {"A": [L, d_in, r], "B":
  [L, r, d_out]}}``, scaling pre-folded into B at add time) loaded from a
  safetensors file or an in-memory dict. The digest makes re-adds idempotent
  and retries token-exact: the same id always resolves to the same bytes.
- **pool** — fixed-size slot arrays ``[L, P, ...]`` (slot 0 = identity zeros,
  the block-0 sentinel of ``paged_cache``) that the backend places on device
  and the jitted step gathers per batch row. Residency follows the
  ``BlockManager`` discipline verbatim: refcount per resident adapter, LRU of
  zero-ref residents, eviction ONLY under slot pressure — a warm adapter
  stays warm until a cold one needs its slot, and an in-use adapter can
  never be evicted.
- **versioning** — every pool mutation bumps ``version``; the backend caches
  its device copy keyed on it and re-places only when an adapter actually
  loaded or evicted (the sharded-params id-check pattern, applied to the
  adapter pool).

**Concurrency model.** Unlike ``BlockManager`` (engine-loop confined), the
registry is mutated from two sides: ``acquire``/``release`` on the engine
loop thread and ``add``/``remove`` from admin HTTP threads — so every state
transition holds ``_lock``. The ``engine.adapter_load`` fault point fires
inside :meth:`acquire` after the slot decision but before the pool write;
the slot is rolled back on the way out, so an injected load failure can
never leak a slot.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...utils.faults import FaultPoint
from ...utils.log import logger

__all__ = ["AdapterRegistry", "AdapterPressure", "UnknownAdapterError",
           "adapter_dims_from_config", "PROJ_NAMES"]

#: the projections a LoRA adapter may target, in canonical order — the same
#: seven matmuls the serving forward applies per layer
PROJ_NAMES = ("q_proj", "k_proj", "v_proj", "o_proj",
              "gate_proj", "up_proj", "down_proj")

_F_ADAPTER_LOAD = FaultPoint("engine.adapter_load")


class AdapterPressure(RuntimeError):
    """Every pool slot is held by an in-use adapter: the acquire must wait.

    The engine treats this exactly like KV-block pressure — the request stays
    queued and re-tries admission next step; it is NOT an error surfaced to
    the client."""


class UnknownAdapterError(ValueError):
    """``adapter_id`` names no adapter in the host store."""


def adapter_dims_from_config(config) -> Dict[str, Tuple[int, int]]:
    """Per-projection (d_in, d_out) from a model config — the shapes the pool
    arrays must carry for each targetable matmul."""
    h = int(config.hidden_size)
    n_heads = int(config.num_attention_heads)
    n_kv = int(getattr(config, "num_key_value_heads", n_heads) or n_heads)
    head_dim = int(getattr(config, "head_dim", h // n_heads))
    inter = int(getattr(config, "intermediate_size", 4 * h))
    q = n_heads * head_dim
    kv = n_kv * head_dim
    return {
        "q_proj": (h, q),
        "k_proj": (h, kv),
        "v_proj": (h, kv),
        "o_proj": (q, h),
        "gate_proj": (h, inter),
        "up_proj": (h, inter),
        "down_proj": (inter, h),
    }


class _Entry:
    """One stored adapter: canonical weights + content digest."""

    __slots__ = ("adapter_id", "weights", "rank", "digest")

    def __init__(self, adapter_id: str, weights: Dict[str, Dict[str, np.ndarray]],
                 rank: int, digest: str):
        self.adapter_id = adapter_id
        self.weights = weights
        self.rank = rank
        self.digest = digest


def _digest(weights: Dict[str, Dict[str, np.ndarray]]) -> str:
    h = hashlib.sha256()
    for proj in sorted(weights):
        for part in ("A", "B"):
            arr = np.ascontiguousarray(weights[proj][part])
            h.update(f"{proj}.{part}:{arr.dtype}:{arr.shape}".encode())
            h.update(arr.tobytes())
    return h.hexdigest()


class AdapterRegistry:
    """Content-addressed LoRA adapter store + refcounted device-slot pool.

    ``pool_slots`` counts *adapter* slots; the pool arrays carry one extra
    leading slot (index 0) holding zeros — the identity adapter every
    ``adapter_id=None`` row gathers, so one jitted program serves mixed
    adapter/no-adapter batches with no branching.
    """

    def __init__(self, config=None, *, num_layers: Optional[int] = None,
                 proj_dims: Optional[Dict[str, Tuple[int, int]]] = None,
                 max_rank: int = 8, pool_slots: int = 4,
                 dtype=np.float32):
        if config is not None:
            num_layers = int(config.num_hidden_layers)
            proj_dims = adapter_dims_from_config(config)
        if num_layers is None or proj_dims is None:
            raise ValueError("AdapterRegistry needs config= or "
                             "(num_layers= and proj_dims=)")
        if pool_slots < 1:
            raise ValueError("pool_slots must be >= 1")
        if max_rank < 1:
            raise ValueError("max_rank must be >= 1")
        unknown = set(proj_dims) - set(PROJ_NAMES)
        if unknown:
            raise ValueError(f"unknown projections {sorted(unknown)}; "
                             f"targetable: {PROJ_NAMES}")
        self.num_layers = num_layers
        self.proj_dims = dict(proj_dims)
        self.max_rank = max_rank
        self.pool_slots = pool_slots
        self.dtype = np.dtype(dtype)
        self._lock = threading.RLock()
        self._store: Dict[str, _Entry] = {}  # guarded-by: _lock
        self._slots: Dict[str, int] = {}  # guarded-by: _lock
        self._refs: Dict[str, int] = {}  # guarded-by: _lock
        self._lru: "OrderedDict[str, None]" = OrderedDict()  # guarded-by: _lock
        self._free: List[int] = list(range(1, pool_slots + 1))  # guarded-by: _lock
        # host pool arrays, mutated in place under the lock; the backend holds
        # a reference and re-places on device only when `version` moved
        P = pool_slots + 1  # + identity slot 0
        self._pool = {  # guarded-by: _lock
            proj: {
                "A": np.zeros((num_layers, P, d_in, max_rank), self.dtype),
                "B": np.zeros((num_layers, P, max_rank, d_out), self.dtype),
            }
            for proj, (d_in, d_out) in self.proj_dims.items()
        }
        self.version = 1  # pool content generation; bumped on load/evict
        # monotone counters — torn reads skew one scrape by one event, the
        # BlockManager cache_hits contract
        self.hits = 0
        self.misses = 0
        self.loads = 0
        self.evictions = 0

    # ----------------------------------------------------------------- store
    def add(self, adapter_id: str, source, *, scaling: Optional[float] = None) -> str:
        """Register an adapter in the host store; returns its content digest.

        ``source`` is a safetensors path (flat ``{proj}.lora_A`` keys, the
        :meth:`LoRAModel.export_adapter` format) or a dict — nested
        ``{proj: {"A": ..., "B": ...}}`` or the same flat keys. ``scaling``
        (alpha/r) is folded into B here, once, so the pool gather stays a
        plain two-matmul delta; a safetensors source may carry it in
        metadata. Idempotent on identical content; replacing a *different*
        adapter under a live id is refused while any request holds it."""
        if not adapter_id or not isinstance(adapter_id, str):
            raise ValueError("adapter_id must be a non-empty string")
        weights, meta_scaling = self._coerce_source(source)
        if scaling is None:
            scaling = meta_scaling if meta_scaling is not None else 1.0
        weights = self._canonicalize(adapter_id, weights, float(scaling))
        digest = _digest(weights)
        rank = max(w["A"].shape[-1] for w in weights.values())
        with self._lock:
            cur = self._store.get(adapter_id)
            if cur is not None:
                if cur.digest == digest:
                    return digest  # same bytes: no-op re-add
                if self._refs.get(adapter_id, 0) > 0:
                    raise ValueError(
                        f"adapter {adapter_id!r} is in use by "
                        f"{self._refs[adapter_id]} request(s); cannot replace")
                self._evict_locked(adapter_id)  # holds-lock via RLock re-entry
            self._store[adapter_id] = _Entry(adapter_id, weights, rank, digest)
            logger.info(f"adapter {adapter_id!r} registered "
                        f"(rank {rank}, digest {digest[:12]})")
            return digest

    def remove(self, adapter_id: str):
        """Drop an adapter from store and pool. Refused while in use."""
        with self._lock:
            if adapter_id not in self._store:
                raise UnknownAdapterError(f"unknown adapter {adapter_id!r}")
            if self._refs.get(adapter_id, 0) > 0:
                raise ValueError(f"adapter {adapter_id!r} is in use by "
                                 f"{self._refs[adapter_id]} request(s); cannot remove")
            self._evict_locked(adapter_id)
            del self._store[adapter_id]

    def __contains__(self, adapter_id: str) -> bool:
        with self._lock:
            return adapter_id in self._store

    def ids(self) -> List[str]:
        with self._lock:
            return sorted(self._store)

    def digest_of(self, adapter_id: str) -> str:
        with self._lock:
            ent = self._store.get(adapter_id)
            if ent is None:
                raise UnknownAdapterError(f"unknown adapter {adapter_id!r}")
            return ent.digest

    def weights_of(self, adapter_id: str) -> Dict[str, Dict[str, np.ndarray]]:
        """The canonical (B pre-scaled) weights — the round-trip target of
        ``LoRAModel.export_adapter``."""
        with self._lock:
            ent = self._store.get(adapter_id)
            if ent is None:
                raise UnknownAdapterError(f"unknown adapter {adapter_id!r}")
            return {p: {k: v.copy() for k, v in w.items()}
                    for p, w in ent.weights.items()}

    # ----------------------------------------------------------------- pool
    def acquire(self, adapter_id: str) -> int:
        """Take one reference on ``adapter_id``; returns its pool slot,
        loading it into a (possibly LRU-evicted) slot when not resident.

        Raises :exc:`UnknownAdapterError` for an unregistered id,
        :exc:`AdapterPressure` when every slot is pinned by in-use adapters
        (the caller gates admission, exactly like KV-block pressure), and
        whatever the ``engine.adapter_load`` fault point injects — with the
        slot rolled back, so chaos never leaks pool capacity."""
        with self._lock:
            ent = self._store.get(adapter_id)
            if ent is None:
                raise UnknownAdapterError(f"unknown adapter {adapter_id!r}")
            slot = self._slots.get(adapter_id)
            if slot is not None:
                self._refs[adapter_id] = self._refs.get(adapter_id, 0) + 1
                self._lru.pop(adapter_id, None)
                self.hits += 1
                return slot
            self.misses += 1
            if self._free:
                slot = self._free.pop()
            elif self._lru:
                victim, _ = self._lru.popitem(last=False)
                slot = self._slots.pop(victim)
                self._zero_slot(slot)
                self.evictions += 1
                self.version += 1
                logger.info(f"adapter {victim!r} evicted from slot {slot} "
                            f"(pressure from {adapter_id!r})")
            else:
                raise AdapterPressure(
                    f"adapter pool exhausted: all {self.pool_slots} slots "
                    f"pinned by in-use adapters")
            try:
                _F_ADAPTER_LOAD.fire(adapter_id=adapter_id)
                self._write_slot(slot, ent)
            except BaseException:
                # the slot was taken but never published: return it — an
                # injected/real load failure must not leak pool capacity
                self._free.append(slot)
                raise
            self._slots[adapter_id] = slot
            self._refs[adapter_id] = 1
            self.loads += 1
            self.version += 1
            return slot

    def release(self, adapter_id: str):
        """Drop one reference; a zero-ref adapter stays resident on the LRU
        (warm) until slot pressure evicts it."""
        with self._lock:
            r = self._refs.get(adapter_id, 0) - 1
            if r > 0:
                self._refs[adapter_id] = r
                return
            self._refs.pop(adapter_id, None)
            if adapter_id in self._slots:
                self._lru[adapter_id] = None
                self._lru.move_to_end(adapter_id)

    def reset_refs(self):
        """Drop every reference (engine reset: no request survives, so no
        adapter is in use). Residency is kept — the pool stays warm."""
        with self._lock:
            for aid in list(self._refs):
                self._refs.pop(aid, None)
                if aid in self._slots:
                    self._lru[aid] = None

    def slot_of(self, adapter_id: str) -> Optional[int]:
        with self._lock:
            return self._slots.get(adapter_id)

    def refcount(self, adapter_id: str) -> int:
        with self._lock:
            return self._refs.get(adapter_id, 0)

    def resident(self) -> List[str]:
        with self._lock:
            return sorted(self._slots)

    def pool_arrays(self) -> Tuple[Dict[str, Dict[str, np.ndarray]], int]:
        """(host pool tree, version) — read atomically so the backend never
        pairs fresh arrays with a stale version."""
        with self._lock:
            return self._pool, self.version

    def stats(self) -> Dict:
        with self._lock:
            return {
                "registered": len(self._store),
                "resident": len(self._slots),
                "pool_slots": self.pool_slots,
                "free_slots": len(self._free),
                "pinned": sum(1 for v in self._refs.values() if v > 0),
                "hits": self.hits,
                "misses": self.misses,
                "loads": self.loads,
                "evictions": self.evictions,
                "version": self.version,
            }

    # ------------------------------------------------------------- internals
    # holds-lock: _lock
    def _evict_locked(self, adapter_id: str):
        slot = self._slots.pop(adapter_id, None)
        self._lru.pop(adapter_id, None)
        self._refs.pop(adapter_id, None)
        if slot is not None:
            self._zero_slot(slot)
            self._free.append(slot)
            self.evictions += 1
            self.version += 1

    # holds-lock: _lock
    def _zero_slot(self, slot: int):
        for w in self._pool.values():
            w["A"][:, slot] = 0
            w["B"][:, slot] = 0

    # holds-lock: _lock
    def _write_slot(self, slot: int, ent: _Entry):
        self._zero_slot(slot)
        for proj, w in ent.weights.items():
            r = w["A"].shape[-1]
            # zero-padding to max_rank is exact: the padded rank columns of A
            # meet the padded rank rows of B at zero, contributing nothing
            self._pool[proj]["A"][:, slot, :, :r] = w["A"]
            self._pool[proj]["B"][:, slot, :r, :] = w["B"]

    def _coerce_source(self, source):
        """source -> (proj -> {"A","B"} float arrays, scaling from metadata)."""
        meta_scaling = None
        if isinstance(source, str):
            from ...utils.safetensors_io import SafeFile

            with SafeFile(source) as sf:
                meta = sf.metadata or {}
                if "scaling" in meta:
                    meta_scaling = float(meta["scaling"])
                elif "lora_alpha" in meta and "r" in meta:
                    meta_scaling = float(meta["lora_alpha"]) / float(meta["r"])
                source = {k: sf.get_tensor(k) for k in sf.keys()}
        if not isinstance(source, dict):
            raise TypeError(f"adapter source must be a safetensors path or a "
                            f"dict, got {type(source).__name__}")
        if any(isinstance(v, dict) for v in source.values()):
            nested = source
        else:  # flat "{proj}.lora_A" keys
            nested = {}
            for key, arr in source.items():
                if "." not in key:
                    raise ValueError(f"flat adapter key {key!r} is not "
                                     "'{proj}.lora_A' / '{proj}.lora_B'")
                proj, part = key.rsplit(".", 1)
                part = {"lora_A": "A", "lora_B": "B", "A": "A", "B": "B"}.get(part)
                if part is None:
                    raise ValueError(f"adapter key {key!r} must end in "
                                     ".lora_A or .lora_B")
                nested.setdefault(proj, {})[part] = arr
        return nested, meta_scaling

    def _canonicalize(self, adapter_id: str, nested, scaling: float):
        """Validate shapes against the model dims; fold scaling into B."""
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for proj, w in nested.items():
            if proj not in self.proj_dims:
                raise ValueError(f"adapter {adapter_id!r} targets unknown "
                                 f"projection {proj!r}; model has "
                                 f"{sorted(self.proj_dims)}")
            if "A" not in w or "B" not in w:
                raise ValueError(f"adapter {adapter_id!r} projection {proj!r} "
                                 "needs both A and B")
            a = np.asarray(w["A"], dtype=self.dtype)
            b = np.asarray(w["B"], dtype=self.dtype)
            d_in, d_out = self.proj_dims[proj]
            L = self.num_layers
            if a.ndim != 3 or a.shape[0] != L or a.shape[1] != d_in:
                raise ValueError(
                    f"adapter {adapter_id!r} {proj}.A has shape {a.shape}; "
                    f"want [{L}, {d_in}, r<={self.max_rank}]")
            r = a.shape[2]
            if r > self.max_rank:
                raise ValueError(f"adapter {adapter_id!r} rank {r} exceeds "
                                 f"pool max_rank {self.max_rank}")
            if b.shape != (L, r, d_out):
                raise ValueError(
                    f"adapter {adapter_id!r} {proj}.B has shape {b.shape}; "
                    f"want [{L}, {r}, {d_out}] to match A rank {r}")
            out[proj] = {"A": a, "B": b * self.dtype.type(scaling)}
        if not out:
            raise ValueError(f"adapter {adapter_id!r} has no weights")
        return out
