"""Multi-tenant serving: batched multi-LoRA adapters + per-tenant isolation.

Two halves, deliberately separable:

- :mod:`.adapters` — the :class:`AdapterRegistry`: a content-addressed host
  store of LoRA A/B weight pairs plus a fixed-size device-resident adapter
  pool with refcount + LRU eviction (``paged_cache.BlockManager``'s block
  discipline applied to adapter slots). The engine acquires a pool slot per
  admitted request and the backend gathers per-row deltas from the pool
  inside the unchanged jitted step programs.
- :mod:`.quotas` — :class:`TenantQuotas`: per-tenant admission limits (max
  inflight, KV-block share) that ride the existing priority classes, plus
  the per-tenant goodput fold over the engine's request-attributed token
  accounting.
- :mod:`.metering` — :class:`UsageMeter`: billing-grade usage records (one
  per finished request, trace-id idempotent) with a rolling per-tenant/
  per-adapter aggregate and an optional durable JSONL ledger
  (``observability/usage.py``) whose totals reconcile against the goodput
  ledger's useful-token truth.
"""

from .adapters import (AdapterPressure, AdapterRegistry, UnknownAdapterError,
                       adapter_dims_from_config, PROJ_NAMES)
from .metering import UsageMeter
from .quotas import (DEFAULT_TENANT, TenantQuota, TenantQuotas,
                     tenant_goodput_fold)

__all__ = [
    "UsageMeter",
    "AdapterPressure",
    "AdapterRegistry",
    "UnknownAdapterError",
    "adapter_dims_from_config",
    "PROJ_NAMES",
    "DEFAULT_TENANT",
    "TenantQuota",
    "TenantQuotas",
    "tenant_goodput_fold",
]
