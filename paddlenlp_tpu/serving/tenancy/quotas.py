"""Per-tenant admission quotas + the per-tenant goodput fold.

A tenant is a client-declared string on every request (``"default"`` when
absent). Isolation has three legs, each riding machinery that already
exists:

- **max inflight** — the scheduler's admission window, partitioned: a tenant
  at its cap sheds with ``reason="tenant_quota"`` (HTTP 503) while other
  tenants admit normally. Rides the priority classes: the quota check runs
  AFTER brownout, so a browned-out class sheds as before regardless of quota
  headroom.
- **KV-block share** — an engine-side admission gate: a tenant whose running
  requests already hold its share of the usable KV blocks waits in queue
  (the ``kv_pressure`` gate pattern), it is not errored. Prevents one tenant
  with long prompts from starving the pool.
- **goodput fold** — the engine attributes useful/rework token positions per
  request (it already computes them per request for the PR 15 ledger); this
  module folds those per-tenant counters into the ``stats()`` /
  ``/debug/efficiency`` document.

:class:`TenantQuotas` is pure policy (no locks, no counters): callers own
their bookkeeping — the scheduler its inflight map, the engine its per-tenant
block counts — and ask this object only for the limits.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

__all__ = ["DEFAULT_TENANT", "TenantQuota", "TenantQuotas", "tenant_goodput_fold"]

#: the tenant every request without an explicit ``tenant`` field belongs to
DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Limits for one tenant; ``None`` means unlimited."""

    max_inflight: Optional[int] = None
    kv_block_share: Optional[float] = None  # fraction of usable KV blocks, 0..1

    def __post_init__(self):
        if self.max_inflight is not None and self.max_inflight < 1:
            raise ValueError("max_inflight must be >= 1 (or None for unlimited)")
        if self.kv_block_share is not None \
                and not (0.0 < self.kv_block_share <= 1.0):
            raise ValueError("kv_block_share must be in (0, 1] (or None)")


class TenantQuotas:
    """Per-tenant limits with a default for unlisted tenants.

    ``quotas`` maps tenant -> :class:`TenantQuota` (or a plain dict with the
    same fields); ``default`` applies to tenants without an entry — the
    usual fleet shape is one generous default plus explicit caps for the
    noisy tenants."""

    def __init__(self, quotas: Optional[Dict[str, object]] = None,
                 default: Optional[object] = None):
        self._quotas = {t: self._coerce(q) for t, q in (quotas or {}).items()}
        self._default = self._coerce(default) if default is not None \
            else TenantQuota()

    @staticmethod
    def _coerce(q) -> TenantQuota:
        if isinstance(q, TenantQuota):
            return q
        if isinstance(q, dict):
            return TenantQuota(**q)
        raise TypeError(f"tenant quota must be TenantQuota or dict, "
                        f"got {type(q).__name__}")

    def quota(self, tenant: str) -> TenantQuota:
        return self._quotas.get(tenant, self._default)

    def max_inflight(self, tenant: str) -> Optional[int]:
        return self.quota(tenant).max_inflight

    def kv_block_cap(self, tenant: str, total_usable_blocks: int) -> Optional[int]:
        """Absolute block cap for ``tenant`` (None = uncapped), floored at 1
        so a tiny share can never make a tenant unadmittable outright."""
        share = self.quota(tenant).kv_block_share
        if share is None:
            return None
        return max(1, int(share * total_usable_blocks))

    def describe(self) -> Dict:
        return {
            "default": dataclasses.asdict(self._default),
            "tenants": {t: dataclasses.asdict(q) for t, q in sorted(self._quotas.items())},
        }


def tenant_goodput_fold(tenant_counts: Dict[str, Dict[str, int]]) -> Dict[str, Dict]:
    """Fold the engine's per-tenant token attribution into per-tenant goodput.

    ``tenant_counts`` is ``{tenant: {"useful": n, "rework": n, "requests": n,
    "tokens_out": n}}`` (the engine's ``tenant_goodput`` accumulator). The
    per-tenant ratio is ``useful / (useful + rework)`` — padding and
    speculative rejection are step-global costs that cannot be attributed to
    one tenant's rows, so the fold deliberately covers only the attributable
    part of the PR 15 conservation invariant."""
    out: Dict[str, Dict] = {}
    for tenant, c in sorted(tenant_counts.items()):
        useful = int(c.get("useful", 0))
        rework = int(c.get("rework", 0))
        attributed = useful + rework
        out[tenant] = {
            "useful": useful,
            "rework": rework,
            "requests": int(c.get("requests", 0)),
            "tokens_out": int(c.get("tokens_out", 0)),
            "goodput_ratio": round(useful / attributed, 6) if attributed else 1.0,
        }
    return out
