"""Background engine-driver thread: thread-safe submission + token streams,
supervised for fault tolerance.

Counterpart of the reference's serving split (``llm/predict/flask_server.py``
pushes prompts into the inference process and reads tokens back over a SysV
message queue): here the ``InferenceEngine`` runs on ONE dedicated thread that
continuously drives ``engine.step()``, and HTTP worker threads talk to it only
through queues — the engine itself is never touched concurrently, so the
host-side block manager needs no locks.

- ``submit()`` returns a :class:`RequestHandle`: a future (``result()``) plus
  a per-request token queue (``tokens()``) fed by the engine's ``stream_cb``;
- ``cancel()`` routes through the loop thread to ``engine.abort`` so KV blocks
  free deterministically between steps;
- per-request deadlines are enforced by the loop (expired requests abort with
  ``finish_reason='abort'`` and ``timed_out=True`` on the handle);
- all request lifecycle events land in the metrics plane (TTFT, queue wait,
  inter-token latency, tokens, preemptions, KV utilization).

**Supervision.** An exception out of ``engine.step()`` no longer kills the
loop. The loop transitions to DEGRADED: in-flight requests are triaged by the
:class:`SupervisorPolicy` — retryable ones (within their bounded retry budget)
are stashed for requeue, the rest resolve immediately with
``finish_reason="engine_error"`` — then the engine is rebuilt (via the
``engine_factory``, or ``engine.reset()`` in place) after an exponential
backoff, stashed requests are resubmitted with their already-streamed tokens
folded into the prompt (the same recompute trick preemption uses, so greedy
and fixed-seed sampled requests continue with identical tokens), and the loop
resumes. While DEGRADED the :class:`~.scheduler.Scheduler` circuit-breaks new
admissions with 503 + ``Retry-After``. Restarts and retries are exported as
``paddlenlp_serving_engine_restarts_total`` /
``paddlenlp_serving_request_retries_total``, and each degraded window lands in
the span tracer as an ``engine_degraded`` span.

**Concurrency model.** The engine and everything it owns (scheduler state,
``BlockManager``, device handles) are confined to the ONE loop thread — HTTP
worker threads reach them only through the ``_cmds`` queue (thread-safe) and
the per-request :class:`RequestHandle`. ``EngineLoop`` fields are therefore
lock-free by confinement: ``_handles``/``_requeue``/``_last_token_t`` are
written on the loop thread only; ``recent_finished`` is an append-only deque
(atomic ops) that HTTP readers may see a few entries stale; ``_state``/
``_phase``/``_stop`` are single-slot flags where a racy read returns a
momentarily stale-but-valid value by design. The only lock in this module is
``RequestHandle._cb_lock``, guarding the done/callback handoff between the
loop thread and client threads — its fields carry ``# guarded-by:``
annotations enforced by ``tools/analyze`` (lock-discipline checker).
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import queue
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..observability.flight_recorder import RECORDER
from ..observability.goodput import WASTE_KINDS
from ..observability.postmortem import PostmortemDumper
from ..observability.tracer import TRACER
from ..utils.faults import FaultPoint
from ..utils.log import logger
from .metrics import REGISTRY, MetricsRegistry
from .tenancy.adapters import UnknownAdapterError
from .tenancy.metering import UsageMeter
from .tenancy.quotas import DEFAULT_TENANT

__all__ = ["EngineLoop", "RequestHandle", "ServingMetrics", "SupervisorPolicy",
           "ATTRIBUTION_PHASES", "request_attribution", "canary_digest",
           "CANARY_PROMPT_IDS"]

#: the per-request latency-attribution phase vocabulary. Non-overlapping by
#: construction: queue + admission_gate span arrival -> first admission, the
#: admission -> first-token window splits into promote_wait (waiting on a
#: host-tier KV promotion copy) + prefill remainder, and the decode window
#: (first token -> finish) splits into chunk_stall + migration_wait + decode
#: remainder — so the phases always sum to e2e exactly when the timeline is
#: complete. The router adds an eighth phase, ``hedge_race``, to the same
#: histogram family for its first-token races.
ATTRIBUTION_PHASES = ("queue", "admission_gate", "promote_wait", "prefill",
                      "chunk_stall", "migration_wait", "decode")


def request_attribution(req) -> Optional[Dict[str, float]]:
    """Decompose one finished request's e2e latency into the attribution
    phases (seconds). Works on engine ``Request``s and ``_FailedRequest``
    shims alike (missing bookkeeping degrades to coarser phases, never an
    error); returns None when the request has no measurable timeline."""
    arrival = getattr(req, "arrival_t", None)
    finish = getattr(req, "finish_t", None)
    if arrival is None or finish is None:
        return None
    sched = getattr(req, "sched_t", None)
    first = getattr(req, "first_token_t", None)
    gated = getattr(req, "gated_t", None)
    out = {p: 0.0 for p in ATTRIBUTION_PHASES}
    end_queue = sched if sched is not None else finish
    if sched is not None and gated is not None and arrival <= gated <= sched:
        # the engine marked the moment the request hit an admission gate at
        # the head of the queue: waiting *behind* others vs waiting *on a
        # gate* are different operator actions (scale out vs retune gates)
        out["queue"] = gated - arrival
        out["admission_gate"] = sched - gated
    else:
        out["queue"] = max(end_queue - arrival, 0.0)
    if sched is not None:
        end_prefill = first if first is not None else finish
        prefill_raw = max(end_prefill - sched, 0.0)
        promote = max(getattr(req, "promote_wait_s", 0.0), 0.0)
        open_promote = getattr(req, "promote_start_t", None)
        if open_promote is not None:
            # finished (abort/quarantine) with the promotion copy still in
            # flight: the open episode ends at the prefill window's end
            promote += max(end_prefill - open_promote, 0.0)
        promote = min(promote, prefill_raw)
        out["promote_wait"] = promote
        out["prefill"] = prefill_raw - promote
    if first is not None:
        decode_raw = max(finish - first, 0.0)
        stall = min(max(getattr(req, "chunk_stall_s", 0.0), 0.0), decode_raw)
        mig = max(getattr(req, "migration_wait_s", 0.0), 0.0)
        open_mig = getattr(req, "migrate_start_t", None)
        if open_mig is not None:
            # the request finished (abort/quarantine) with a migration still
            # in flight: the open episode ends at finish
            mig += max(finish - open_mig, 0.0)
        mig = min(mig, decode_raw - stall)
        out["chunk_stall"] = stall
        out["migration_wait"] = mig
        out["decode"] = decode_raw - stall - mig
    return out

_END = object()  # token-queue sentinel: stream closed

_F_REBUILD = FaultPoint("engine.rebuild")
_F_SLOT_REBUILD = FaultPoint("engine.slot_rebuild")
_F_WEIGHT_SWAP = FaultPoint("engine.weight_swap")

#: the fixed greedy canary probe: low token ids exist in every vocab the stack
#: serves, and greedy decoding makes the output a pure function of the weights
#: — the same prompt on two replicas with the same checkpoint MUST digest
#: identically (the rollout's cross-replica verification contract)
CANARY_PROMPT_IDS = (1, 2, 3, 4, 5, 6, 7, 8)


def canary_digest(token_ids) -> str:
    """Stable digest of a greedy canary generation (order-sensitive, dtype-
    insensitive): what ships in a rollout request and what a reference replica
    records. Pure stdlib so tools/rollout.py can import-free reimplement it."""
    return hashlib.sha256(
        ",".join(str(int(t)) for t in token_ids).encode()).hexdigest()


class _CanaryMismatch(RuntimeError):
    """The post-swap canary generation digested differently than expected."""


class _WeightSwap:
    """One in-flight weight-swap command (HTTP thread <-> loop thread handoff).

    The HTTP handler does all validation and checkpoint loading BEFORE
    constructing this (nothing engine-side has mutated if loading fails); the
    loop thread owns quiesce, ``sync_params``, the cache-epoch bump, the
    canary and rollback. ``mode``:

    - ``finish_old``: in-flight requests finish under the old weights; the
      swap waits for the engine to drain (new submissions are held, not
      rejected — the drain is bounded by the caller's timeout).
    - ``pause_resume``: in-flight requests are stashed immediately (the
      supervisor's recompute-requeue trick) and resume under the NEW weights;
      their continuations are explicitly NOT token-identical to what the old
      weights would have produced (``token_identity: false`` in the result).
    """

    def __init__(self, new_params, version: str, mode: str = "finish_old",
                 canary_prompt_ids=None, canary_sampling=None,
                 canary_digest: Optional[str] = None):
        self.new_params = new_params
        self.version = version
        self.mode = mode
        self.canary_prompt_ids = canary_prompt_ids
        self.canary_sampling = canary_sampling
        self.canary_digest = canary_digest
        self.result: Optional[Dict] = None
        self.error: Optional[BaseException] = None
        self._done = threading.Event()

    def finish(self, result: Dict):
        self.result = result
        self._done.set()

    def fail(self, error: BaseException):
        self.error = error
        self._done.set()

    def wait(self, timeout: Optional[float]) -> Dict:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"weight swap to {self.version!r} not finished within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.result


@dataclasses.dataclass
class SupervisorPolicy:
    """Governs the DEGRADED transition after an engine-step exception.

    ``max_retries`` bounds how many engine rebuilds a single request may ride
    through before it is fast-cleared with ``finish_reason="engine_error"``
    (per-request override via ``submit(..., max_retries=)``). The rebuild
    backoff is exponential in the consecutive-failure count, capped at
    ``backoff_max_s``; a healthy stretch of ``failure_reset_s`` resets the
    count. ``max_rebuild_attempts=None`` keeps trying forever — the circuit
    breaker (503) is the pressure valve, not loop death.

    ``max_slot_quarantines`` bounds *partial* recovery: a step failure the
    engine attributed to ONE request (the exception carries a ``req_id``)
    quarantines only that slot — its KV blocks are released, its handle
    resolves ``engine_error``, and the loop resumes without degrading — up to
    this many consecutive quarantines inside a ``failure_reset_s`` window.
    Past the bound (or when attribution is absent) the full degrade/rebuild
    path runs: repeated "single bad request" failures in a tight window
    usually mean the engine itself is poisoned."""

    max_retries: int = 2
    backoff_base_s: float = 0.25
    backoff_max_s: float = 10.0
    failure_reset_s: float = 60.0
    max_rebuild_attempts: Optional[int] = None
    max_slot_quarantines: int = 3


class _FailedRequest:
    """Finished-request shim for handles resolved without a live engine
    request (engine died and the retry budget is spent). Carries exactly the
    fields the metrics plane, the trace emitter, and the HTTP layer read."""

    def __init__(self, req_id, prompt_ids, output_ids, trace,
                 arrival_t, finish_reason="engine_error",
                 tenant: str = DEFAULT_TENANT,
                 adapter_id: Optional[str] = None):
        self.req_id = req_id if req_id is not None else -1
        self.prompt_ids = list(prompt_ids)
        self.output_ids = list(output_ids)
        self.trace = trace
        self.tenant = tenant
        self.adapter_id = adapter_id
        self.aborted = False
        self.done = True
        self.finish_reason = finish_reason
        self.arrival_t = arrival_t
        self.sched_t = None
        self.first_token_t = None
        self.finish_t = time.time()
        self.queue_wait = None
        self.ttft = None
        self.decode_time = None


class RequestHandle:
    """Client-side view of one in-flight request (future + token stream)."""

    def __init__(self, prompt_len: int, deadline_t: Optional[float] = None,
                 trace: Optional[str] = None, max_retries: Optional[int] = None,
                 priority: str = "interactive", tenant: str = DEFAULT_TENANT,
                 adapter_id: Optional[str] = None):
        self.req_id: Optional[int] = None  # assigned on the loop thread
        self.trace = trace  # span-tracer trace id linking this request's phases
        self.prompt_len = prompt_len
        self.priority = priority  # serving priority class (brownout shed order)
        self.tenant = tenant  # isolation/accounting key (requests_total label)
        self.adapter_id = adapter_id  # LoRA adapter this request decodes with
        self.depth_at_submit = 0  # engine backlog when submitted (queue-wait norm)
        self.deadline_t = deadline_t
        self.submitted_t = time.time()
        self.timed_out = False
        self.max_retries = max_retries  # None = supervisor policy default
        self.retries = 0  # engine rebuilds this request rode through
        self._token_q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._request = None  # engine Request once finished
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._cb_lock = threading.Lock()
        self._callbacks: List = []  # guarded-by: _cb_lock
        # supervisor state: everything needed to resubmit after a rebuild
        self._streamed: List[int] = []  # every token delivered to the client
        self._stream_closed = False  # a done=True token was delivered (EOS/length)
        self._first_token_t: Optional[float] = None  # true TTFT anchor across rebuilds
        self._retry_prefix: List[int] = []  # tokens emitted before the last rebuild
        self._prompt_ids: Optional[List[int]] = None
        self._sampling = None
        # prompt tokens the dying engine had already prefilled when this
        # handle was stashed (goodput: a zero-streamed requeue's re-prefill
        # of those positions is rework, not useful — captured at triage)
        self._prefilled_hint = 0

    # ------------------------------------------------------------- futures
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the request finishes; returns the engine ``Request``."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.req_id} not finished within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._request

    @property
    def output_ids(self) -> List[int]:
        req = self.result()
        return list(req.output_ids)

    @property
    def finish_reason(self) -> Optional[str]:
        return self._request.finish_reason if self._request is not None else None

    # ------------------------------------------------------------- streaming
    def tokens(self, timeout: Optional[float] = None):
        """Yield token ids in generation order until the stream closes.

        ``timeout`` bounds the wait for EACH token (None = wait forever)."""
        while True:
            item = self._token_q.get(timeout=timeout)
            if item is _END:
                return
            tok, done = item
            yield tok
            if done:
                # drain the sentinel the resolver pushes after the last token
                try:
                    self._token_q.get_nowait()
                except queue.Empty:
                    pass
                return

    # ------------------------------------------------------------- loop-side
    def _on_token(self, tok: int, done: bool):
        if self._first_token_t is None:
            self._first_token_t = time.time()
        self._streamed.append(tok)
        if done:
            self._stream_closed = True
        self._token_q.put((tok, done))

    def add_done_callback(self, fn):
        """Run ``fn(handle)`` when the request resolves (immediately if done)."""
        with self._cb_lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _resolve(self, request, error: Optional[BaseException] = None):
        with self._cb_lock:
            if self._done.is_set():
                return
            self._request = request
            self._error = error
            self._token_q.put(_END)
            self._done.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn(self)
            except Exception as e:  # a bad callback must not kill the loop
                logger.warning(f"request done-callback failed: {e!r}")


class ServingMetrics:
    """Registers the serving metric catalog against one engine.

    Engine-state gauges are pull-mode (sampled at scrape); request-lifecycle
    series are pushed by the loop. Names are stable API — the README catalog
    and ``tools/bench_serve.py`` consume them."""

    def __init__(self, engine, registry: Optional[MetricsRegistry] = None):
        self.registry = r = registry or REGISTRY
        self.requests = r.counter(
            "paddlenlp_serving_requests_total",
            "Finished requests by terminal state, serving priority class, "
            "and tenant",
            labelnames=("status", "priority", "tenant"))
        self.tokens = r.counter(
            "paddlenlp_serving_tokens_generated_total", "Generated tokens (all requests)")
        self.preemptions = r.counter(
            "paddlenlp_serving_preemptions_total", "KV-exhaustion preemptions (recompute requeues)")
        self.engine_restarts = r.counter(
            "paddlenlp_serving_engine_restarts_total",
            "Engine rebuilds after a step exception (supervisor recoveries)")
        self.request_retries = r.counter(
            "paddlenlp_serving_request_retries_total",
            "In-flight requests requeued across an engine rebuild")
        self.slot_quarantines = r.counter(
            "paddlenlp_serving_slot_quarantines_total",
            "Poisoned requests quarantined by slot-level partial recovery "
            "(KV released, handle failed, engine kept running)")
        self.shed = r.counter(
            "paddlenlp_serving_requests_shed_total",
            "Submissions rejected on arrival by overload controls, by reason "
            "(shed = brownout priority shed; deadline = queue-wait estimate "
            "already blew the request's deadline_ms; tenant_quota = the "
            "tenant's max_inflight admission quota was full), priority class, "
            "and tenant — the per-class view of the brownout ladder's shed "
            "order and the per-tenant view of isolation pushback",
            labelnames=("reason", "priority", "tenant"))
        self.brownout_level = r.gauge(
            "paddlenlp_serving_brownout_level",
            "Current overload-brownout ladder level (0 normal, 1 shed "
            "best-effort, 2 conserve, 3 clamp max_tokens)")
        self.latency_attribution = r.histogram(
            "paddlenlp_serving_latency_attribution_seconds",
            "Per-request e2e latency decomposed by phase (queue/"
            "admission_gate/promote_wait/prefill/chunk_stall/migration_wait/"
            "decode on replicas; hedge_race on the router) — phases sum to e2e",
            labelnames=("phase",))
        self.ttft = r.histogram(
            "paddlenlp_serving_ttft_seconds", "Time from arrival to first token")
        self.queue_wait = r.histogram(
            "paddlenlp_serving_queue_wait_seconds", "Time from arrival to slot admission")
        self.inter_token = r.histogram(
            "paddlenlp_serving_inter_token_seconds", "Latency between consecutive tokens")
        self.e2e = r.histogram(
            "paddlenlp_serving_e2e_seconds", "Time from arrival to completion")
        self.queue_depth = r.gauge(
            "paddlenlp_serving_queue_depth", "Requests waiting for a slot")
        self.running = r.gauge(
            "paddlenlp_serving_running_slots", "Requests actively decoding")
        self.occupancy = r.gauge(
            "paddlenlp_serving_slot_occupancy", "running / max_batch_size")
        self.kv_free = r.gauge(
            "paddlenlp_serving_kv_free_blocks", "Free KV-cache blocks")
        self.kv_util = r.gauge(
            "paddlenlp_serving_kv_utilization", "1 - free/total KV blocks")
        self.spec_accept = r.gauge(
            "paddlenlp_serving_spec_acceptance_rate", "Accepted/drafted speculative tokens")
        self.prefix_hits = r.counter(
            "paddlenlp_serving_prefix_cache_hits_total",
            "Admissions that reused >=1 cached KV block from the prefix cache")
        self.prefix_cached_tokens = r.counter(
            "paddlenlp_serving_prefix_cache_cached_tokens_total",
            "Prompt tokens whose prefill was skipped via cached KV blocks")
        self.prefix_evictions = r.counter(
            "paddlenlp_serving_prefix_cache_evictions_total",
            "Cached KV blocks evicted under allocation pressure")
        self.kv_cached = r.gauge(
            "paddlenlp_serving_kv_cached_blocks",
            "KV blocks registered in the prefix-cache index")
        self.prefill_chunks = r.counter(
            "paddlenlp_serving_prefill_chunks_total",
            "Prompt chunks processed by ragged mixed prefill/decode steps")
        self.prefill_chunk_tokens = r.histogram(
            "paddlenlp_serving_prefill_chunk_tokens",
            "Prompt tokens fed per prefill chunk",
            buckets=(16, 32, 64, 128, 256, 512, 1024, 2048, 4096))
        self.decode_stall = r.histogram(
            "paddlenlp_serving_decode_stall_seconds",
            "Per-step decode gap attributable to concurrent prefill-chunk work "
            "(duration of mixed steps that carried both chunks and decodes)")
        self.stage_kv_util = r.gauge(
            "paddlenlp_serving_stage_kv_utilization",
            "Per-stage share of KV blocks held (disaggregated backends: TTFT "
            "pressure lives on the prefill stage, inter-token on decode)",
            labelnames=("stage",))
        self.stage_queue_depth = r.gauge(
            "paddlenlp_serving_stage_queue_depth",
            "Per-stage queue depth of the disaggregated backend (prefill: "
            "waiting + mid-prefill requests; decode: migrated-pending)",
            labelnames=("stage",))
        self.kv_migrations = r.counter(
            "paddlenlp_serving_kv_migrations_total",
            "Sequences whose KV blocks migrated prefill->decode (disaggregated backend)")
        self.kv_migrated_blocks = r.counter(
            "paddlenlp_serving_kv_migrated_blocks_total",
            "KV blocks copied prefill->decode across stage pools")
        self.kv_migrated_bytes = r.counter(
            "paddlenlp_serving_kv_migrated_bytes_total",
            "Bytes of KV copied prefill->decode (the migration-bandwidth series)")
        self.kv_migration_inflight = r.gauge(
            "paddlenlp_serving_kv_migration_inflight",
            "Prefill->decode block migrations currently in flight")
        # hierarchical KV: the host spill tier under the prefix cache
        self.kv_host_blocks = r.gauge(
            "paddlenlp_serving_kv_host_blocks",
            "Prefix-cache KV blocks currently resident in the host spill tier")
        self.kv_host_spills = r.counter(
            "paddlenlp_serving_kv_host_spills_total",
            "LRU-evicted prefix-cache blocks demoted device->host (batched D2H)")
        self.kv_host_promotes = r.counter(
            "paddlenlp_serving_kv_host_promotes_total",
            "Host-tier blocks promoted host->device ahead of a prefix-matched "
            "request's prefill")
        self.kv_host_promote_bytes = r.counter(
            "paddlenlp_serving_kv_host_promote_bytes_total",
            "Bytes of KV copied host->device by promotions (the promotion-"
            "bandwidth series)")
        self.mesh_devices = r.gauge(
            "paddlenlp_serving_mesh_devices",
            "Devices this replica's engine backend spans (1 = single-chip)")
        self.mesh_axis_size = r.gauge(
            "paddlenlp_serving_mesh_axis_size",
            "Device-mesh axis degree of the sharded serving backend, per named axis",
            labelnames=("axis",))
        # ---- goodput ledger (observability/goodput.py): per-step device-
        # efficiency accounting with the exact conservation invariant
        # fed == useful + padding + spec_rejected + rework
        self.fed_tokens = r.counter(
            "paddlenlp_serving_fed_tokens_total",
            "Token positions the device step programs processed (padded "
            "launch geometry, the goodput denominator)")
        self.useful_tokens = r.counter(
            "paddlenlp_serving_useful_tokens_total",
            "Fed positions that built new KV or emitted a kept token "
            "(the goodput numerator)")
        self.wasted_tokens = r.counter(
            "paddlenlp_serving_wasted_tokens_total",
            "Non-useful fed positions by waste kind (padding = bucket pads + "
            "dead rows + idle decode slots; spec_rejected = drafted-rejected "
            "speculative positions; rework = re-fed positions after "
            "preemption/requeue, COW tails, migration re-seeds)",
            labelnames=("kind",))
        self.goodput_ratio = r.gauge(
            "paddlenlp_serving_goodput_ratio",
            "Lifetime useful/fed token ratio of the engine's device steps")
        self.serving_mfu = r.gauge(
            "paddlenlp_serving_mfu",
            "Estimated model-FLOPs utilization of the serving engine "
            "(useful tokens * flops-per-token / wall / device peak; NaN off-TPU)")
        self.step_gap = r.histogram(
            "paddlenlp_serving_step_gap_seconds",
            "Host gap between consecutive busy engine steps (loop overhead: "
            "command drain, deadlines, metrics) — the host-bound half of "
            "step-time anatomy",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0))
        self.compiles = r.counter(
            "paddlenlp_serving_compiles_total",
            "XLA backend compilations attributed to a serving step program "
            "(jax.monitoring, per program that triggered the trace)",
            labelnames=("program",))
        self.compile_seconds = r.counter(
            "paddlenlp_serving_compile_seconds_total",
            "Seconds spent in XLA compilation attributed per serving step program",
            labelnames=("program",))
        self.shape_buckets = r.gauge(
            "paddlenlp_serving_jit_shape_buckets",
            "Distinct jit launch geometries seen by the engine (live "
            "shape-bucket cardinality — growth without bound is a retrace storm)")
        self.kv_fragmentation = r.gauge(
            "paddlenlp_serving_kv_fragmentation",
            "Internal fragmentation of allocated KV blocks "
            "(1 - held tokens / (held blocks * block_size))")
        # spec-decode acceptance as first-class counters (the rate gauge's
        # inputs, and the ledger's spec_rejected bucket = drafted - accepted)
        self.spec_drafted = r.counter(
            "paddlenlp_serving_spec_drafted_tokens_total",
            "Speculative tokens proposed (n-gram or draft-model) for verification")
        self.spec_accepted = r.counter(
            "paddlenlp_serving_spec_accepted_tokens_total",
            "Speculative tokens accepted by the verify forward")
        # billing-grade usage: token counters labeled by who pays for them
        # (UsageMeter increments these once per finished request)
        self.usage_tokens = r.counter(
            "paddlenlp_serving_usage_tokens_total",
            "Metered usage tokens booked per finished request, by tenant, "
            "adapter (\"base\" = no LoRA), and kind "
            "(prompt | cached = prefix-cache credit | completion)",
            labelnames=("tenant", "adapter", "kind"))
        self.usage_records = r.counter(
            "paddlenlp_serving_usage_records_total",
            "Usage records booked (exactly one per finished request id)",
            labelnames=("tenant",))
        # info-style gauge (value is always 1 on the live series): the base-
        # weight version this replica serves — the router's federated scrape
        # makes a mixed-version fleet visible as multiple {version} series
        self.weights_info = r.gauge(
            "paddlenlp_serving_weights_info",
            "Base-weight version this replica currently serves (1 = active; "
            "a completed swap removes the superseded version's series)",
            labelnames=("version",))
        self.rebind(engine)

    def rebind(self, engine):
        """Point the pull-mode gauges at ``engine`` — the supervisor swaps the
        engine on rebuild, and gauges bound to the dead instance would scrape
        a ghost."""
        mgr = engine.mgr
        self.queue_depth.set_function(lambda: len(engine.waiting))
        self.running.set_function(
            lambda: sum(1 for s in engine.slots if s is not None))
        self.occupancy.set_function(
            lambda: sum(1 for s in engine.slots if s is not None) / max(engine.max_batch_size, 1))
        self.kv_free.set_function(lambda: mgr.num_free)
        self.kv_util.set_function(
            lambda: 1.0 - mgr.num_free / max(mgr.total_usable_blocks, 1))
        self.spec_accept.set_function(
            lambda: engine.spec_stats["accepted"] / max(engine.spec_stats["drafted"], 1))
        self.kv_cached.set_function(lambda: getattr(mgr, "num_cached_blocks", 0))
        # goodput pull gauges ride the engine's ledger (stand-in engines
        # without one read as idle: ratio 1.0, NaN MFU, zero cardinality)
        ledger = getattr(engine, "ledger", None)
        self.goodput_ratio.set_function(
            lambda: ledger.ratio() if ledger is not None else 1.0)
        self.serving_mfu.set_function(
            lambda: ledger.mfu() if ledger is not None else float("nan"))
        self.shape_buckets.set_function(
            lambda: len(ledger.shape_buckets) if ledger is not None else 0)
        self.kv_fragmentation.set_function(
            lambda: engine.kv_fragmentation()
            if hasattr(engine, "kv_fragmentation") else 0.0)
        # mesh placement is static per engine: stamped once per (re)bind, not
        # pulled per scrape — a rebuilt engine may come up on a new layout, so
        # axes the new engine doesn't report drop back to degree 1 (a label
        # series, once exposed, must not keep reporting the dead layout)
        backend = getattr(engine, "backend", None)
        desc = backend.describe() if backend is not None else {}
        self.mesh_devices.set(desc.get("devices", 1))
        mesh_axes = desc.get("mesh") or {}
        for axis in getattr(self, "_mesh_axes_stamped", set()) - set(mesh_axes):
            self.mesh_axis_size.set(1, axis=axis)
        for axis, size in mesh_axes.items():
            self.mesh_axis_size.set(size, axis=axis)
        self._mesh_axes_stamped = set(mesh_axes)
        # prefix-cache counters are deltas off the engine's monotone totals;
        # a rebuilt engine restarts its totals at 0, so rebaseline here
        self._pc_last = {
            "hits": getattr(mgr, "cache_hits", 0),
            "cached_tokens": getattr(mgr, "cached_tokens_total", 0),
            "evictions": getattr(mgr, "evictions", 0),
        }
        # host-tier residency is a pull gauge off the tier itself; the spill/
        # promote counters are deltas off its monotone stats, rebaselined here
        # (engine reset keeps the tier instance, so totals usually carry over)
        tier = getattr(engine, "_host_tier", None)
        self.kv_host_blocks.set_function(
            lambda: tier.num_blocks if tier is not None else 0)
        self._host_last = dict(tier.stats) if tier is not None else \
            {"spills": 0, "promoted_blocks": 0, "promote_bytes": 0}
        self._engine = engine
        self._chunk_last = dict(getattr(engine, "chunk_stats", {"chunks": 0}))
        # migration counters are deltas off the backend's monotone totals; a
        # rebuilt engine's backend restarts at 0, so rebaseline like the rest
        self._mig_last = dict(getattr(backend, "migration_stats", None)
                              or {"migrations": 0, "blocks": 0, "bytes": 0})
        # chunked-prefill histograms consume the engine's (seq, value) event
        # rings; start past whatever the (possibly reset-in-place) engine
        # already recorded so a rebuild never re-observes old events
        self._chunk_seq_seen = max(
            [s for s, _ in getattr(engine, "recent_chunk_sizes", ())]
            + [s for s, _ in getattr(engine, "recent_decode_stalls", ())]
            + [0])
        # goodput counters are deltas off the ledger's monotone totals; same
        # rebaseline-on-rebind contract as the prefix-cache/migration deltas
        self._gp_last = dict(ledger.totals) if ledger is not None else {}
        self._compile_last = dict(ledger.compiles) if ledger is not None else {}
        self._compile_s_last = dict(ledger.compile_seconds) if ledger is not None else {}
        self._spec_last = dict(getattr(engine, "spec_stats", None)
                               or {"drafted": 0, "accepted": 0})
        self._step_time_seen = max(
            [s for s, *_ in getattr(engine, "recent_step_times", ())] + [0])

    def on_finished(self, req):
        status = req.finish_reason or ("abort" if req.aborted else "unknown")
        self.requests.inc(status=status,
                          priority=getattr(req, "priority", "interactive"),
                          tenant=getattr(req, "tenant", DEFAULT_TENANT))
        self.tokens.inc(len(req.output_ids))
        if req.ttft is not None:
            self.ttft.observe(req.ttft)
        if req.queue_wait is not None:
            self.queue_wait.observe(req.queue_wait)
        if req.finish_t is not None:
            self.e2e.observe(req.finish_t - req.arrival_t)

    def on_step(self, stats: Dict, preempt_delta: int):
        if preempt_delta > 0:
            self.preemptions.inc(preempt_delta)
        pc = stats.get("prefix_cache")
        if pc:
            for key, counter in (("hits", self.prefix_hits),
                                 ("cached_tokens", self.prefix_cached_tokens),
                                 ("evictions", self.prefix_evictions)):
                delta = pc.get(key, 0) - self._pc_last[key]
                if delta > 0:
                    counter.inc(delta)
                self._pc_last[key] = pc.get(key, 0)
            host = pc.get("host")
            if host and host.get("enabled"):
                for key, counter in (("spills", self.kv_host_spills),
                                     ("promoted_blocks", self.kv_host_promotes),
                                     ("promote_bytes", self.kv_host_promote_bytes)):
                    delta = host.get(key, 0) - self._host_last.get(key, 0)
                    if delta > 0:
                        counter.inc(delta)
                    self._host_last[key] = host.get(key, 0)
        cp = stats.get("chunked_prefill")
        if cp:
            delta = cp.get("chunks", 0) - self._chunk_last.get("chunks", 0)
            if delta > 0:
                self.prefill_chunks.inc(delta)
            self._chunk_last["chunks"] = cp.get("chunks", 0)
            # histogram observations come from the engine's bounded event rings
            # (on_step runs on the loop thread, the only writer — no race)
            seen = self._chunk_seq_seen
            for seq, n in getattr(self._engine, "recent_chunk_sizes", ()):
                if seq > seen:
                    self.prefill_chunk_tokens.observe(n)
                    self._chunk_seq_seen = max(self._chunk_seq_seen, seq)
            for seq, dur in getattr(self._engine, "recent_decode_stalls", ()):
                if seq > seen:
                    self.decode_stall.observe(dur)
                    self._chunk_seq_seen = max(self._chunk_seq_seen, seq)
        gp = stats.get("goodput")
        if gp:
            totals = gp.get("totals", {})
            delta_fed = totals.get("fed", 0) - self._gp_last.get("fed", 0)
            if delta_fed > 0:
                self.fed_tokens.inc(delta_fed)
            delta_useful = totals.get("useful", 0) - self._gp_last.get("useful", 0)
            if delta_useful > 0:
                self.useful_tokens.inc(delta_useful)
            for kind in WASTE_KINDS:
                delta = totals.get(kind, 0) - self._gp_last.get(kind, 0)
                if delta > 0:
                    self.wasted_tokens.inc(delta, kind=kind)
            self._gp_last = dict(totals)
            for program, n in gp.get("compiles", {}).items():
                delta = n - self._compile_last.get(program, 0)
                if delta > 0:
                    self.compiles.inc(delta, program=program)
                self._compile_last[program] = n
            for program, secs in gp.get("compile_seconds", {}).items():
                delta = secs - self._compile_s_last.get(program, 0.0)
                if delta > 0:
                    self.compile_seconds.inc(delta, program=program)
                self._compile_s_last[program] = secs
            # step-gap observations from the engine's bounded event ring
            # (loop thread, the only writer — the chunk-ring contract); gaps
            # marked unmeasured (< 0: first/post-idle steps) are skipped
            seen = self._step_time_seen
            for seq, gap_s, _dev, _host in getattr(self._engine,
                                                   "recent_step_times", ()):
                if seq > seen:
                    if gap_s >= 0:
                        self.step_gap.observe(gap_s)
                    self._step_time_seen = max(self._step_time_seen, seq)
        sp = stats.get("spec_stats")
        if sp:
            for key, counter in (("drafted", self.spec_drafted),
                                 ("accepted", self.spec_accepted)):
                delta = sp.get(key, 0) - self._spec_last.get(key, 0)
                if delta > 0:
                    counter.inc(delta)
                self._spec_last[key] = sp.get(key, 0)
        dg = stats.get("disagg")
        if dg:
            for stage in ("prefill", "decode"):
                st = dg.get(f"{stage}_stage", {})
                self.stage_kv_util.set(st.get("kv_utilization", 0.0), stage=stage)
                self.stage_queue_depth.set(st.get("queue_depth", 0), stage=stage)
            self.kv_migration_inflight.set(dg.get("migrations_inflight", 0))
            mig = dg.get("migrations", {})
            for key, counter in (("migrations", self.kv_migrations),
                                 ("blocks", self.kv_migrated_blocks),
                                 ("bytes", self.kv_migrated_bytes)):
                delta = mig.get(key, 0) - self._mig_last.get(key, 0)
                if delta > 0:
                    counter.inc(delta)
                self._mig_last[key] = mig.get(key, 0)


class EngineLoop:
    """Owns the engine on one thread; everything else talks through queues."""

    def __init__(self, engine, metrics: Optional[ServingMetrics] = None,
                 registry: Optional[MetricsRegistry] = None, idle_wait_s: float = 0.05,
                 engine_factory: Optional[Callable[[], object]] = None,
                 policy: Optional[SupervisorPolicy] = None,
                 postmortem: Optional[PostmortemDumper] = None,
                 usage: Optional[UsageMeter] = None):
        self.engine = engine
        self.metrics = metrics or ServingMetrics(engine, registry)
        self.idle_wait_s = idle_wait_s
        self.engine_factory = engine_factory
        self.policy = policy or SupervisorPolicy()
        # billing-grade usage: one record per finished request, booked at
        # resolution time (every finish path funnels through _trace_finished
        # except shutdown cleanup, which books directly). PDNLP_TPU_USAGE_DIR
        # arms the durable JSONL ledger.
        self.usage = usage if usage is not None \
            else UsageMeter.from_env(metrics=self.metrics)
        # incident black box: supervisor degrades and slot quarantines
        # auto-dump a bundle (events + spans + health + metrics + config) to
        # PDNLP_TPU_POSTMORTEM_DIR; POST /debug/postmortem forces one
        self.postmortem = postmortem or PostmortemDumper(
            registry=self.metrics.registry, health_fn=self._postmortem_health,
            config_fn=self._postmortem_config)
        self._cmds: "queue.Queue" = queue.Queue()
        self._wake = threading.Event()
        self._handles: Dict[int, RequestHandle] = {}
        self._requeue: List[RequestHandle] = []  # stashed across a rebuild
        self._last_token_t: Dict[int, float] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._started = False
        self._state = "stopped"  # stopped | running | degraded
        self._phase = "init"  # last loop phase (join-failure diagnostics)
        self._consecutive_failures = 0
        self._last_failure_t = 0.0
        # slot-level quarantine accounting (loop-thread only, like the above):
        # the streak escalates to a full rebuild at max_slot_quarantines;
        # slot_quarantines is the monotone total /health reports
        self._quarantine_streak = 0
        self._last_quarantine_t = 0.0
        self.slot_quarantines = 0
        self._retry_after_hint = self.policy.backoff_base_s
        self._trace_seq = itertools.count()
        # live queue-wait estimator: per-backlog-slot queue+gate seconds of
        # recently finished requests (PR-13 attribution), appended on the loop
        # thread, read (sorted) by HTTP threads computing Retry-After hints —
        # iterating a deque concurrently with an append raises RuntimeError,
        # so BOTH sides take the lock (appends are per-finished-request, reads
        # per-rejection: cold path either way). Scaled by the CURRENT backlog
        # at estimate time, the p50 becomes the hint that tracks queue depth.
        self._qw_lock = threading.Lock()
        self._queue_wait_samples: deque = deque(maxlen=64)  # guarded-by: _qw_lock
        # samples only refresh when admitted requests FINISH — if overload
        # leaves a high estimate and then everything is shed/deadline-rejected
        # on arrival, nothing ever refreshes it and the rejection latches on
        # an idle replica. Stale samples (no finish for this long) are
        # dropped, falling back to the cold-start default.
        self.queue_wait_sample_ttl_s = 60.0
        self._qw_fresh_t = 0.0  # guarded-by: _qw_lock — last sample append
        self._default_queue_wait_s = 0.05
        # /debug/requests tail: finished-request summaries (appended only on
        # the loop thread; deque ops are atomic so HTTP readers need no lock)
        self.recent_finished: deque = deque(maxlen=64)
        # live weight-swap state: the version string this replica serves
        # (reported on /health; the rollout orchestrator's convergence check),
        # the swap currently quiescing, and submissions held while it does.
        # All loop-thread-confined except weights_version, which HTTP threads
        # read as a single-slot value (momentarily stale reads are fine).
        self.weights_version = "v0"
        self._pending_swap: Optional[_WeightSwap] = None
        self._held_cmds: List[tuple] = []
        self.metrics.weights_info.set(1.0, version=self.weights_version)

    # ------------------------------------------------------------- lifecycle
    def start(self):
        if self._started:
            return self
        self._started = True
        self._stop = False
        self._state = "running"
        self._thread = threading.Thread(target=self._run, name="engine-loop", daemon=True)
        self._thread.start()
        return self

    @property
    def running(self) -> bool:
        return self._started and not self._stop

    @property
    def state(self) -> str:
        """``running`` | ``degraded`` | ``stopped``."""
        return self._state

    @property
    def degraded(self) -> bool:
        return self._state == "degraded"

    def retry_after_hint(self) -> float:
        """Suggested client backoff (seconds) while degraded — the current
        rebuild backoff, so Retry-After tracks actual recovery cadence."""
        return max(self._retry_after_hint, 0.1)

    def stop(self, drain: bool = True, timeout: Optional[float] = None,
             join_timeout_s: float = 30.0) -> bool:
        """Stop the loop. ``drain=True`` finishes in-flight work first
        (bounded by ``timeout``); leftovers and ``drain=False`` abort.

        Returns True once the loop thread has actually exited. A thread that
        refuses to join within ``join_timeout_s`` (e.g. wedged inside a device
        call) is reported — with its last-known phase — and ``False`` is
        returned so the caller knows the engine may still be mutating."""
        if not self._started:
            return True
        if drain:
            deadline = None if timeout is None else time.time() + timeout
            while self.pending_count() > 0:
                if self.degraded:
                    # a degraded engine may never come back (factory failing
                    # forever) — draining would spin until the heat death of
                    # the process; abort the stashed work instead
                    logger.warning(
                        f"engine degraded during drain; aborting {self.pending_count()} requests")
                    break
                if deadline is not None and time.time() >= deadline:
                    logger.warning(f"engine loop drain timed out; aborting {self.pending_count()} requests")
                    break
                time.sleep(0.01)
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout_s)
            if self._thread.is_alive():
                logger.error(
                    f"engine loop thread failed to stop within {join_timeout_s}s "
                    f"(last phase: {self._phase!r}); thread left detached — "
                    "engine state must be treated as poisoned")
                return False
        self._started = False
        self._state = "stopped"
        try:
            # seal the open usage segment: sealed segments are what the
            # offline aggregator (tools/usage_report.py) merges
            self.usage.close()
        except Exception:  # noqa: BLE001
            logger.warning("usage ledger seal on stop failed", exc_info=True)
        return True

    def pending_count(self) -> int:
        return (len(self._handles) + len(self._requeue) + len(self._held_cmds)
                + self._cmds.qsize())

    # ------------------------------------------------------------- client api
    def submit(self, prompt_ids, sampling=None, deadline_s: Optional[float] = None,
               max_retries: Optional[int] = None,
               trace: Optional[str] = None,
               priority: str = "interactive",
               tenant: str = DEFAULT_TENANT,
               adapter_id: Optional[str] = None) -> RequestHandle:
        """Thread-safe request submission; returns immediately with a handle.

        ``max_retries`` overrides the supervisor policy's per-request requeue
        budget (0 = never requeue across an engine rebuild: fail fast with
        ``finish_reason="engine_error"``). ``trace`` adopts an inbound trace id
        (the router's ``rtr-N`` from the traceparent header) instead of minting
        a local ``req-N`` — the key to cross-tier trace stitching.
        ``priority`` orders the engine's waiting queue (interactive ahead of
        batch ahead of best_effort) and selects the brownout shed class.
        ``tenant`` keys per-tenant quotas and metric labels; ``adapter_id``
        selects the LoRA adapter (registry-resident or hot-loadable) the
        engine decodes this request with — None runs the shared base model."""
        if not self.running:
            raise RuntimeError("engine loop is not running")
        deadline_t = None if deadline_s is None else time.time() + deadline_s
        handle = RequestHandle(prompt_len=len(prompt_ids), deadline_t=deadline_t,
                               trace=trace if trace is not None else f"req-{next(self._trace_seq)}",
                               max_retries=max_retries, priority=priority,
                               tenant=tenant, adapter_id=adapter_id)
        handle._prompt_ids = [int(t) for t in prompt_ids]
        handle._sampling = sampling
        self._cmds.put(("submit", handle, prompt_ids, sampling))
        self._wake.set()
        return handle

    def cancel(self, handle: RequestHandle):
        """Request cancellation; resolves the handle once the loop aborts it."""
        handle._cancelled = True
        self._cmds.put(("abort", handle))
        self._wake.set()

    def request_weight_swap(self, new_params, version: str, *,
                            mode: str = "finish_old",
                            canary_prompt_ids=None, canary_sampling=None,
                            canary_digest: Optional[str] = None,
                            timeout_s: Optional[float] = 120.0) -> Dict:
        """Thread-safe live weight swap; blocks until the loop installed the
        new params (canary passed) or rolled back to the retained old ones.

        The caller (the /admin/weights handler) must have fully validated and
        loaded ``new_params`` already — by the time this is called the only
        remaining failure modes are the swap itself and the canary, both of
        which roll back. Returns the loop's result dict (``ok``,
        ``weights_version``, ``canary_digest``, ``resumed``,
        ``token_identity``, ``wall_s`` — plus ``reason``/``error`` on a
        rollback); raises TimeoutError when the quiesce outlives
        ``timeout_s`` (the swap stays queued and will still run)."""
        if not self.running:
            raise RuntimeError("engine loop is not running")
        if mode not in ("finish_old", "pause_resume"):
            raise ValueError(f"unknown swap mode {mode!r} "
                             "(want finish_old | pause_resume)")
        swap = _WeightSwap(new_params, version, mode=mode,
                           canary_prompt_ids=canary_prompt_ids,
                           canary_sampling=canary_sampling,
                           canary_digest=canary_digest)
        self._cmds.put(("weights", swap))
        self._wake.set()
        return swap.wait(timeout_s)

    # ------------------------------------------------------------- loop body
    def _run(self):
        try:
            while not self._stop:
                try:
                    self._run_iteration()
                except Exception as e:
                    # engine-step (or command-processing) failure: supervise —
                    # degrade, triage, rebuild, resume. Raises only when the
                    # rebuild budget is exhausted.
                    self._supervise(e)
        except BaseException as e:  # loop death must not strand waiters
            logger.error(f"engine loop crashed: {e!r}")
            self._resolve_all_with_error(e)
            raise
        finally:
            self._state = "stopped"
            self._shutdown_cleanup()

    def _run_iteration(self):
        self._phase = "drain_cmds"
        self._drain_cmds()
        self._phase = "deadlines"
        self._enforce_deadlines()
        if self._pending_swap is not None:
            swap = self._pending_swap
            # finish_old waits for the engine to run dry at a step boundary
            # (held submissions guarantee it eventually does; deadlines bound
            # wedged streams); pause_resume stashes and swaps immediately
            if swap.mode == "pause_resume" or (
                    not self._handles and not self._requeue):
                self._phase = "weight_swap"
                self._execute_swap(swap)
        if self.engine.has_work():
            self._phase = "step"
            stats_before = self.engine.num_preemptions
            for req in self.engine.step():
                self._finish(req)
            self.metrics.on_step(
                self.engine.stats(), self.engine.num_preemptions - stats_before)
        else:
            self._phase = "idle"
            self._wake.wait(timeout=self.idle_wait_s)
            self._wake.clear()

    # ------------------------------------------------------------- supervisor
    def _supervise(self, exc: Exception):
        """Recover from a step failure: slot-level quarantine when the engine
        attributed it to one poisoned request, otherwise the full DEGRADED
        transition (triage in-flight work, rebuild, requeue, resume)."""
        if self._try_quarantine(exc):
            return
        now = time.time()
        if now - self._last_failure_t > self.policy.failure_reset_s:
            self._consecutive_failures = 0
        self._consecutive_failures += 1
        self._last_failure_t = now
        self._state = "degraded"
        degraded_t0 = now
        logger.error(
            f"engine step failed (consecutive failure {self._consecutive_failures}): {exc!r}; "
            "entering DEGRADED state")
        RECORDER.record("supervisor.degraded", error=repr(exc)[:200],
                        consecutive=self._consecutive_failures,
                        inflight=len(self._handles))
        TRACER.instant("engine_failure", cat="engine_loop", error=repr(exc),
                       consecutive=self._consecutive_failures,
                       inflight=len(self._handles))
        n_failed = self._triage(exc)
        # black box: snapshot the incident AFTER triage so the bundle's
        # health/events already reflect the dispositions (rate-limited;
        # opt-in via PDNLP_TPU_POSTMORTEM_DIR)
        self.postmortem.dump("supervisor_degraded", detail={
            "error": repr(exc)[:500],
            "consecutive_failures": self._consecutive_failures,
            "failed": n_failed, "requeued": len(self._requeue)})

        attempt = 0
        while not self._stop:
            # exponent clamped: a persistent failure grows the counters without
            # bound, and 2**1000 would overflow float and kill the supervisor
            # that promises to retry forever
            backoff = min(
                self.policy.backoff_base_s
                * (2 ** min(self._consecutive_failures - 1 + attempt, 30)),
                self.policy.backoff_max_s)
            self._retry_after_hint = backoff
            self._phase = "degraded"
            self._wake.wait(timeout=backoff)
            self._wake.clear()
            if self._stop:
                return
            self._phase = "rebuild"
            try:
                _F_REBUILD.fire(attempt=attempt)
                engine = self.engine_factory() if self.engine_factory is not None \
                    else self._reset_engine()
            except Exception as rebuild_exc:
                attempt += 1
                logger.error(f"engine rebuild attempt {attempt} failed: {rebuild_exc!r}")
                if (self.policy.max_rebuild_attempts is not None
                        and attempt >= self.policy.max_rebuild_attempts):
                    for handle in self._requeue:
                        handle._resolve(None, error=rebuild_exc)
                    self._requeue = []
                    raise
                continue
            self.engine = engine
            self.metrics.rebind(engine)
            self.metrics.engine_restarts.inc()
            n_requeued = self._resubmit_stashed()
            self._state = "running"
            RECORDER.record("supervisor.recovered", attempts=attempt + 1,
                            requeued=n_requeued, failed=n_failed)
            dur = time.time() - degraded_t0
            TRACER.add_span("engine_degraded", degraded_t0, dur, cat="engine_loop",
                            wall=True, error=repr(exc), requeued=n_requeued,
                            failed=n_failed, rebuild_attempts=attempt + 1)
            logger.warning(
                f"engine rebuilt after {dur:.2f}s degraded "
                f"(requeued {n_requeued}, failed {n_failed}, attempts {attempt + 1})")
            return

    def _try_quarantine(self, exc: Exception) -> bool:
        """Slot-level partial recovery: when the engine attributed the step
        failure to ONE request (``exc.req_id``), release only that request's
        slot + KV blocks, resolve its handle, sweep up any requests the same
        step had already finished, and resume — the loop never leaves
        ``running``, unaffected streams never pause, and the scheduler's 503
        circuit breaker never trips. Returns True when fully handled; False
        escalates to the full degrade/rebuild path."""
        req_id = getattr(exc, "req_id", None)
        release = getattr(self.engine, "release_request", None)
        if req_id is None or release is None:
            return False
        t0 = time.time()
        if t0 - self._last_quarantine_t > self.policy.failure_reset_s:
            self._quarantine_streak = 0
        if self._quarantine_streak >= self.policy.max_slot_quarantines:
            logger.error(
                f"req {req_id}: poisoned, but {self._quarantine_streak} slots were "
                "already quarantined this window — escalating to a full rebuild")
            return False
        handle = self._handles.pop(req_id, None)
        if handle is None:
            return False
        self._phase = "slot_quarantine"
        try:
            _F_SLOT_REBUILD.fire(req_id=req_id)
            release(req_id)
            # the failed step may have committed device-side penalty-count
            # updates for tokens whose host emit never ran (they regenerate
            # from host state next step) — resync survivors' counts from
            # host truth so penalty-sampling neighbors don't double-count
            resync = getattr(self.engine, "resync_counts", None)
            if resync is not None:
                resync()
        except Exception as rebuild_exc:
            # the slot itself cannot be rebuilt: put the handle back so the
            # full path's triage owns its disposition
            self._handles[req_id] = handle
            logger.error(f"slot quarantine of req {req_id} failed: {rebuild_exc!r}; "
                         "escalating to full rebuild")
            return False
        self._quarantine_streak += 1
        self._last_quarantine_t = t0
        self.slot_quarantines += 1
        self.metrics.slot_quarantines.inc()
        self._last_token_t.pop(req_id, None)
        streamed = list(handle._streamed)
        reason = self._closed_stream_reason(handle, streamed) \
            or ("abort" if handle._cancelled else "engine_error")
        self._resolve_failed(handle, streamed, finish_reason=reason)
        # requests the failed step had already finished (done=True streamed,
        # engine-side state retired) lost only their resolution when step()
        # raised before returning them — resolve them as the completions
        # their clients already saw, exactly triage's closed-stream rule
        swept = 0
        for h in list(self._handles.values()):
            if h.done() or not h._stream_closed:
                continue
            release(h.req_id)  # no-op when the engine already retired it
            self._handles.pop(h.req_id, None)
            self._last_token_t.pop(h.req_id, None)
            s = list(h._streamed)
            self._resolve_failed(h, s, finish_reason=self._closed_stream_reason(h, s) or "stop")
            swept += 1
        RECORDER.record("supervisor.quarantine", req_id=req_id,
                        trace=handle.trace, streak=self._quarantine_streak,
                        swept=swept, error=repr(exc)[:200])
        TRACER.add_span("slot_quarantine", t0, time.time() - t0, cat="engine_loop",
                        wall=True, req_id=req_id, error=repr(exc),
                        streak=self._quarantine_streak, swept=swept)
        self.postmortem.dump("slot_quarantine", detail={
            "req_id": req_id, "trace": handle.trace,
            "error": repr(exc)[:500], "streak": self._quarantine_streak})
        logger.warning(
            f"req {req_id}: quarantined after per-request failure ({exc!r}); "
            f"slot rebuilt, engine kept running ({len(self._handles)} unaffected)")
        return True

    @staticmethod
    def _closed_stream_reason(handle: RequestHandle, streamed: List[int]) -> Optional[str]:
        """Terminal reason for a handle whose stream already delivered its
        done=True token (or full budget) — the crash ate only the finish
        bookkeeping. None when the stream is still open."""
        max_new = getattr(handle._sampling, "max_new_tokens", None)
        if max_new is not None and len(streamed) >= max_new:
            return "length"
        if handle._stream_closed:
            return "stop"
        return None

    def _triage(self, exc: Exception) -> int:
        """Split in-flight handles into the requeue stash and immediate
        ``engine_error`` resolutions, per the retry policy. Returns the number
        fast-cleared."""
        n_failed = 0
        for handle in list(self._handles.values()):
            if handle.done():
                continue
            limit = handle.max_retries if handle.max_retries is not None \
                else self.policy.max_retries
            streamed = list(handle._streamed)
            # a request whose stream already delivered its done=True token
            # (EOS or full budget) just needs its resolution — the crash ate
            # only the finish bookkeeping; requeueing it would generate PAST
            # the end of a completed sequence
            reason = self._closed_stream_reason(handle, streamed)
            if reason is not None:
                self._resolve_failed(handle, streamed, finish_reason=reason)
                continue
            # a cancel that raced the crash is still a cancel, not an engine
            # failure — resolve it as the abort the client asked for
            if handle._cancelled:
                self._resolve_failed(handle, streamed, finish_reason="abort")
                continue
            retryable = (
                handle.retries < limit
                # streamed tokens can only be folded into a retry prompt when
                # the sampling budget is adjustable alongside
                and (not streamed or handle._sampling is not None)
            )
            if retryable:
                handle.retries += 1
                handle._prefilled_hint = self._prefilled_len_of(handle.req_id)
                self.metrics.request_retries.inc()
                self._requeue.append(handle)
            else:
                n_failed += 1
                self._resolve_failed(handle, streamed)
        self._handles.clear()
        self._last_token_t.clear()
        return n_failed

    def _prefilled_len_of(self, req_id) -> int:
        """How many prompt tokens the (possibly poisoned) engine had already
        prefilled for ``req_id`` — read defensively at triage time so the
        requeue's goodput hint covers partial chunk walks too. 0 on any
        stand-in engine without the scheduler surface."""
        try:
            for r in list(self.engine.slots):
                if r is not None and r.req_id == req_id:
                    return int(getattr(r, "prefilled_len", 0))
        except Exception:
            pass
        return 0

    def _resolve_failed(self, handle: RequestHandle, streamed: List[int],
                        finish_reason: str = "engine_error"):
        req = _FailedRequest(handle.req_id, handle._prompt_ids or [], streamed,
                             handle.trace, handle.submitted_t,
                             finish_reason=finish_reason, tenant=handle.tenant,
                             adapter_id=handle.adapter_id)
        req.aborted = finish_reason == "abort"
        req.priority = handle.priority  # requests_total{priority} label
        if handle._first_token_t is not None:
            req.first_token_t = handle._first_token_t
            req.ttft = handle._first_token_t - req.arrival_t
            req.decode_time = req.finish_t - handle._first_token_t
        self.metrics.on_finished(req)
        self._trace_finished(req, handle)
        handle._resolve(req)

    def _resubmit_stashed(self) -> int:
        """Resubmit stashed handles into the rebuilt engine. Tokens already
        streamed become prompt suffix (recompute-requeue, exactly the
        preemption trick) with the remaining budget — positional sampling keys
        make the continuation identical for greedy/fixed-seed requests."""
        stashed, self._requeue = self._requeue, []
        n = 0
        for handle in stashed:
            if handle.done():  # cancelled while degraded
                continue
            streamed = list(handle._streamed)
            prompt = list(handle._prompt_ids or []) + streamed
            sampling = handle._sampling
            if streamed and sampling is not None:
                sampling = dataclasses.replace(
                    sampling, max_new_tokens=sampling.max_new_tokens - len(streamed))
            handle._retry_prefix = streamed
            stream_cb = self._make_stream_cb(handle)
            # goodput: a requeue with streamed tokens re-prefills a prompt the
            # dead engine had fully processed (all but the final sampled
            # token); a zero-streamed requeue may still have been mid-chunk-
            # walk — either way the re-fed span is requeue_refill rework,
            # never useful a second time
            rework_hwm = (len(prompt) - 1 if streamed
                          else min(handle._prefilled_hint, len(prompt)))
            try:
                handle.req_id = self._add_to_engine(handle, prompt, sampling,
                                                    stream_cb,
                                                    rework_hwm=rework_hwm)
            except Exception as e:
                # the rebuilt engine rejected the requeue: fail THIS request
                # rather than losing it (a poisoned engine will re-trip the
                # supervisor on the next step)
                logger.error(f"requeue of {handle.trace} failed: {e!r}")
                self._resolve_failed(handle, streamed)
                continue
            self._handles[handle.req_id] = handle
            n += 1
        return n

    def _reset_engine(self):
        """No factory: recover the existing engine in place via its
        ``reset()`` (drops all scheduler/allocator state)."""
        reset = getattr(self.engine, "reset", None)
        if reset is None:
            raise RuntimeError(
                "engine has no reset() and no engine_factory was provided; "
                "cannot recover from a step failure")
        reset()
        return self.engine

    def _resolve_all_with_error(self, e: BaseException):
        for h in list(self._handles.values()) + list(self._requeue):
            h._resolve(None, error=e)
        self._handles.clear()
        self._requeue = []
        if self._pending_swap is not None:
            self._pending_swap.fail(e)
            self._pending_swap = None
        for cmd in self._held_cmds:
            cmd[1]._resolve(None, error=e)
        self._held_cmds = []
        while True:
            try:
                cmd = self._cmds.get_nowait()
            except queue.Empty:
                break
            if cmd[0] == "submit":
                cmd[1]._resolve(None, error=e)
            elif cmd[0] == "weights":
                cmd[1].fail(e)

    # ------------------------------------------------------------- commands
    def _drain_cmds(self):
        while True:
            try:
                cmd = self._cmds.get_nowait()
            except queue.Empty:
                return
            kind, handle = cmd[0], cmd[1]
            if kind == "submit":
                _, _, prompt_ids, sampling = cmd
                if handle._cancelled:
                    handle._resolve(None)
                    continue
                if self._pending_swap is not None:
                    # a swap is quiescing: new work must not extend the drain
                    # (finish_old) or race the canary — hold it, re-inject
                    # after the swap settles (the handle's clock keeps
                    # running, so queue-wait metrics see the swap stall)
                    self._held_cmds.append(cmd)
                    continue
                handle.depth_at_submit = self._engine_backlog()
                stream_cb = self._make_stream_cb(handle)
                try:
                    handle.req_id = self._add_to_engine(handle, prompt_ids,
                                                        sampling, stream_cb)
                except UnknownAdapterError as e:
                    # a client error (bad adapter_id), not an engine failure:
                    # resolve the waiter without tripping the supervisor into
                    # a degrade/rebuild cycle
                    handle._resolve(None, error=e)
                    continue
                except BaseException as e:
                    # the command is consumed — resolve the waiter before the
                    # supervisor takes over, or the client blocks forever
                    handle._resolve(None, error=e)
                    raise
                self._handles[handle.req_id] = handle
            elif kind == "abort":
                self._abort_handle(handle)
            elif kind == "weights":
                if self._pending_swap is not None:
                    handle.fail(RuntimeError(
                        "another weight swap is already in progress"))
                else:
                    self._pending_swap = handle

    # ------------------------------------------------------------- weight swap
    def _execute_swap(self, swap: _WeightSwap):
        """Perform one quiesced weight swap on the loop thread — all-or-
        nothing per replica: retain old params → ``sync_params`` (eager
        placement) → prefix-cache epoch bump → greedy canary → commit; ANY
        failure restores the retained old params and re-bumps the epoch, so
        the replica keeps serving the version it served before. Old params
        are released (last reference dropped) only after the canary passed."""
        t0 = time.time()
        RECORDER.record("swap.begin", version=swap.version, mode=swap.mode)
        if swap.mode == "pause_resume" and self._handles:
            # stash in-flight requests exactly like the supervisor's triage:
            # streamed tokens fold into the retry prompt and the request
            # resumes under whichever params the swap settles on — explicitly
            # NOT token-identical to an uninterrupted old-weights generation
            for handle in list(self._handles.values()):
                if handle.done():
                    continue
                handle._prefilled_hint = self._prefilled_len_of(handle.req_id)
                self.engine.abort(handle.req_id)
                self.metrics.request_retries.inc()
                self._requeue.append(handle)
            self._handles.clear()
            self._last_token_t.clear()
        engine = self.engine
        old_params = engine.model.params  # retained until canary pass
        digest = None
        try:
            _F_WEIGHT_SWAP.fire(version=swap.version)
            engine.sync_params(swap.new_params)
            engine.clear_prefix_cache()
            if swap.canary_prompt_ids:
                digest = self._run_canary(swap)
                if swap.canary_digest is not None and digest != swap.canary_digest:
                    raise _CanaryMismatch(
                        f"canary digest {digest[:16]}... != expected "
                        f"{swap.canary_digest[:16]}...")
        except Exception as e:
            reason = ("canary_mismatch" if isinstance(e, _CanaryMismatch)
                      else "swap_failed")
            try:
                # a canary that died mid-generate may have left engine-side
                # request state: reset drops it (no client work is resident)
                if engine.has_work() and callable(getattr(engine, "reset", None)):
                    engine.reset()
                engine.sync_params(old_params)
                engine.clear_prefix_cache()
            except Exception as rb:
                # rollback itself failing leaves the replica poisoned — the
                # next step exception sends it through the supervisor
                logger.error(f"weight-swap rollback failed: {rb!r}")
            RECORDER.record("swap.rollback", version=swap.version, reason=reason,
                            error=repr(e)[:200])
            self.postmortem.dump("weight_swap_rollback", detail={
                "version": swap.version, "reason": reason,
                "error": repr(e)[:500], "canary_digest": digest})
            logger.error(
                f"weight swap to {swap.version!r} rolled back ({reason}): {e!r}")
            result = {"ok": False, "reason": reason, "error": repr(e)[:500],
                      "rolled_back": True,
                      "weights_version": self.weights_version,
                      "canary_digest": digest,
                      "wall_s": round(time.time() - t0, 3)}
        else:
            old_version = self.weights_version
            self.weights_version = swap.version
            if old_version != swap.version:
                self.metrics.weights_info.remove_series(version=old_version)
            self.metrics.weights_info.set(1.0, version=swap.version)
            RECORDER.record("swap.done", version=swap.version,
                            resumed=len(self._requeue))
            logger.info(f"weights swapped: {old_version!r} -> {swap.version!r} "
                        f"in {time.time() - t0:.2f}s (canary {digest and digest[:12]})")
            result = {"ok": True, "weights_version": swap.version,
                      "canary_digest": digest,
                      "wall_s": round(time.time() - t0, 3)}
        finally:
            self._pending_swap = None
            held, self._held_cmds = self._held_cmds, []
            for cmd in held:
                self._cmds.put(cmd)
        # pause_resume: the stash resumes under whichever params won (the new
        # ones, or the rolled-back old ones) — resumed continuations are
        # never token-identity-guaranteed, and the result says so
        resumed = self._resubmit_stashed() if self._requeue else 0
        result["resumed"] = resumed
        result["token_identity"] = resumed == 0
        swap.finish(result)

    def _run_canary(self, swap: _WeightSwap) -> str:
        """Greedy canary self-check on the drained engine: generate the fixed
        probe and digest the output ids. Runs on the loop thread between
        steps, so it never interleaves with client work."""
        out = self.engine.generate([list(swap.canary_prompt_ids)],
                                   swap.canary_sampling)[0]
        return canary_digest(out)

    def _add_to_engine(self, handle: RequestHandle, prompt_ids, sampling,
                       stream_cb, rework_hwm: int = 0) -> int:
        """One engine submission. ``priority`` / ``rework_hwm`` / ``tenant`` /
        ``adapter_id`` are forwarded only when non-default so engine stand-ins
        (chaos-test stubs, older backends) with the narrower ``add_request``
        signature keep working."""
        kw = {}
        if handle.priority != "interactive":
            kw["priority"] = handle.priority
        if rework_hwm > 0:
            kw["rework_hwm"] = rework_hwm
        if handle.tenant != DEFAULT_TENANT:
            kw["tenant"] = handle.tenant
        if handle.adapter_id is not None:
            # never dropped on TypeError: silently serving an adapter request
            # from the base model would be a cross-tenant correctness bug
            kw["adapter_id"] = handle.adapter_id
        try:
            return self.engine.add_request(prompt_ids, sampling, stream_cb=stream_cb,
                                           trace=handle.trace, **kw)
        except TypeError:
            dropped = [k for k in ("rework_hwm", "tenant") if k in kw]
            if not dropped:
                raise
            # engine stand-in without the goodput/tenancy kwargs: those hints
            # are best-effort accounting, the resubmission is not
            for k in dropped:
                kw.pop(k)
            return self.engine.add_request(prompt_ids, sampling, stream_cb=stream_cb,
                                           trace=handle.trace, **kw)

    def _engine_backlog(self) -> int:
        """Requests ahead of a new arrival: engine waiting queue + running
        slots. Falls back to the handle count for engines without the standard
        scheduler surface (test stubs). Tolerates concurrent mutation — a
        slightly stale count only jitters the Retry-After hint."""
        try:
            running = sum(1 for s in list(self.engine.slots) if s is not None)
            return len(self.engine.waiting) + running
        except Exception:
            return len(self._handles)

    def queue_wait_estimate(self, backlog: Optional[int] = None) -> float:
        """Live estimate (seconds) of how long a newly arriving request would
        wait for a slot: the p50 of recent per-backlog-slot queue+gate waits
        (PR-13 attribution) scaled by the CURRENT engine backlog — so 429/503
        ``Retry-After`` hints and deadline-aware admission track queue depth
        instead of quoting a constant. Callable from any thread."""
        if backlog is None:
            backlog = self._engine_backlog()
        with self._qw_lock:
            if self._queue_wait_samples and \
                    time.time() - self._qw_fresh_t > self.queue_wait_sample_ttl_s:
                self._queue_wait_samples.clear()
            samples = sorted(self._queue_wait_samples)
        per_slot = samples[len(samples) // 2] if samples else self._default_queue_wait_s
        return per_slot * (backlog + 1)

    def _make_stream_cb(self, handle: RequestHandle):
        def cb(tok: int, done: bool):
            now = time.time()
            last = self._last_token_t.get(handle.req_id)
            if last is not None:
                self.metrics.inter_token.observe(now - last)
            self._last_token_t[handle.req_id] = now
            handle._on_token(tok, done)
        return cb

    def _abort_handle(self, handle: RequestHandle):
        if handle.done():
            return
        if handle.req_id is None:
            # submit command not yet processed; the submit branch resolves it
            return
        req = self.engine.abort(handle.req_id)
        if req is not None:
            self._finish(req)

    def _enforce_deadlines(self):
        now = time.time()
        for handle in list(self._handles.values()):
            if handle.deadline_t is not None and now >= handle.deadline_t and not handle.done():
                logger.warning(f"req {handle.req_id}: deadline exceeded; aborting")
                handle.timed_out = True
                self._abort_handle(handle)

    def _finish(self, req):
        handle = self._handles.pop(req.req_id, None)
        if handle is not None and handle._retry_prefix:
            # a request that rode through >=1 engine rebuilds: its pre-crash
            # tokens were folded into the prompt — unfold so output_ids /
            # usage counts cover the FULL generation the client received, and
            # rebase the timing anchors so TTFT/e2e cover the pre-crash stint
            # and the degraded window (the SLO series must SEE the incident,
            # not report a fresh fast request)
            req.output_ids = list(handle._retry_prefix) + list(req.output_ids)
            req.prompt_ids = req.prompt_ids[: handle.prompt_len]
            req.arrival_t = handle.submitted_t
            if handle._first_token_t is not None:
                req.first_token_t = handle._first_token_t
        self.metrics.on_finished(req)
        self._last_token_t.pop(req.req_id, None)
        self._trace_finished(req, handle)
        if handle is not None:
            handle._resolve(req)

    def _trace_finished(self, req, handle: Optional[RequestHandle]):
        """Retrospective per-request phase spans (the engine's timing fields
        become a queue → prefill → decode timeline under the request's trace)
        plus a summary row for /debug/requests."""
        trace = handle.trace if handle is not None else getattr(req, "trace", None)
        phases = {}
        meta = dict(req_id=req.req_id, prompt_len=len(req.prompt_ids))
        if req.sched_t is not None:
            phases["queue"] = (req.arrival_t, req.sched_t)
        if req.sched_t is not None and req.first_token_t is not None:
            phases["prefill"] = (req.sched_t, req.first_token_t)
        if req.first_token_t is not None and req.finish_t is not None:
            phases["decode"] = (req.first_token_t, req.finish_t)
        for name, (t0, t1) in phases.items():
            TRACER.add_span(name, t0, t1 - t0, cat="request", trace=trace,  # span-names: queue prefill decode
                            wall=True, **meta)
        if req.finish_t is not None:
            TRACER.add_span("request", req.arrival_t, req.finish_t - req.arrival_t,
                            cat="request", trace=trace, wall=True,
                            finish_reason=req.finish_reason,
                            tokens=len(req.output_ids), **meta)
        # latency attribution: every finished request's e2e decomposed into
        # the phase vocabulary, observed into the {phase} histogram family
        # and surfaced on /debug/requests + in postmortem bundles
        attribution = request_attribution(req)
        if attribution is not None:
            for phase, seconds in attribution.items():
                self.metrics.latency_attribution.observe(seconds, phase=phase)
            if handle is not None:
                # feed the live queue-wait estimator: this request's observed
                # pre-admission wait, normalized by the backlog it arrived
                # behind (loop-thread append; see queue_wait_estimate)
                wait = attribution["queue"] + attribution["admission_gate"]
                with self._qw_lock:
                    self._queue_wait_samples.append(
                        wait / (max(handle.depth_at_submit, 0) + 1))
                    self._qw_fresh_t = time.time()
        # billing: exactly one usage record per request id — _trace_finished
        # is the funnel every resolution path passes through (normal finish,
        # abort, engine_error quarantine), and the meter's seen-id set makes
        # a double resolution book nothing twice
        usage_record = self.usage.record_finished(req, handle,
                                                  attribution=attribution)
        self.recent_finished.append({
            "trace": trace,
            "req_id": req.req_id,
            "state": "finished",
            "finish_reason": req.finish_reason,
            "retries": handle.retries if handle is not None else 0,
            "tenant": getattr(req, "tenant", None) or DEFAULT_TENANT,
            "adapter_id": getattr(req, "adapter_id", None)
            or (handle.adapter_id if handle is not None else None),
            "prompt_len": len(req.prompt_ids),
            "output_tokens": len(req.output_ids),
            "arrival_t": req.arrival_t,
            "queue_wait_s": req.queue_wait,
            "ttft_s": req.ttft,
            "decode_time_s": req.decode_time,
            "finish_t": req.finish_t,
            "attribution": attribution,
            "usage": None if usage_record is None else {
                k: usage_record[k]
                for k in ("prompt_tokens", "cached_tokens", "completion_tokens",
                          "useful_tokens", "kv_block_seconds",
                          "adapter_slot_seconds")},
        })

    def inflight_info(self) -> List[Dict]:
        """Point-in-time timelines of in-flight requests for /debug/requests.

        Called from HTTP threads while the loop mutates state: every field read
        is a single attribute/len fetch (atomic under the GIL) and the handle
        map is copied defensively, so the result may be a few tokens stale but
        never corrupt."""
        now = time.time()
        out = []
        handles = list(self._handles.values())
        requeued = list(self._requeue)
        for handle in handles + requeued:
            req = None
            if handle.req_id is not None and handle not in requeued:
                try:
                    req = next((r for r in list(self.engine.slots)
                                if r is not None and r.req_id == handle.req_id), None)
                    if req is None:
                        req = next((r for r in list(self.engine.waiting)
                                    if r.req_id == handle.req_id), None)
                except RuntimeError:
                    # slots/waiting mutated mid-copy by the loop thread: report
                    # the handle-level view only rather than failing the scrape
                    req = None
            info = {
                "trace": handle.trace,
                "req_id": handle.req_id,
                "prompt_len": handle.prompt_len,
                "age_s": now - handle.submitted_t,
                "retries": handle.retries,
                "deadline_in_s": None if handle.deadline_t is None else handle.deadline_t - now,
            }
            if handle in requeued:
                info["state"] = "requeued"  # waiting for the engine rebuild
            elif req is None:
                info["state"] = "submitted"
            else:
                info["state"] = "queued" if req.sched_t is None else (
                    "prefill" if req.first_token_t is None else "decode")
                info["output_tokens"] = len(req.output_ids)
                info["queue_wait_s"] = req.queue_wait
                info["ttft_s"] = req.ttft
                # disagg visibility: which stage pool holds the KV, and how
                # long the request has been waiting on block migration so far
                # — a stuck migration is visible LIVE, not just postmortem
                info["kv_stage"] = getattr(req, "kv_stage", None)
                mig_wait = getattr(req, "migration_wait_s", 0.0)
                open_t = getattr(req, "migrate_start_t", None)
                if open_t is not None:
                    mig_wait += max(now - open_t, 0.0)
                info["migration_wait_s"] = mig_wait
                # host-tier visibility: how long the request has waited on its
                # H2D promotion copy so far (kv_stage == "promoting" while the
                # copy is in flight) — a stuck promotion is visible LIVE too
                promote_wait = getattr(req, "promote_wait_s", 0.0)
                open_t = getattr(req, "promote_start_t", None)
                if open_t is not None:
                    promote_wait += max(now - open_t, 0.0)
                info["promote_wait_s"] = promote_wait
                info["usage_so_far"] = self._usage_so_far(req, handle)
            out.append(info)
        return out

    def _usage_so_far(self, req, handle: RequestHandle) -> Dict:
        """Running usage totals for one in-flight request (the live half of a
        usage record): tokens so far plus the KV-residency integral extended
        to 'now'. Same stale-but-never-corrupt contract as inflight_info."""
        kv_s = float(getattr(req, "kv_block_seconds", 0.0) or 0.0)
        occ_t = getattr(req, "kv_occ_t", None)
        if occ_t is not None:
            try:
                held = len(self.engine.mgr.tables.get(req.req_id, ()))
            except Exception:  # mgr mutated mid-read: report the booked part
                held = 0
            kv_s += max(time.perf_counter() - occ_t, 0.0) * held
        return {
            "prompt_tokens": handle.prompt_len,
            "cached_tokens": int(getattr(req, "cached_tokens", 0) or 0),
            "completion_tokens": len(handle._streamed),
            "useful_tokens": int(getattr(req, "useful_tokens", 0) or 0),
            "kv_block_seconds": round(kv_s, 6),
        }

    # ------------------------------------------------------------- postmortem
    def _postmortem_health(self) -> Dict:
        """Bundle health snapshot: loop + scheduler-visible state, engine
        stats, the in-flight view and the finished tail (which carries each
        request's latency attribution — the offline analyzer reads it)."""
        return {
            "loop_state": self._state,
            "phase": self._phase,
            "pending": self.pending_count(),
            "weights_version": self.weights_version,
            "slot_quarantines": self.slot_quarantines,
            "engine": self.engine.stats(),
            "inflight": self.inflight_info(),
            "recent_finished": list(self.recent_finished),
            "usage": self.usage.snapshot(),
        }

    def _postmortem_config(self) -> Dict:
        """Bundle config snapshot: the engine/supervisor knobs that shaped
        the decisions in the event trail."""
        eng = self.engine
        return {
            "max_batch_size": getattr(eng, "max_batch_size", None),
            "decode_steps": getattr(eng, "decode_steps", None),
            "prefill_chunk_tokens": getattr(eng, "prefill_chunk_tokens", None),
            "enable_prefix_cache": getattr(eng, "enable_prefix_cache", None),
            "staged": getattr(eng, "staged", False),
            "migration_inflight_limit": getattr(eng, "migration_inflight_limit", None),
            "decode_pressure_gate": getattr(eng, "decode_pressure_gate", None),
            "prefill_pressure_gate": getattr(eng, "prefill_pressure_gate", None),
            "backend": self._guarded_describe(),
            "supervisor_policy": dataclasses.asdict(self.policy),
        }

    def _guarded_describe(self) -> Dict:
        try:
            return self.engine.backend.describe()
        except Exception as e:
            return {"error": repr(e)}

    def _shutdown_cleanup(self):
        for handle in list(self._handles.values()):
            if handle.req_id is not None:
                req = self.engine.abort(handle.req_id)
                if req is not None:
                    self.metrics.on_finished(req)
                    # shutdown bypasses _trace_finished (no span emission at
                    # teardown) but the request still consumed tokens — book
                    # it, same idempotent path
                    self.usage.record_finished(req, handle)
                    handle._resolve(req)
                    continue
            handle._resolve(None)
        self._handles.clear()
        # requests stashed for a rebuild that never happened (stop while
        # degraded): their clients are blocked in result() — resolve them
        for handle in self._requeue:
            handle._resolve(None)
        self._requeue = []
        # a swap the stop interrupted (and submissions it was holding):
        # their waiters are blocked — fail/resolve them
        stop_err = RuntimeError("engine loop stopped")
        if self._pending_swap is not None:
            self._pending_swap.fail(stop_err)
            self._pending_swap = None
        for cmd in self._held_cmds:
            cmd[1]._resolve(None)
        self._held_cmds = []
        # submit commands that raced the stop and never reached the engine:
        # their clients are blocked in result() — resolve them too
        while True:
            try:
                cmd = self._cmds.get_nowait()
            except queue.Empty:
                break
            if cmd[0] == "submit":
                cmd[1]._resolve(None)
            elif cmd[0] == "weights":
                cmd[1].fail(stop_err)
