"""Background engine-driver thread: thread-safe submission + token streams.

Counterpart of the reference's serving split (``llm/predict/flask_server.py``
pushes prompts into the inference process and reads tokens back over a SysV
message queue): here the ``InferenceEngine`` runs on ONE dedicated thread that
continuously drives ``engine.step()``, and HTTP worker threads talk to it only
through queues — the engine itself is never touched concurrently, so the
host-side block manager needs no locks.

- ``submit()`` returns a :class:`RequestHandle`: a future (``result()``) plus
  a per-request token queue (``tokens()``) fed by the engine's ``stream_cb``;
- ``cancel()`` routes through the loop thread to ``engine.abort`` so KV blocks
  free deterministically between steps;
- per-request deadlines are enforced by the loop (expired requests abort with
  ``finish_reason='abort'`` and ``timed_out=True`` on the handle);
- all request lifecycle events land in the metrics plane (TTFT, queue wait,
  inter-token latency, tokens, preemptions, KV utilization).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..observability.tracer import TRACER
from ..utils.log import logger
from .metrics import REGISTRY, MetricsRegistry

__all__ = ["EngineLoop", "RequestHandle", "ServingMetrics"]

_END = object()  # token-queue sentinel: stream closed


class RequestHandle:
    """Client-side view of one in-flight request (future + token stream)."""

    def __init__(self, prompt_len: int, deadline_t: Optional[float] = None,
                 trace: Optional[str] = None):
        self.req_id: Optional[int] = None  # assigned on the loop thread
        self.trace = trace  # span-tracer trace id linking this request's phases
        self.prompt_len = prompt_len
        self.deadline_t = deadline_t
        self.submitted_t = time.time()
        self.timed_out = False
        self._token_q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._request = None  # engine Request once finished
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._callbacks: List = []
        self._cb_lock = threading.Lock()

    # ------------------------------------------------------------- futures
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the request finishes; returns the engine ``Request``."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.req_id} not finished within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._request

    @property
    def output_ids(self) -> List[int]:
        req = self.result()
        return list(req.output_ids)

    @property
    def finish_reason(self) -> Optional[str]:
        return self._request.finish_reason if self._request is not None else None

    # ------------------------------------------------------------- streaming
    def tokens(self, timeout: Optional[float] = None):
        """Yield token ids in generation order until the stream closes.

        ``timeout`` bounds the wait for EACH token (None = wait forever)."""
        while True:
            item = self._token_q.get(timeout=timeout)
            if item is _END:
                return
            tok, done = item
            yield tok
            if done:
                # drain the sentinel the resolver pushes after the last token
                try:
                    self._token_q.get_nowait()
                except queue.Empty:
                    pass
                return

    # ------------------------------------------------------------- loop-side
    def _on_token(self, tok: int, done: bool):
        self._token_q.put((tok, done))

    def add_done_callback(self, fn):
        """Run ``fn(handle)`` when the request resolves (immediately if done)."""
        with self._cb_lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _resolve(self, request, error: Optional[BaseException] = None):
        with self._cb_lock:
            if self._done.is_set():
                return
            self._request = request
            self._error = error
            self._token_q.put(_END)
            self._done.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn(self)
            except Exception as e:  # a bad callback must not kill the loop
                logger.warning(f"request done-callback failed: {e!r}")


class ServingMetrics:
    """Registers the serving metric catalog against one engine.

    Engine-state gauges are pull-mode (sampled at scrape); request-lifecycle
    series are pushed by the loop. Names are stable API — the README catalog
    and ``tools/bench_serve.py`` consume them."""

    def __init__(self, engine, registry: Optional[MetricsRegistry] = None):
        self.registry = r = registry or REGISTRY
        self.requests = r.counter(
            "paddlenlp_serving_requests_total", "Finished requests by terminal state",
            labelnames=("status",))
        self.tokens = r.counter(
            "paddlenlp_serving_tokens_generated_total", "Generated tokens (all requests)")
        self.preemptions = r.counter(
            "paddlenlp_serving_preemptions_total", "KV-exhaustion preemptions (recompute requeues)")
        self.ttft = r.histogram(
            "paddlenlp_serving_ttft_seconds", "Time from arrival to first token")
        self.queue_wait = r.histogram(
            "paddlenlp_serving_queue_wait_seconds", "Time from arrival to slot admission")
        self.inter_token = r.histogram(
            "paddlenlp_serving_inter_token_seconds", "Latency between consecutive tokens")
        self.e2e = r.histogram(
            "paddlenlp_serving_e2e_seconds", "Time from arrival to completion")
        self.queue_depth = r.gauge(
            "paddlenlp_serving_queue_depth", "Requests waiting for a slot")
        self.running = r.gauge(
            "paddlenlp_serving_running_slots", "Requests actively decoding")
        self.occupancy = r.gauge(
            "paddlenlp_serving_slot_occupancy", "running / max_batch_size")
        self.kv_free = r.gauge(
            "paddlenlp_serving_kv_free_blocks", "Free KV-cache blocks")
        self.kv_util = r.gauge(
            "paddlenlp_serving_kv_utilization", "1 - free/total KV blocks")
        self.spec_accept = r.gauge(
            "paddlenlp_serving_spec_acceptance_rate", "Accepted/drafted speculative tokens")
        mgr = engine.mgr
        self.queue_depth.set_function(lambda: len(engine.waiting))
        self.running.set_function(
            lambda: sum(1 for s in engine.slots if s is not None))
        self.occupancy.set_function(
            lambda: sum(1 for s in engine.slots if s is not None) / max(engine.max_batch_size, 1))
        self.kv_free.set_function(lambda: mgr.num_free)
        self.kv_util.set_function(
            lambda: 1.0 - mgr.num_free / max(mgr.total_usable_blocks, 1))
        self.spec_accept.set_function(
            lambda: engine.spec_stats["accepted"] / max(engine.spec_stats["drafted"], 1))

    def on_finished(self, req):
        status = req.finish_reason or ("abort" if req.aborted else "unknown")
        self.requests.inc(status=status)
        self.tokens.inc(len(req.output_ids))
        if req.ttft is not None:
            self.ttft.observe(req.ttft)
        if req.queue_wait is not None:
            self.queue_wait.observe(req.queue_wait)
        if req.finish_t is not None:
            self.e2e.observe(req.finish_t - req.arrival_t)

    def on_step(self, stats: Dict, preempt_delta: int):
        if preempt_delta > 0:
            self.preemptions.inc(preempt_delta)


class EngineLoop:
    """Owns the engine on one thread; everything else talks through queues."""

    def __init__(self, engine, metrics: Optional[ServingMetrics] = None,
                 registry: Optional[MetricsRegistry] = None, idle_wait_s: float = 0.05):
        self.engine = engine
        self.metrics = metrics or ServingMetrics(engine, registry)
        self.idle_wait_s = idle_wait_s
        self._cmds: "queue.Queue" = queue.Queue()
        self._wake = threading.Event()
        self._handles: Dict[int, RequestHandle] = {}
        self._last_token_t: Dict[int, float] = {}
        self._seen_preemptions = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._started = False
        self._trace_seq = itertools.count()
        # /debug/requests tail: finished-request summaries (appended only on
        # the loop thread; deque ops are atomic so HTTP readers need no lock)
        self.recent_finished: deque = deque(maxlen=64)

    # ------------------------------------------------------------- lifecycle
    def start(self):
        if self._started:
            return self
        self._started = True
        self._stop = False
        self._thread = threading.Thread(target=self._run, name="engine-loop", daemon=True)
        self._thread.start()
        return self

    @property
    def running(self) -> bool:
        return self._started and not self._stop

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the loop. ``drain=True`` finishes in-flight work first
        (bounded by ``timeout``); leftovers and ``drain=False`` abort."""
        if not self._started:
            return
        if drain:
            deadline = None if timeout is None else time.time() + timeout
            while self.pending_count() > 0:
                if deadline is not None and time.time() >= deadline:
                    logger.warning(f"engine loop drain timed out; aborting {self.pending_count()} requests")
                    break
                time.sleep(0.01)
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        self._started = False

    def pending_count(self) -> int:
        return len(self._handles) + self._cmds.qsize()

    # ------------------------------------------------------------- client api
    def submit(self, prompt_ids, sampling=None, deadline_s: Optional[float] = None) -> RequestHandle:
        """Thread-safe request submission; returns immediately with a handle."""
        if not self.running:
            raise RuntimeError("engine loop is not running")
        deadline_t = None if deadline_s is None else time.time() + deadline_s
        handle = RequestHandle(prompt_len=len(prompt_ids), deadline_t=deadline_t,
                               trace=f"req-{next(self._trace_seq)}")
        self._cmds.put(("submit", handle, prompt_ids, sampling))
        self._wake.set()
        return handle

    def cancel(self, handle: RequestHandle):
        """Request cancellation; resolves the handle once the loop aborts it."""
        handle._cancelled = True
        self._cmds.put(("abort", handle))
        self._wake.set()

    # ------------------------------------------------------------- loop body
    def _run(self):
        try:
            while not self._stop:
                self._drain_cmds()
                self._enforce_deadlines()
                if self.engine.has_work():
                    stats_before = self.engine.num_preemptions
                    for req in self.engine.step():
                        self._finish(req)
                    self.metrics.on_step(
                        self.engine.stats(), self.engine.num_preemptions - stats_before)
                else:
                    self._wake.wait(timeout=self.idle_wait_s)
                    self._wake.clear()
        except BaseException as e:  # loop death must not strand waiters
            logger.error(f"engine loop crashed: {e!r}")
            for h in list(self._handles.values()):
                h._resolve(None, error=e)
            self._handles.clear()
            while True:
                try:
                    cmd = self._cmds.get_nowait()
                except queue.Empty:
                    break
                if cmd[0] == "submit":
                    cmd[1]._resolve(None, error=e)
            raise
        finally:
            self._shutdown_cleanup()

    def _drain_cmds(self):
        while True:
            try:
                cmd = self._cmds.get_nowait()
            except queue.Empty:
                return
            kind, handle = cmd[0], cmd[1]
            if kind == "submit":
                _, _, prompt_ids, sampling = cmd
                if handle._cancelled:
                    handle._resolve(None)
                    continue
                stream_cb = self._make_stream_cb(handle)
                handle.req_id = self.engine.add_request(
                    prompt_ids, sampling, stream_cb=stream_cb, trace=handle.trace)
                self._handles[handle.req_id] = handle
            elif kind == "abort":
                self._abort_handle(handle)

    def _make_stream_cb(self, handle: RequestHandle):
        def cb(tok: int, done: bool):
            now = time.time()
            last = self._last_token_t.get(handle.req_id)
            if last is not None:
                self.metrics.inter_token.observe(now - last)
            self._last_token_t[handle.req_id] = now
            handle._on_token(tok, done)
        return cb

    def _abort_handle(self, handle: RequestHandle):
        if handle.done():
            return
        if handle.req_id is None:
            # submit command not yet processed; the submit branch resolves it
            return
        req = self.engine.abort(handle.req_id)
        if req is not None:
            self._finish(req)

    def _enforce_deadlines(self):
        now = time.time()
        for handle in list(self._handles.values()):
            if handle.deadline_t is not None and now >= handle.deadline_t and not handle.done():
                logger.warning(f"req {handle.req_id}: deadline exceeded; aborting")
                handle.timed_out = True
                self._abort_handle(handle)

    def _finish(self, req):
        self.metrics.on_finished(req)
        self._last_token_t.pop(req.req_id, None)
        handle = self._handles.pop(req.req_id, None)
        self._trace_finished(req, handle)
        if handle is not None:
            handle._resolve(req)

    def _trace_finished(self, req, handle: Optional[RequestHandle]):
        """Retrospective per-request phase spans (the engine's timing fields
        become a queue → prefill → decode timeline under the request's trace)
        plus a summary row for /debug/requests."""
        trace = handle.trace if handle is not None else getattr(req, "trace", None)
        phases = {}
        meta = dict(req_id=req.req_id, prompt_len=len(req.prompt_ids))
        if req.sched_t is not None:
            phases["queue"] = (req.arrival_t, req.sched_t)
        if req.sched_t is not None and req.first_token_t is not None:
            phases["prefill"] = (req.sched_t, req.first_token_t)
        if req.first_token_t is not None and req.finish_t is not None:
            phases["decode"] = (req.first_token_t, req.finish_t)
        for name, (t0, t1) in phases.items():
            TRACER.add_span(name, t0, t1 - t0, cat="request", trace=trace,
                            wall=True, **meta)
        if req.finish_t is not None:
            TRACER.add_span("request", req.arrival_t, req.finish_t - req.arrival_t,
                            cat="request", trace=trace, wall=True,
                            finish_reason=req.finish_reason,
                            tokens=len(req.output_ids), **meta)
        self.recent_finished.append({
            "trace": trace,
            "req_id": req.req_id,
            "state": "finished",
            "finish_reason": req.finish_reason,
            "prompt_len": len(req.prompt_ids),
            "output_tokens": len(req.output_ids),
            "arrival_t": req.arrival_t,
            "queue_wait_s": req.queue_wait,
            "ttft_s": req.ttft,
            "decode_time_s": req.decode_time,
            "finish_t": req.finish_t,
        })

    def inflight_info(self) -> List[Dict]:
        """Point-in-time timelines of in-flight requests for /debug/requests.

        Called from HTTP threads while the loop mutates state: every field read
        is a single attribute/len fetch (atomic under the GIL) and the handle
        map is copied defensively, so the result may be a few tokens stale but
        never corrupt."""
        now = time.time()
        out = []
        for handle in list(self._handles.values()):
            req = None
            if handle.req_id is not None:
                try:
                    req = next((r for r in list(self.engine.slots)
                                if r is not None and r.req_id == handle.req_id), None)
                    if req is None:
                        req = next((r for r in list(self.engine.waiting)
                                    if r.req_id == handle.req_id), None)
                except RuntimeError:
                    # slots/waiting mutated mid-copy by the loop thread: report
                    # the handle-level view only rather than failing the scrape
                    req = None
            info = {
                "trace": handle.trace,
                "req_id": handle.req_id,
                "prompt_len": handle.prompt_len,
                "age_s": now - handle.submitted_t,
                "deadline_in_s": None if handle.deadline_t is None else handle.deadline_t - now,
            }
            if req is None:
                info["state"] = "submitted"
            else:
                info["state"] = "queued" if req.sched_t is None else (
                    "prefill" if req.first_token_t is None else "decode")
                info["output_tokens"] = len(req.output_ids)
                info["queue_wait_s"] = req.queue_wait
                info["ttft_s"] = req.ttft
            out.append(info)
        return out

    def _shutdown_cleanup(self):
        for handle in list(self._handles.values()):
            if handle.req_id is not None:
                req = self.engine.abort(handle.req_id)
                if req is not None:
                    self.metrics.on_finished(req)
                    handle._resolve(req)
                    continue
            handle._resolve(None)
        self._handles.clear()
        # submit commands that raced the stop and never reached the engine:
        # their clients are blocked in result() — resolve them too
        while True:
            try:
                cmd = self._cmds.get_nowait()
            except queue.Empty:
                break
            if cmd[0] == "submit":
                cmd[1]._resolve(None)
