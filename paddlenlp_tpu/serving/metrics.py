"""Process-wide metrics plane: counters/gauges/histograms + Prometheus text.

Counterpart of the reference serving stack's monitoring hooks (its
``paddlenlp/server`` deploys behind a gateway that scrapes per-process stats);
here a single in-process registry is the source of truth for everything the
serving runtime reports — TTFT, inter-token latency, queue depth, KV-block
utilization, preemptions, speculative acceptance.

Deliberately stdlib-only (no jax, no prometheus_client): the registry must be
importable from trainer callbacks and tools without pulling in a backend, and
the container has no prometheus_client wheel. Exposition follows the
Prometheus text format 0.0.4 so a real scraper can consume ``/metrics``
unchanged.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "DEFAULT_LATENCY_BUCKETS",
]

# seconds; spans sub-ms CPU token steps up to multi-minute queue waits
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _format_labels(labels: LabelKey) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> LabelKey:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name}: got labels {sorted(labels)}, want {sorted(self.labelnames)}")
        return tuple((k, str(labels[k])) for k in self.labelnames)

    def expose(self) -> List[str]:  # pragma: no cover - overridden
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing count (requests, tokens, preemptions)."""

    kind = "counter"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError(f"counter {self.name}: cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labelnames:
            items = [((), 0.0)]
        return [f"{self.name}{_format_labels(k)} {_format_value(v)}" for k, v in items]


class Gauge(_Metric):
    """Point-in-time value (queue depth, slot occupancy, free blocks).

    ``set_function`` registers a pull-mode callable sampled at exposition —
    the natural shape for engine state the serving loop owns (free blocks,
    running slots) where push-updates from the hot loop would just be noise.
    """

    kind = "gauge"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._values: Dict[LabelKey, float] = {}
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)

    def set_function(self, fn: Callable[[], float]):
        if self.labelnames:
            raise ValueError(f"gauge {self.name}: set_function needs a label-less gauge")
        self._fn = fn

    def remove_series(self, **labels):
        """Drop one labeled series from the exposition — for label values
        that name entities with a bounded lifetime (a removed replica): a
        gauge pinned to its last value would read as a live fact forever."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    def value(self, **labels) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        if self._fn is not None:
            try:
                v = float(self._fn())
            except Exception:
                v = float("nan")
            return [f"{self.name} {_format_value(v)}"]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labelnames:
            items = [((), 0.0)]
        return [f"{self.name}{_format_labels(k)} {_format_value(v)}" for k, v in items]


class Histogram(_Metric):
    """Cumulative-bucket histogram (TTFT, inter-token latency, e2e latency)."""

    kind = "histogram"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, labelnames)
        b = sorted(float(x) for x in buckets)
        if not b or b[-1] != float("inf"):
            b.append(float("inf"))
        self.buckets = tuple(b)
        # per-labelset: (bucket counts, sum, count)
        self._data: Dict[LabelKey, Tuple[List[int], float, int]] = {}

    def observe(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            counts, total, n = self._data.get(key, ([0] * len(self.buckets), 0.0, 0))
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
                    break
            self._data[key] = (counts, total + value, n + 1)

    def count(self, **labels) -> int:
        with self._lock:
            return self._data.get(self._key(labels), ([], 0.0, 0))[2]

    def sum(self, **labels) -> float:
        with self._lock:
            return self._data.get(self._key(labels), ([], 0.0, 0))[1]

    def percentile(self, q: float, **labels) -> float:
        """Bucket-interpolated percentile (upper bound of the hit bucket) —
        good enough for the smoke benchmark's p50/p99 without storing samples."""
        with self._lock:
            counts, _, n = self._data.get(self._key(labels), ([], 0.0, 0))
            counts = list(counts)
        if n == 0:
            return 0.0
        target = q * n
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                ub = self.buckets[i]
                return self.buckets[i - 1] if math.isinf(ub) and i > 0 else ub
        return self.buckets[-2] if len(self.buckets) > 1 else 0.0

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted((k, (list(c), s, n)) for k, (c, s, n) in self._data.items())
        if not items and not self.labelnames:
            items = [((), ([0] * len(self.buckets), 0.0, 0))]
        out: List[str] = []
        for key, (counts, total, n) in items:
            cum = 0
            for i, ub in enumerate(self.buckets):
                cum += counts[i]
                le = "+Inf" if math.isinf(ub) else _format_value(ub)
                lk = key + (("le", le),)
                out.append(f"{self.name}_bucket{_format_labels(lk)} {cum}")
            out.append(f"{self.name}_sum{_format_labels(key)} {_format_value(total)}")
            out.append(f"{self.name}_count{_format_labels(key)} {n}")
        return out


class MetricsRegistry:
    """Named-metric registry with Prometheus text exposition.

    ``counter``/``gauge``/``histogram`` are idempotent: re-requesting an
    existing name returns the registered instance (so engine loop, scheduler
    and API can each grab handles without plumbing objects through)."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(f"metric {name} already registered as {m.kind}")
                return m
            m = cls(name, help, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames=labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames=labelnames)

    def histogram(self, name: str, help: str = "", labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames=labelnames, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str):
        with self._lock:
            self._metrics.pop(name, None)

    def expose(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in metrics:
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


#: process-wide default registry (the /metrics endpoint serves this)
REGISTRY = MetricsRegistry()
