"""Overload brownout: graceful degradation when scaling can't keep up.

When the autoscaler is at its max envelope (or a provision is still in
flight), a saturated replica must degrade *selectively* instead of timing out
uniformly: an interactive user keeps a fast first token while a best-effort
batch job gets a clean 503 + ``Retry-After``. The :class:`BrownoutController`
owns that ladder — a small state machine the :class:`~.scheduler.Scheduler`
consults on every admission:

====== ================== ==========================================================
level  name               effect
====== ================== ==========================================================
0      ``normal``         nothing
1      ``shed_best_effort`` ``priority="best_effort"`` submissions 503 on arrival
2      ``conserve``       + speculative decode disabled on the engine, and the
                          replica advertises ``brownout>=2`` on ``/health`` so the
                          router stops racing hedge shadows against it
3      ``clamp``          + ``max_new_tokens`` of newly admitted requests capped
====== ================== ==========================================================

**Entry** is driven by a pressure signal (the scheduler wires
``max(inflight / max_inflight, queue_wait_estimate / saturation_wait_s)``) or
by an external *push* (the router's SLO fast-burn hook or the autoscaler at
its max envelope POST ``/admin/brownout`` — the same best-effort propagation
channel drains use). A push sets a level *floor* with a TTL; local pressure
can escalate above it but never below while it holds.

**Exit** is hysteresis-guarded: pressure must stay below ``exit_pressure``
continuously for ``exit_hold_s`` before the ladder steps DOWN one level —
and the clock restarts per level, so a flapping signal cannot oscillate the
fleet between shedding and not shedding. Escalations are likewise spaced by
``step_hold_s`` so one pressure spike cannot jump straight to clamping.

Every level change is a cataloged flight-recorder event
(``brownout.enter``/``brownout.step``/``brownout.exit``) — the postmortem
trail shows exactly when and why the replica started shedding.

**Concurrency model.** ``evaluate``/``push``/``note_level`` may be called
from any HTTP worker thread (the scheduler evaluates on every submit, the
admin plane pushes); all mutable state is guarded by ``_lock`` (``#
guarded-by:`` annotations, enforced by the ``tools/analyze`` lock-discipline
checker). Level transitions are decided AND applied under a dedicated
``_apply_lock`` (held across both, with ``_lock`` only for the state
mutation inside) so a concurrent evaluate/push pair cannot apply enter and
exit side effects in the opposite order from the decisions; the
``on_level_change`` hook runs under ``_apply_lock`` but outside ``_lock``
(it touches the engine).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

from ..observability.flight_recorder import RECORDER
from ..observability.tracer import TRACER
from ..utils.log import logger

__all__ = ["BrownoutController", "BrownoutPolicy", "PRIORITIES",
           "BROWNOUT_LEVELS"]

#: the request-priority vocabulary, most- to least-protected. ``interactive``
#: is the default for requests that don't say.
PRIORITIES = ("interactive", "batch", "best_effort")

#: ladder level names, index == level
BROWNOUT_LEVELS = ("normal", "shed_best_effort", "conserve", "clamp")


@dataclasses.dataclass
class BrownoutPolicy:
    """Knobs governing the ladder. ``enter_pressure``/``exit_pressure`` bound
    the hysteresis band; ``saturation_wait_s`` is the queue-wait estimate that
    counts as pressure 1.0 (the scheduler folds it into the signal);
    ``step_hold_s`` spaces escalations, ``exit_hold_s`` is the sustained-calm
    requirement per de-escalation step; ``max_tokens_cap`` is the level-3
    clamp; ``push_ttl_s`` is how long a router/autoscaler push floors the
    level without being refreshed."""

    enter_pressure: float = 1.0
    exit_pressure: float = 0.5
    saturation_wait_s: float = 1.0
    step_hold_s: float = 2.0
    exit_hold_s: float = 5.0
    max_level: int = 3
    max_tokens_cap: int = 32
    push_ttl_s: float = 30.0

    def __post_init__(self):
        if not 0 <= self.exit_pressure <= self.enter_pressure:
            raise ValueError(
                f"need 0 <= exit_pressure <= enter_pressure, got "
                f"{self.exit_pressure} / {self.enter_pressure}")
        if not 0 <= self.max_level < len(BROWNOUT_LEVELS):
            raise ValueError(f"max_level must be in [0, {len(BROWNOUT_LEVELS) - 1}]")
        if self.max_tokens_cap < 1:
            raise ValueError("max_tokens_cap must be >= 1")


class BrownoutController:
    """The replica-side overload ladder (see module docstring).

    ``pressure_fn`` returns the current saturation signal (>= 1.0 means
    overloaded); ``on_level_change(level)`` applies level side effects (the
    serving server wires spec-decode disable here). ``now`` is injectable on
    every method so tests drive synthetic timelines."""

    def __init__(self, policy: Optional[BrownoutPolicy] = None,
                 pressure_fn: Optional[Callable[[], float]] = None,
                 on_level_change: Optional[Callable[[int], None]] = None):
        self.policy = policy or BrownoutPolicy()
        self.pressure_fn = pressure_fn
        self.on_level_change = on_level_change
        self._lock = threading.Lock()
        self._level = 0  # guarded-by: _lock
        self._pushed_level = 0  # guarded-by: _lock — external floor
        self._pushed_until = 0.0  # guarded-by: _lock — floor expiry
        # the last level _note_transition reported: evaluate()/push() diff
        # against THIS (not the instantaneous effective level) so a floor
        # expiring via TTL between calls still fires the exit transition —
        # otherwise on_level_change side effects (spec decode off) would
        # outlive the brownout silently
        self._last_reported = 0  # guarded-by: _lock
        self._last_step_t = 0.0  # guarded-by: _lock — last escalation time
        self._calm_since: Optional[float] = None  # guarded-by: _lock — exit-hysteresis anchor
        self._entered_t = 0.0  # guarded-by: _lock — when level left 0
        # level transitions are DECIDED and applied (hook + event) atomically
        # under this lock so a concurrent evaluate/push pair cannot apply
        # enter and exit in the opposite order from the decisions
        self._apply_lock = threading.Lock()
        # monotone counters for stats()/bench (single-writer-ish int bumps,
        # read-only consumers tolerate a momentarily stale value)
        self.entries = 0
        self.sheds = 0

    # ------------------------------------------------------------- inspection
    @property
    def level(self) -> int:
        with self._lock:
            return self._effective_level(time.time())

    @property
    def level_name(self) -> str:
        return BROWNOUT_LEVELS[self.level]

    def _effective_level(self, now: float) -> int:  # holds-lock: _lock
        """Caller holds ``_lock``. The local ladder level, floored by an
        unexpired push."""
        floor = self._pushed_level if now < self._pushed_until else 0
        return max(self._level, floor)

    # ------------------------------------------------------------- decisions
    def should_shed(self, priority: str, now: Optional[float] = None) -> bool:
        """True when this submission must be rejected on arrival: level >= 1
        sheds ``best_effort`` traffic first (the bottom of the ladder)."""
        if priority != "best_effort":
            return False
        now = now if now is not None else time.time()
        with self._lock:
            shed = self._effective_level(now) >= 1
            if shed:
                self.sheds += 1
        return shed

    def max_tokens_cap(self, now: Optional[float] = None) -> Optional[int]:
        """The level-3 clamp on ``max_new_tokens`` for NEW requests (None =
        no clamp)."""
        now = now if now is not None else time.time()
        with self._lock:
            return self.policy.max_tokens_cap if self._effective_level(now) >= 3 else None

    def spec_disabled(self, now: Optional[float] = None) -> bool:
        """Level >= 2: speculative decode should be off (it spends device
        cycles on throughput the fleet does not have)."""
        now = now if now is not None else time.time()
        with self._lock:
            return self._effective_level(now) >= 2

    # ------------------------------------------------------------- transitions
    def evaluate(self, now: Optional[float] = None) -> int:
        """Fold one pressure reading into the ladder; returns the effective
        level. Safe (and cheap) to call on every admission."""
        if self.pressure_fn is None:
            return self.level
        now = now if now is not None else time.time()
        try:
            pressure = float(self.pressure_fn())
        except Exception as e:  # a broken signal must never take down admission
            logger.warning(f"brownout: pressure signal failed: {e!r}")
            return self.level
        with self._apply_lock:
            with self._lock:
                before = self._last_reported
                if pressure >= self.policy.enter_pressure:
                    self._calm_since = None
                    if (self._level < self.policy.max_level
                            and now - self._last_step_t >= self.policy.step_hold_s):
                        self._level += 1
                        self._last_step_t = now
                elif pressure < self.policy.exit_pressure and self._level > 0:
                    if self._calm_since is None:
                        self._calm_since = now
                    elif now - self._calm_since >= self.policy.exit_hold_s:
                        self._level -= 1
                        # hysteresis restarts per level: each step down needs
                        # its own sustained-calm window
                        self._calm_since = now
                else:
                    # inside the hysteresis band: neither escalate nor start/
                    # keep the calm clock — the ladder holds
                    self._calm_since = None
                after = self._effective_level(now)
                self._last_reported = after
            self._note_transition(before, after, "saturation", now)
        return after

    def push(self, level: int, reason: str = "slo_fast_burn",
             ttl_s: Optional[float] = None, now: Optional[float] = None) -> int:
        """External brownout floor (router SLO fast burn / autoscaler at its
        max envelope). Repeated pushes refresh the TTL; ``level=0`` lifts the
        floor immediately (local pressure still governs the local ladder)."""
        level = max(0, min(int(level), self.policy.max_level))
        now = now if now is not None else time.time()
        ttl = float(ttl_s) if ttl_s is not None else self.policy.push_ttl_s
        with self._apply_lock:
            with self._lock:
                before = self._last_reported
                self._pushed_level = level
                self._pushed_until = now + ttl if level > 0 else 0.0
                after = self._effective_level(now)
                self._last_reported = after
            self._note_transition(before, after, reason, now)
        return after

    def _note_transition(self, before: int, after: int, reason: str, now: float):
        """Record one effective-level transition (hook + flight-recorder event
        + span instant). Caller holds ``_apply_lock`` (and NOT ``_lock``):
        decision and application are atomic with respect to each other, so a
        concurrent evaluate/push pair cannot apply enter and exit in the
        opposite order from the transitions they decided."""
        if after == before:
            return
        if before == 0 and after > 0:
            with self._lock:
                self._entered_t = now
            self.entries += 1
            RECORDER.record(
                "brownout.enter", reason=reason if reason in
                ("saturation", "slo_fast_burn") else "slo_fast_burn",
                level=after)
        elif before > 0 and after == 0:
            with self._lock:
                held = now - self._entered_t
            RECORDER.record("brownout.exit", held_s=round(held, 3))
        else:
            RECORDER.record("brownout.step", prev=before, level=after,
                            direction="up" if after > before else "down")
        TRACER.instant("brownout", cat="scheduler", prev=before, level=after,
                       reason=reason)
        logger.warning(
            f"brownout: {BROWNOUT_LEVELS[before]} -> {BROWNOUT_LEVELS[after]} "
            f"({reason})")
        if self.on_level_change is not None:
            try:
                self.on_level_change(after)
            except Exception as e:
                logger.warning(f"brownout: level-change hook failed: {e!r}")

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            now = time.time()
            eff = self._effective_level(now)
            return {
                "level": eff,
                "level_name": BROWNOUT_LEVELS[eff],
                "local_level": self._level,
                "pushed_level": self._pushed_level if now < self._pushed_until else 0,
                "entries": self.entries,
                "sheds": self.sheds,
            }
