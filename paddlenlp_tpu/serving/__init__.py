"""Continuous-batching LLM serving runtime (engine loop, admission control,
OpenAI-style HTTP API with SSE streaming, Prometheus metrics plane).

Import order matters for dependency weight: :mod:`.metrics` is stdlib-only
(reused by trainer callbacks/tools); the loop/scheduler/API pull in the
jax-backed engine lazily at construction time.
"""

from . import router  # noqa: F401  (multi-replica front tier; stdlib-only)
from .api import ServingServer  # noqa: F401
from .brownout import BrownoutController, BrownoutPolicy, PRIORITIES  # noqa: F401
from .chat import ChatTemplate  # noqa: F401
from .engine_loop import (  # noqa: F401
    EngineLoop,
    RequestHandle,
    ServingMetrics,
    SupervisorPolicy,
)
from .metrics import REGISTRY, Counter, Gauge, Histogram, MetricsRegistry  # noqa: F401
from .scheduler import (  # noqa: F401
    DeadlineUnmetError,
    DegradedError,
    SaturatedError,
    Scheduler,
    SchedulerConfig,
    ShedError,
    ShuttingDownError,
)

__all__ = [
    "router",
    "ServingServer",
    "ChatTemplate",
    "EngineLoop",
    "RequestHandle",
    "ServingMetrics",
    "SupervisorPolicy",
    "Scheduler",
    "SchedulerConfig",
    "SaturatedError",
    "ShuttingDownError",
    "DegradedError",
    "ShedError",
    "DeadlineUnmetError",
    "BrownoutController",
    "BrownoutPolicy",
    "PRIORITIES",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "REGISTRY",
]
