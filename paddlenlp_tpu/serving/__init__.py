"""Continuous-batching LLM serving runtime (engine loop, admission control,
OpenAI-style HTTP API with SSE streaming, Prometheus metrics plane).

Import order matters for dependency weight: :mod:`.metrics` is stdlib-only
(reused by trainer callbacks/tools); the loop/scheduler/API pull in the
jax-backed engine lazily at construction time.
"""

from .api import ServingServer  # noqa: F401
from .engine_loop import EngineLoop, RequestHandle, ServingMetrics  # noqa: F401
from .metrics import REGISTRY, Counter, Gauge, Histogram, MetricsRegistry  # noqa: F401
from .scheduler import (  # noqa: F401
    SaturatedError,
    Scheduler,
    SchedulerConfig,
    ShuttingDownError,
)

__all__ = [
    "ServingServer",
    "EngineLoop",
    "RequestHandle",
    "ServingMetrics",
    "Scheduler",
    "SchedulerConfig",
    "SaturatedError",
    "ShuttingDownError",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "REGISTRY",
]
