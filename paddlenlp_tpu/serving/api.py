"""OpenAI-style HTTP front end for the continuous-batching engine.

Counterpart of the reference's ``llm/predict/flask_server.py`` (streaming chat
HTTP) and ``paddlenlp/server`` (REST), rebuilt on the serving runtime: requests
go through :class:`Scheduler` admission into the :class:`EngineLoop`, tokens
stream back over SSE, and the metrics plane is scraped at ``/metrics``.
Stdlib ``ThreadingHTTPServer`` only (no flask/fastapi in the image).

Routes::

    POST /v1/completions   {"prompt": str | [int], "max_tokens": int,
                            "stream": bool, "temperature"/"top_p"/"top_k"/
                            "seed"/"do_sample", "timeout": float,
                            "priority": "interactive"|"batch"|"best_effort",
                            "deadline_ms": float, "tenant": str,
                            "adapter_id": str}
    POST /v1/chat/completions
                           {"messages": [{"role", "content"}, ...],
                            "conversation"?: str, ... same fields} — prefix-
                           stable chat rendering over the same pipeline; the
                            optional conversation key is the router's sticky-
                            affinity hint
    POST /v1/abort         {"id": "cmpl-N"}        — cancel an in-flight request
    GET  /metrics          Prometheus text exposition
    GET  /health           liveness + scheduler/engine stats + tracer clock
    GET  /debug/requests   in-flight + recently finished request timelines
    GET  /debug/efficiency goodput ledger + step anatomy + compile telemetry
                           (what fraction of each device step was useful work)
    GET  /debug/trace      span ring buffer as Chrome trace JSON (Perfetto)
    GET  /debug/spans      span ring buffer as structured JSONL
    POST /debug/profile    on-demand jax.profiler capture (?seconds=S; 409
                           while another capture runs)
    POST /debug/postmortem force a postmortem bundle dump (events + spans +
                           health + metrics + config); returns its path
    POST /admin/brownout   router/autoscaler-pushed overload-brownout floor
                           {"level": 0..3, "reason"?, "ttl_s"?}
    POST /admin/adapters   LoRA adapter hot-load/unload against the engine's
                           AdapterRegistry: {"op": "load", "adapter_id",
                           "path" | "weights", "scaling"?} | {"op": "unload",
                           "adapter_id"} | {"op": "list"}
    POST /admin/weights    live base-weight hot-swap from a committed
                           checkpoint: {"ckpt_dir": str, "version"?,
                           "mode"?: "finish_old"|"pause_resume",
                           "canary"?: bool, "canary_digest"?, "timeout_s"?}
                           — 409 on uncommitted/torn checkpoints, dimension
                           conflicts, or a swap already in flight; a failed
                           swap rolls back to the old weights and also
                           answers 409 (body carries the rollback detail)

Backpressure maps to HTTP: 429 when the admission window is full (retryable),
503 while draining, 413 for oversized bodies. A client disconnect mid-stream
aborts the request so its KV blocks free immediately.

Cross-tier tracing: a ``X-Pdnlp-Traceparent`` request header (stamped by the
router) makes the replica adopt the inbound trace id instead of minting its
own ``req-N``, and pins the propagated 1-in-N sampling decision on the tracer
— the router can then stitch both tiers' spans into one timeline.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
from http.server import ThreadingHTTPServer
from typing import Dict, Optional

from ..observability.exporter import handle_profile_request, route_observability
from ..observability.postmortem import handle_postmortem_request
from ..observability.tracer import TRACEPARENT_HEADER, TRACER, parse_traceparent, use_trace
from ..utils.faults import FaultPoint
from ..utils.log import logger
from .chat import ChatTemplate
from .engine_loop import CANARY_PROMPT_IDS, EngineLoop, RequestHandle, ServingMetrics, SupervisorPolicy
from .httputil import JsonRequestHandler
from .metrics import REGISTRY, MetricsRegistry
from .brownout import PRIORITIES
from .scheduler import (
    DeadlineUnmetError,
    DegradedError,
    SaturatedError,
    Scheduler,
    SchedulerConfig,
    ShedError,
    ShuttingDownError,
)
from .tenancy.adapters import UnknownAdapterError
from .tenancy.quotas import DEFAULT_TENANT, TenantQuotas

__all__ = ["ServingServer", "WeightSwapConflictError"]

MAX_BODY_BYTES = 8 << 20  # 8 MiB: far above any sane prompt payload

# fires inside /admin/weights BEFORE any validation or load — an injected
# fault here must surface as a clean HTTP error with zero engine mutation
_F_WEIGHT_LOAD = FaultPoint("engine.weight_load")

#: model-config dimensions that shape the parameter tree (and the LoRA pool
#: arrays): a checkpoint disagreeing on any of these can never be hot-swapped
_DIM_FIELDS = ("vocab_size", "hidden_size", "intermediate_size",
               "num_hidden_layers", "num_attention_heads",
               "num_key_value_heads", "head_dim")


class WeightSwapConflictError(ValueError):
    """A weight-swap request that can never succeed against this replica as
    it stands: uncommitted/torn checkpoint, dimension mismatch vs the live
    model config or resident adapters, or a swap already in flight (HTTP
    409, never 500 — the engine was not touched)."""


def _sampling_from_payload(payload: dict, max_new_default: int = 64):
    from ..experimental import SamplingParams

    return SamplingParams(
        max_new_tokens=int(payload.get("max_tokens", max_new_default)),
        do_sample=bool(payload.get("do_sample", False)),
        temperature=float(payload.get("temperature", 1.0)),
        top_p=float(payload.get("top_p", 1.0)),
        top_k=int(payload.get("top_k", 0)),
        seed=int(payload.get("seed", 0)),
        repetition_penalty=float(payload.get("repetition_penalty", 1.0)),
        presence_penalty=float(payload.get("presence_penalty", 0.0)),
        frequency_penalty=float(payload.get("frequency_penalty", 0.0)),
    )


class ServingServer:
    """Engine + loop + scheduler + HTTP, wired together.

    ``tokenizer`` is optional: without one, ``prompt`` must be a token-id list
    and responses carry ``token_ids`` instead of decoded ``text`` (the shape
    the CPU tests and the smoke benchmark use)."""

    def __init__(self, engine, tokenizer=None,
                 scheduler_config: Optional[SchedulerConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 max_body_bytes: int = MAX_BODY_BYTES,
                 max_src_tokens: Optional[int] = None,
                 engine_factory=None,
                 supervisor_policy: Optional[SupervisorPolicy] = None,
                 trace_sample_every: Optional[int] = None,
                 tenant_quotas: Optional[TenantQuotas] = None,
                 usage_meter=None,
                 chat_template: Optional[ChatTemplate] = None):
        self.engine = engine
        # /v1/chat/completions rendering (prefix-stable by construction so
        # multi-turn conversations ride the hierarchical prefix cache)
        self.chat_template = chat_template or ChatTemplate()
        self.tokenizer = tokenizer if tokenizer is not None else getattr(engine, "tokenizer", None)
        self.registry = registry or REGISTRY
        self.tracer = TRACER
        if trace_sample_every is not None:
            # standalone 1-in-N sampling knob (router-fronted replicas get the
            # decision in the traceparent header instead)
            self.tracer.sample_every = int(trace_sample_every)
        self.max_body_bytes = max_body_bytes
        self.max_src_tokens = max_src_tokens
        self.loop = EngineLoop(engine, metrics=ServingMetrics(engine, self.registry),
                               engine_factory=engine_factory, policy=supervisor_policy,
                               usage=usage_meter)
        self.scheduler = Scheduler(self.loop, scheduler_config,
                                   tenant_quotas=tenant_quotas)
        # brownout side effects: level >= 2 turns speculative decode off on
        # the live engine (conserve device cycles for committed tokens); the
        # baseline is captured here so exit restores the configured behavior.
        # A supervisor rebuild comes up with factory defaults — the next level
        # transition re-applies.
        self._spec_baseline = bool(getattr(engine, "use_speculative", False))
        self.scheduler.brownout.on_level_change = self._apply_brownout_level
        self.loop.metrics.brownout_level.set_function(
            lambda: self.scheduler.brownout.level)
        self._ids = itertools.count()
        self._live: Dict[str, RequestHandle] = {}
        self._live_lock = threading.Lock()
        # Retry-After hint stamped on drain rejections (503): set by
        # start_drain(), defaults to a short generic backoff
        self._drain_retry_after = 5.0
        self._httpd: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------------- submission
    def _encode(self, prompt):
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompt needs a tokenizer; pass token ids instead")
            ids = self.tokenizer.encode(prompt)
            ids = getattr(ids, "ids", ids)
        else:
            ids = [int(t) for t in prompt]
            # raw token ids come straight off the wire: range-check against
            # the model vocab (a bad id would otherwise surface as a garbage
            # completion, or as an engine-step failure downstream)
            vocab = getattr(getattr(self.engine, "model", None), "config", None)
            vocab = getattr(vocab, "vocab_size", None)
            if ids and vocab is not None and (min(ids) < 0 or max(ids) >= vocab):
                raise ValueError(
                    f"prompt token ids must be in [0, {vocab}); "
                    f"got min {min(ids)}, max {max(ids)}")
        if not ids:
            raise ValueError("empty prompt")
        if self.max_src_tokens is not None:
            ids = ids[-self.max_src_tokens:]
        return ids

    def submit(self, payload: dict, traceparent: Optional[str] = None,
               cid_prefix: str = "cmpl"):
        """Parse + admit one completion request. Returns (completion_id, handle).

        ``traceparent`` is the raw inbound propagation header (if any): the
        request adopts the upstream trace id and sampling decision, so its
        spans stitch into the router's timeline under one id."""
        if "prompt" not in payload:
            raise ValueError("missing required field 'prompt'")
        ids = self._encode(payload["prompt"])
        sampling = _sampling_from_payload(payload)
        if sampling.max_new_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        timeout_s = payload.get("timeout")
        if timeout_s is not None:
            timeout_s = float(timeout_s)
            if timeout_s <= 0:
                raise ValueError("timeout must be > 0 seconds")
        max_retries = payload.get("max_retries")
        if max_retries is not None:
            max_retries = int(max_retries)
            if max_retries < 0:
                raise ValueError("max_retries must be >= 0")
        priority = str(payload.get("priority", "interactive"))
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {'/'.join(PRIORITIES)}, got {priority!r}")
        deadline_s = payload.get("deadline_ms")
        if deadline_s is not None:
            deadline_s = float(deadline_s) / 1e3
            if deadline_s <= 0:
                raise ValueError("deadline_ms must be > 0 milliseconds")
        tenant = str(payload.get("tenant", DEFAULT_TENANT))
        if not tenant:
            raise ValueError("tenant must be a non-empty string")
        adapter_id = payload.get("adapter_id")
        if adapter_id is not None:
            adapter_id = str(adapter_id)
            # reject unknown adapters at the door (400) instead of letting the
            # submission die on the loop thread; the engine re-checks under
            # its own registry view, so a hot-unload race still fails safely
            registry = getattr(self.loop.engine, "adapter_registry", None)
            if registry is None:
                raise ValueError("this replica serves no LoRA adapters "
                                 "(engine has no adapter registry)")
            if adapter_id not in registry:
                raise ValueError(
                    f"unknown adapter_id {adapter_id!r}; load it first via "
                    f"POST /admin/adapters (registered: {registry.ids()})")
        trace_id = None
        ctx = parse_traceparent(traceparent)
        if ctx is not None:
            trace_id, parent_id, sampled = ctx
            # pin the upstream decision BEFORE any span can record under the id
            self.tracer.mark_trace(trace_id, sampled)
            # parentage marker: ties the adopted trace back to the tier that
            # placed it (recorded only for sampled traces, like every span)
            self.tracer.instant("trace_adopted", cat="serving", trace=trace_id,
                                parent=parent_id)
        handle = self.scheduler.submit(ids, sampling, timeout_s=timeout_s,
                                       max_retries=max_retries, trace=trace_id,
                                       priority=priority, deadline_s=deadline_s,
                                       tenant=tenant, adapter_id=adapter_id)
        cid = f"{cid_prefix}-{next(self._ids)}"
        with self._live_lock:
            self._live[cid] = handle
        handle.add_done_callback(lambda _h: self._forget(cid))
        return cid, handle

    def submit_chat(self, payload: dict, traceparent: Optional[str] = None):
        """Parse + admit one chat-completion request (POST
        /v1/chat/completions): render the conversation to token ids with the
        prefix-stable :class:`ChatTemplate`, then feed the ordinary
        completion pipeline — every downstream field (stream, priority,
        deadline, tenant, adapter_id, timeout) means exactly what it does on
        /v1/completions. ``conversation`` is an optional opaque sticky-
        routing key: the router pins a conversation's turns to one replica so
        its cached (device- or host-tier) KV keeps being re-used; the replica
        itself does not interpret it."""
        if "messages" not in payload:
            raise ValueError("missing required field 'messages'")
        if "prompt" in payload:
            raise ValueError("chat completions take 'messages', not 'prompt'")
        conversation = payload.get("conversation")
        if conversation is not None and not isinstance(conversation, str):
            raise ValueError("conversation must be a string key")

        def encode(text: str):
            if self.tokenizer is None:
                raise ValueError("string message content needs a tokenizer; "
                                 "pass token-id lists instead")
            ids = self.tokenizer.encode(text)
            return getattr(ids, "ids", ids)

        ids = self.chat_template.render(payload["messages"], encode)
        body = {k: v for k, v in payload.items()
                if k not in ("messages", "conversation")}
        body["prompt"] = ids
        return self.submit(body, traceparent=traceparent, cid_prefix="chatcmpl")

    def _forget(self, cid: str):
        with self._live_lock:
            self._live.pop(cid, None)

    def abort(self, cid: str) -> bool:
        with self._live_lock:
            handle = self._live.get(cid)
        if handle is None or handle.done():
            return False
        self.scheduler.cancel(handle)
        return True

    def start_drain(self, retry_after_s: Optional[float] = None) -> dict:
        """Replica-side drain: stop admitting NEW requests (direct traffic
        included — they 503 with ``Retry-After``) while in-flight streams
        finish. The router propagates its admin-plane drains here so a
        drained replica rejects clients that bypass the router, not just
        router-routed traffic. The engine loop keeps running until
        :meth:`shutdown`."""
        if retry_after_s is not None:
            retry_after_s = float(retry_after_s)
            if retry_after_s > 0:
                self._drain_retry_after = retry_after_s
        self.scheduler.start_drain()
        return {"draining": True, "retry_after_s": self._drain_retry_after}

    def stop_drain(self) -> dict:
        """Rejoin half of a rolling weight rollout: resume admitting new work
        after a drain. The engine loop never stopped, so there is nothing to
        restart — the admission gate just reopens."""
        self.scheduler.stop_drain()
        return {"draining": False}

    def efficiency(self) -> dict:
        """The ``GET /debug/efficiency`` document: the live engine's goodput
        ledger + step anatomy (the loop swaps engines on rebuild, so this
        always reads through ``loop.engine``). Engines without a ledger
        (stand-ins) report a minimal doc instead of a 500."""
        engine = self.loop.engine
        eff = getattr(engine, "efficiency", None)
        doc = eff() if eff is not None else {"tier": "serving", "ledger": None}
        doc["engine_state"] = self.loop.state
        return doc

    def usage(self) -> dict:
        """The ``GET /debug/usage`` document: the meter's rolling per-tenant/
        per-adapter aggregate plus durable-ledger stats. This is the replica
        view the router's ``/fleet/usage`` fold sums."""
        doc = self.loop.usage.snapshot()
        doc["engine_state"] = self.loop.state
        return doc

    def _apply_brownout_level(self, level: int):
        """Brownout ladder side effects on the live engine: level >= 2
        disables speculative decode (spend device time on committed tokens
        only); exit restores the construction-time baseline."""
        engine = self.loop.engine
        if hasattr(engine, "use_speculative"):
            engine.use_speculative = False if level >= 2 else self._spec_baseline

    def push_brownout(self, payload: dict) -> dict:
        """Router/autoscaler-pushed brownout floor (POST /admin/brownout):
        the fleet tier saw SLO fast burn or is pinned at its max scale
        envelope, so this replica must start shedding even if its local
        pressure signal has not tripped yet. ``{"level": 0..3, "reason"?,
        "ttl_s"?}`` — level 0 lifts the floor."""
        level = int(payload.get("level", 1))
        if not 0 <= level <= 3:
            raise ValueError(f"level must be in [0, 3], got {level}")
        ttl_s = payload.get("ttl_s")
        if ttl_s is not None:
            ttl_s = float(ttl_s)
            if not (ttl_s > 0):
                raise ValueError("ttl_s must be > 0 seconds")
        reason = str(payload.get("reason", "slo_fast_burn"))
        effective = self.scheduler.brownout.push(level, reason=reason, ttl_s=ttl_s)
        return {"level": effective, "pushed": level,
                "brownout": self.scheduler.brownout.stats()}

    def admin_adapters(self, payload: dict) -> dict:
        """LoRA adapter hot-load/unload (POST /admin/adapters) against the
        live engine's :class:`AdapterRegistry`. Ops::

            {"op": "load", "adapter_id": str,
             "path": str | "weights": {"<proj>.lora_A": [[...]], ...},
             "scaling"?: float}     -> registers (idempotent on same bytes)
            {"op": "unload", "adapter_id": str}  -> drops store + pool slot
            {"op": "list"}                       -> ids + pool stats only

        Loading only registers in the host store; the device pool slot is
        taken lazily by the first request that decodes with the adapter.
        Unload is refused (409 via ValueError) while any request holds it."""
        registry = getattr(self.loop.engine, "adapter_registry", None)
        if registry is None:
            raise ValueError("this replica serves no LoRA adapters "
                             "(engine has no adapter registry)")
        op = str(payload.get("op", "list"))
        doc: dict = {"op": op}
        if op == "load":
            adapter_id = str(payload.get("adapter_id") or "")
            source = payload.get("path") if payload.get("path") is not None \
                else payload.get("weights")
            if source is None:
                raise ValueError("load needs 'path' (safetensors) or 'weights'")
            if isinstance(source, dict):
                # JSON bodies carry nested lists; the registry wants arrays
                source = {k: v for k, v in source.items()}
            scaling = payload.get("scaling")
            doc["digest"] = registry.add(
                adapter_id, source,
                scaling=None if scaling is None else float(scaling))
            doc["adapter_id"] = adapter_id
        elif op == "unload":
            adapter_id = str(payload.get("adapter_id") or "")
            registry.remove(adapter_id)
            doc["adapter_id"] = adapter_id
        elif op != "list":
            raise ValueError(f"op must be load/unload/list, got {op!r}")
        doc["adapters"] = registry.ids()
        doc["stats"] = registry.stats()
        return doc

    def _check_ckpt_dims(self, ckpt_dir: str):
        """409-gate a swap on checkpoint/model dimension agreement BEFORE any
        bytes are loaded. Two layers: the checkpoint's own config must agree
        with the live model config on every tree-shaping dimension, and when
        LoRA adapters are resident their pool projection shapes (derived from
        the same dims) must survive the swap — a mismatch is listed per-field
        so the operator sees exactly what conflicts."""
        from .tenancy.adapters import adapter_dims_from_config

        model = self.loop.engine.model
        cur = model.config
        try:
            new = type(cur).from_pretrained(ckpt_dir)
        except Exception as e:
            raise WeightSwapConflictError(
                f"checkpoint {ckpt_dir} has no readable model config: {e}")
        conflicts = []
        for field in _DIM_FIELDS:
            a, b = getattr(cur, field, None), getattr(new, field, None)
            if a is not None and b is not None and int(a) != int(b):
                conflicts.append(f"{field}: model {a} vs checkpoint {b}")
        if conflicts:
            raise WeightSwapConflictError(
                "checkpoint dimensions conflict with the live model config: "
                + "; ".join(conflicts))
        registry = getattr(self.loop.engine, "adapter_registry", None)
        resident = registry.ids() if registry is not None else []
        if resident:
            cur_dims = adapter_dims_from_config(cur)
            new_dims = adapter_dims_from_config(new)
            bad = [f"{proj}: {cur_dims[proj]} vs {new_dims[proj]}"
                   for proj in cur_dims if cur_dims[proj] != new_dims.get(proj)]
            if bad:
                raise WeightSwapConflictError(
                    f"checkpoint projection shapes conflict with resident "
                    f"adapters {resident}: " + "; ".join(bad))

    def _load_ckpt_params(self, ckpt_dir: str):
        """Materialize the checkpoint's parameter tree host-side (placement
        onto the backend's device layout happens inside the quiesced swap via
        ``sync_params``). Built against the LIVE config so the tree structure
        is guaranteed identical; a leaf-shape surprise inside the loader
        (torn shard, wrong file) is still a 409, not a 500."""
        model = self.loop.engine.model
        try:
            loaded = type(model).from_pretrained(
                ckpt_dir, config=model.config, dtype=model.dtype,
                param_dtype=model.param_dtype)
        except ValueError as e:
            raise WeightSwapConflictError(
                f"checkpoint {ckpt_dir} does not match the live parameter "
                f"tree: {e}")
        return loaded.params

    def admin_weights(self, payload: dict) -> dict:
        """Live base-weight hot-swap (POST /admin/weights): validate a
        committed checkpoint, 409-gate dimension conflicts, load the new tree,
        then hand it to the engine loop which quiesces at a step boundary,
        installs through the backend seam, bumps the prefix-cache epoch, runs
        the canary probe, and rolls back all-or-nothing on any failure.
        Everything that can fail cheaply fails HERE, on the HTTP thread,
        before the loop is asked to touch the engine."""
        ckpt_dir = payload.get("ckpt_dir")
        if not ckpt_dir or not isinstance(ckpt_dir, str):
            raise ValueError("missing required field 'ckpt_dir' (string path)")
        _F_WEIGHT_LOAD.fire(path=ckpt_dir)
        from ..trainer.unified_checkpoint import validate_checkpoint

        reason = validate_checkpoint(ckpt_dir, verify_hashes=True)
        if reason is not None:
            raise WeightSwapConflictError(
                f"checkpoint {ckpt_dir} is not swappable: {reason}")
        self._check_ckpt_dims(ckpt_dir)
        new_params = self._load_ckpt_params(ckpt_dir)
        version = str(payload.get("version")
                      or os.path.basename(os.path.normpath(ckpt_dir)))
        mode = str(payload.get("mode", "finish_old"))
        timeout_s = payload.get("timeout_s")
        timeout_s = 120.0 if timeout_s is None else float(timeout_s)
        canary = bool(payload.get("canary", True))
        canary_digest = payload.get("canary_digest")
        if canary_digest is not None:
            canary_digest = str(canary_digest)
        canary_ids = payload.get("canary_prompt")
        if canary_ids is not None:
            canary_ids = tuple(int(t) for t in canary_ids)
        elif canary:
            canary_ids = CANARY_PROMPT_IDS
        try:
            result = self.loop.request_weight_swap(
                new_params, version, mode=mode,
                canary_prompt_ids=canary_ids, canary_digest=canary_digest,
                timeout_s=timeout_s)
        except RuntimeError as e:
            # another swap holds the loop, or the loop is not running: the
            # engine was not touched — a clean conflict, not a server error
            raise WeightSwapConflictError(str(e))
        result["ckpt_dir"] = ckpt_dir
        return result

    def _decode_delta(self, toks, emitted: int, final: bool = False):
        """Incremental detokenization: full-decode + diff. A trailing U+FFFD
        means a codepoint is still split across tokens — hold it back until the
        next token resolves it (or ``final`` flushes it as-is), otherwise the
        replacement char would be emitted and never corrected."""
        if self.tokenizer is None:
            return None, emitted
        text = self.tokenizer.decode(toks, skip_special_tokens=True)
        safe = len(text)
        if not final:
            while safe > emitted and text[safe - 1] == "�":
                safe -= 1
        return text[emitted:safe], safe

    # ------------------------------------------------------------- http
    def _make_httpd(self, host: str, port: int) -> ThreadingHTTPServer:
        server = self

        class Handler(JsonRequestHandler):
            log_prefix = "serving"

            @property
            def max_body_bytes(self):  # live read: the cap is server-tunable
                return server.max_body_bytes

            # --------------------------------------------------------- GET
            def do_GET(self):
                try:
                    # /metrics, /debug/trace, /debug/spans: shared with the
                    # training exporter (observability.exporter)
                    routed = route_observability(self.path, server.registry, server.tracer)
                    if routed is not None:
                        self._send_raw(routed[0], routed[2], routed[1])
                    elif self.path == "/health":
                        if server.scheduler.draining:
                            status = "draining"
                        elif server.loop.degraded:
                            status = "degraded"
                        else:
                            status = "ok"
                        headers = None
                        if status == "degraded":
                            headers = {"Retry-After": max(1, int(round(server.loop.retry_after_hint())))}
                        elif status == "draining":
                            headers = {"Retry-After": max(1, int(round(server._drain_retry_after)))}
                        self._send_json(200 if status == "ok" else 503, {
                            "status": status,
                            "scheduler": server.scheduler.stats(),
                            "engine": server.loop.engine.stats(),
                            # base-weight version this replica serves: the
                            # router's rollout gate and version-skew failover
                            # guard both key off this field
                            "weights_version": server.loop.weights_version,
                            # overload ladder level, top-level so the router's
                            # health poller can read it without digging into
                            # scheduler stats (>= 2 suppresses hedging here)
                            "brownout": server.scheduler.brownout.level,
                            # tracer-timeline clock, piggybacked for the
                            # router's RTT-midpoint clock-skew estimate
                            "now": server.tracer.now(),
                        }, headers=headers)
                    elif self.path == "/debug/requests":
                        self._send_json(200, {
                            "inflight": server.loop.inflight_info(),
                            "recent": list(server.loop.recent_finished),
                        })
                    elif self.path == "/debug/efficiency":
                        self._send_json(200, server.efficiency())
                    elif self.path == "/debug/usage":
                        self._send_json(200, server.usage())
                    else:
                        self._send_error_json(404, f"no route {self.path}", "not_found")
                except (BrokenPipeError, ConnectionResetError):
                    logger.debug("serving: client disconnected during GET")

            # --------------------------------------------------------- POST
            def do_POST(self):
                try:
                    if self.path.split("?", 1)[0] in ("/debug/profile",
                                                      "/debug/postmortem"):
                        # drain any request body before responding: leftover
                        # bytes would desync the next request on this
                        # keep-alive connection
                        n = int(self.headers.get("Content-Length") or 0)
                        if n:
                            self.rfile.read(n)
                        routed = handle_profile_request(self.path) \
                            or handle_postmortem_request(self.path, server.loop.postmortem)
                        self._send_raw(routed[0], routed[2], routed[1])
                    elif self.path == "/v1/completions":
                        payload = self._read_body()
                        if payload is not None:
                            self._completions(payload)
                    elif self.path == "/v1/chat/completions":
                        payload = self._read_body()
                        if payload is not None:
                            self._completions(payload, chat=True)
                    elif self.path == "/v1/abort":
                        payload = self._read_body()
                        if payload is not None:
                            ok = server.abort(str(payload.get("id", "")))
                            self._send_json(200, {"id": payload.get("id"), "cancelled": ok})
                    elif self.path == "/admin/drain":
                        payload = self._read_body()
                        if payload is not None:
                            try:
                                if payload.get("undo"):
                                    doc = server.stop_drain()
                                else:
                                    doc = server.start_drain(payload.get("retry_after_s"))
                            except (TypeError, ValueError):
                                self._send_error_json(
                                    400,
                                    f"retry_after_s must be a number, got "
                                    f"{payload.get('retry_after_s')!r}",
                                    "invalid_request")
                            else:
                                self._send_json(200, doc)
                    elif self.path == "/admin/weights":
                        payload = self._read_body()
                        if payload is not None:
                            try:
                                doc = server.admin_weights(payload)
                            except WeightSwapConflictError as e:
                                self._send_error_json(409, str(e), "weights_conflict")
                            except TimeoutError as e:
                                self._send_error_json(
                                    504, f"weight swap timed out: {e}", "swap_timeout")
                            except (TypeError, ValueError) as e:
                                self._send_error_json(400, str(e), "invalid_request")
                            else:
                                # a swap that failed mid-flight rolled back and
                                # kept serving the old weights: a conflict with
                                # the full rollback detail in the body, so the
                                # router's rollout orchestrator can abort on it
                                self._send_json(200 if doc.get("ok") else 409, doc)
                    elif self.path == "/admin/adapters":
                        payload = self._read_body()
                        if payload is not None:
                            try:
                                doc = server.admin_adapters(payload)
                            except UnknownAdapterError as e:
                                self._send_error_json(404, str(e), "unknown_adapter")
                            except (TypeError, ValueError) as e:
                                self._send_error_json(400, str(e), "invalid_request")
                            else:
                                self._send_json(200, doc)
                    elif self.path == "/admin/brownout":
                        payload = self._read_body()
                        if payload is not None:
                            try:
                                doc = server.push_brownout(payload)
                            except (TypeError, ValueError) as e:
                                self._send_error_json(400, str(e), "invalid_request")
                            else:
                                self._send_json(200, doc)
                    else:
                        self._send_error_json(404, f"no route {self.path}", "not_found")
                except (BrokenPipeError, ConnectionResetError):
                    # dead socket: never attempt a second write
                    logger.debug("serving: client disconnected during POST")
                except Exception as e:
                    logger.warning(f"serving: error on {self.path}: {e!r}")
                    try:
                        self._send_error_json(500, str(e), "internal_error")
                    except (BrokenPipeError, ConnectionResetError):
                        pass

            def _completions(self, payload: dict, chat: bool = False):
                try:
                    submit = server.submit_chat if chat else server.submit
                    cid, handle = submit(
                        payload, traceparent=self.headers.get(TRACEPARENT_HEADER))
                except SaturatedError as e:
                    # Retry-After from the live queue-wait estimate: the hint
                    # tracks how deep the backlog actually is right now
                    self._send_error_json(
                        429, str(e), "rate_limit_exceeded",
                        headers={"Retry-After": max(1, int(round(
                            getattr(e, "retry_after_s", 1.0))))})
                    return
                except ShedError as e:
                    # brownout priority shed: clean 503 + the live hint — the
                    # client (or router) backs off instead of re-queueing work
                    # the ladder will keep rejecting
                    self._send_error_json(
                        503, str(e), "overloaded_shed",
                        headers={"Retry-After": max(1, int(round(e.retry_after_s)))})
                    return
                except DeadlineUnmetError as e:
                    self._send_error_json(
                        503, str(e), "deadline_unmet",
                        headers={"Retry-After": max(1, int(round(e.retry_after_s)))})
                    return
                except DegradedError as e:
                    # circuit breaker: engine rebuild in progress — a clean 503
                    # with a recovery hint, never a connection reset
                    self._send_error_json(
                        503, str(e), "engine_recovering",
                        headers={"Retry-After": max(1, int(round(e.retry_after_s)))})
                    return
                except ShuttingDownError as e:
                    # draining replica: a clean 503 WITH a retry hint so a
                    # direct client backs off instead of hammering a server
                    # that is leaving the fleet
                    self._send_error_json(
                        503, str(e), "shutting_down",
                        headers={"Retry-After": max(1, int(round(server._drain_retry_after)))})
                    return
                except (ValueError, TypeError) as e:
                    self._send_error_json(400, str(e), "invalid_request")
                    return
                # ambient trace id: log records emitted while serving this
                # request carry it in JSON log mode (log <-> trace join key)
                with use_trace(handle.trace):
                    if payload.get("stream"):
                        self._stream_response(cid, handle, chat=chat)
                    else:
                        self._batch_response(cid, handle, chat=chat)

            def _batch_response(self, cid: str, handle, chat: bool = False):
                try:
                    req = handle.result()  # deadline enforced by the loop
                except UnknownAdapterError as e:
                    # adapter hot-unloaded between the door check and engine
                    # admission: still a client-visible 4xx, not a 500
                    self._send_error_json(400, str(e), "unknown_adapter")
                    return
                choice = {"index": 0, "finish_reason": req.finish_reason if req else "abort"}
                toks = list(req.output_ids) if req is not None else []
                text = (server.tokenizer.decode(toks, skip_special_tokens=True)
                        if server.tokenizer is not None else None)
                if chat:
                    # chat shape: the completion is an assistant message whose
                    # token_ids are what the NEXT turn should thread back as
                    # assistant content for an exact prefix-cache replay
                    message = {"role": "assistant", "token_ids": toks}
                    if text is not None:
                        message["content"] = text
                    choice["message"] = message
                else:
                    choice["token_ids"] = toks
                    if text is not None:
                        choice["text"] = text
                self._send_json(200, {
                    "id": cid,
                    "object": "chat.completion" if chat else "text_completion",
                    "choices": [choice],
                    "usage": {
                        "prompt_tokens": handle.prompt_len,
                        "cached_tokens": int(getattr(req, "cached_tokens", 0) or 0),
                        "completion_tokens": len(toks),
                        "total_tokens": handle.prompt_len + len(toks),
                    },
                    "timing": {
                        "ttft_s": req.ttft if req else None,
                        "queue_wait_s": req.queue_wait if req else None,
                        "decode_time_s": req.decode_time if req else None,
                    },
                })

            def _stream_response(self, cid: str, handle, chat: bool = False):
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()

                def chunk(obj: dict):
                    self.wfile.write(f"data: {json.dumps(obj)}\n\n".encode())
                    self.wfile.flush()

                obj = "chat.completion.chunk" if chat else "text_completion.chunk"
                toks, emitted = [], 0
                try:
                    if chat:
                        # role preamble first, in the OpenAI chat-chunk shape
                        chunk({"id": cid, "object": obj, "choices": [
                            {"index": 0, "delta": {"role": "assistant"},
                             "finish_reason": None}]})
                    for tok in handle.tokens():
                        toks.append(tok)
                        piece, emitted = server._decode_delta(toks, emitted)
                        if chat:
                            delta = {"token": tok}
                            if piece is not None:
                                delta["content"] = piece
                            c = {"index": 0, "delta": delta, "finish_reason": None}
                        else:
                            c = {"index": 0, "token": tok, "finish_reason": None}
                            if piece is not None:
                                c["text"] = piece
                        chunk({"id": cid, "object": obj, "choices": [c]})
                    req = handle.result()
                    final = {"index": 0,
                             "finish_reason": req.finish_reason if req else "abort"}
                    # flush any held-back partial-codepoint text
                    piece, emitted = server._decode_delta(toks, emitted, final=True)
                    if chat:
                        final["delta"] = {"content": piece} if piece else {}
                    elif piece:
                        final["text"] = piece
                    chunk({"id": cid, "object": obj,
                           "choices": [final],
                           "usage": {"prompt_tokens": handle.prompt_len,
                                     "cached_tokens": int(getattr(req, "cached_tokens", 0) or 0),
                                     "completion_tokens": len(toks),
                                     "total_tokens": handle.prompt_len + len(toks)}})
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    # client went away mid-stream: free the slot + KV now
                    logger.debug(f"serving: client disconnected; aborting {cid}")
                    server.abort(cid)
                except Exception as e:
                    # headers already sent — a second status line would corrupt
                    # the stream; terminate it in-band instead
                    logger.warning(f"serving: stream {cid} failed: {e!r}")
                    server.abort(cid)
                    try:
                        chunk({"id": cid, "object": "error",
                               "error": {"message": str(e), "type": "internal_error"}})
                        self.wfile.write(b"data: [DONE]\n\n")
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        pass

        httpd = ThreadingHTTPServer((host, port), Handler)
        httpd.daemon_threads = True
        return httpd

    # ------------------------------------------------------------- lifecycle
    def start_in_thread(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start loop + HTTP without blocking; returns the bound port."""
        self.loop.start()
        self._httpd = self._make_httpd(host, port)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True, name="serving-http")
        t.start()
        bound = self._httpd.server_address[1]
        logger.info(f"serving API on {host}:{bound} (POST /v1/completions, GET /metrics)")
        return bound

    def run(self, host: str = "0.0.0.0", port: int = 8011):
        self.loop.start()
        self._httpd = self._make_httpd(host, port)
        logger.info(f"serving API on {host}:{port} (POST /v1/completions, GET /metrics)")
        try:
            self._httpd.serve_forever()
        finally:
            self.shutdown()

    def shutdown(self, drain_timeout_s: Optional[float] = 30.0):
        """Graceful: stop admitting (503), drain in-flight, stop loop + HTTP."""
        self.scheduler.shutdown(timeout_s=drain_timeout_s)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
