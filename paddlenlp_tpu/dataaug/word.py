"""Word-level data augmentation.

Counterpart of ``paddlenlp/dataaug/word.py`` (``WordSubstitute`` :29,
``WordInsert`` :313, ``WordSwap`` :516, ``WordDelete`` :582). Zero-egress
build: substitution/insertion draw from a user-supplied synonym table (the
reference's embedding/WordNet sources are download-backed); swap/delete are
source-free. All augmenters are seeded and deterministic.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["WordSubstitute", "WordInsert", "WordSwap", "WordDelete"]


class BaseAugment:
    _joiner = " "  # char-level augmenters re-join without separators

    def __init__(self, create_n: int = 1, aug_n: Optional[int] = None,
                 aug_percent: float = 0.1, seed: int = 0):
        self.create_n = create_n
        self.aug_n = aug_n
        self.aug_percent = aug_percent
        self.rng = np.random.default_rng(seed)

    def _tokenize(self, text: str) -> List[str]:
        return text.split()

    def _n_for(self, tokens: List[str]) -> int:
        if self.aug_n is not None:
            return min(self.aug_n, max(len(tokens), 1))
        return max(1, int(len(tokens) * self.aug_percent))

    def _augment_once(self, tokens: List[str]) -> Optional[List[str]]:
        raise NotImplementedError

    def augment(self, text):
        """str -> List[str] of create_n variants; List[str] -> list per input."""
        if isinstance(text, list):
            return [self.augment(t) for t in text]
        tokens = self._tokenize(text)
        out = []
        for _ in range(self.create_n * 4):  # retry budget for degenerate inputs
            if len(out) >= self.create_n:
                break
            aug = self._augment_once(list(tokens))
            if aug is not None:
                cand = self._joiner.join(aug)
                if cand != text and cand not in out:
                    out.append(cand)
        return out

    def __call__(self, text):
        return self.augment(text)


class WordSubstitute(BaseAugment):
    """Replace words using a synonym table {"word": ["syn1", ...]}."""

    def __init__(self, aug_type: str = "custom", custom_file_or_dict=None, **kw):
        super().__init__(**kw)
        if isinstance(custom_file_or_dict, dict):
            self.table: Dict[str, List[str]] = custom_file_or_dict
        elif isinstance(custom_file_or_dict, str):
            import json

            with open(custom_file_or_dict, encoding="utf-8") as f:
                self.table = json.load(f)
        else:
            raise ValueError("WordSubstitute needs a synonym dict or a json file path "
                             "(this build has no download-backed synonym sources)")

    def _augment_once(self, tokens):
        cands = [i for i, t in enumerate(tokens) if t in self.table and self.table[t]]
        if not cands:
            return None
        n = min(self._n_for(tokens), len(cands))
        for i in self.rng.choice(cands, size=n, replace=False):
            tokens[i] = str(self.rng.choice(self.table[tokens[i]]))
        return tokens


class WordInsert(WordSubstitute):
    """Insert a synonym next to a known word."""

    def _augment_once(self, tokens):
        cands = [i for i, t in enumerate(tokens) if t in self.table and self.table[t]]
        if not cands:
            return None
        n = min(self._n_for(tokens), len(cands))
        for i in sorted(self.rng.choice(cands, size=n, replace=False), reverse=True):
            tokens.insert(i + 1, str(self.rng.choice(self.table[tokens[i]])))
        return tokens


class WordSwap(BaseAugment):
    """Swap adjacent word pairs."""

    def _augment_once(self, tokens):
        if len(tokens) < 2:
            return None
        n = self._n_for(tokens)
        for _ in range(n):
            i = int(self.rng.integers(0, len(tokens) - 1))
            tokens[i], tokens[i + 1] = tokens[i + 1], tokens[i]
        return tokens


class WordDelete(BaseAugment):
    """Delete random words."""

    def _augment_once(self, tokens):
        if len(tokens) < 2:
            return None
        n = min(self._n_for(tokens), len(tokens) - 1)
        drop = set(self.rng.choice(len(tokens), size=n, replace=False).tolist())
        return [t for i, t in enumerate(tokens) if i not in drop]
