from .word import WordDelete, WordInsert, WordSubstitute, WordSwap

__all__ = ["WordSubstitute", "WordInsert", "WordSwap", "WordDelete"]
