from .char import CharDelete, CharInsert, CharSubstitute, CharSwap  # noqa: F401
from .word import WordDelete, WordInsert, WordSubstitute, WordSwap  # noqa: F401

__all__ = ["WordSubstitute", "WordInsert", "WordSwap", "WordDelete",
           "CharSubstitute", "CharInsert", "CharSwap", "CharDelete"]
