"""Character-level data augmentation.

Counterpart of ``paddlenlp/dataaug/char.py`` (``CharSubstitute``, ``CharInsert``,
``CharSwap``, ``CharDelete`` — ~2k LoC of download-backed variants). Character
units (not whitespace words), so it works on Chinese text; substitution and
insertion draw from a user-supplied homophone/confusion table, swap/delete are
source-free. Deterministic under ``seed``.
"""

from __future__ import annotations

from typing import List

from .word import BaseAugment, WordInsert, WordSubstitute

__all__ = ["CharSubstitute", "CharInsert", "CharSwap", "CharDelete"]


class _CharTokenizeMixin:
    _joiner = ""  # char units re-join without spaces

    def _tokenize(self, text: str) -> List[str]:
        return list(text)


class CharSubstitute(_CharTokenizeMixin, WordSubstitute):
    """Replace characters using a confusion table {"char": ["variant", ...]}."""


class CharInsert(_CharTokenizeMixin, WordInsert):
    """Insert a table variant next to a known character."""


class CharSwap(_CharTokenizeMixin, BaseAugment):
    """Swap adjacent characters."""

    def _augment_once(self, chars):
        if len(chars) < 2:
            return None
        n = self._n_for(chars)
        for _ in range(n):
            i = int(self.rng.integers(0, len(chars) - 1))
            chars[i], chars[i + 1] = chars[i + 1], chars[i]
        return chars


class CharDelete(_CharTokenizeMixin, BaseAugment):
    """Delete random characters."""

    def _augment_once(self, chars):
        if len(chars) < 2:
            return None
        n = min(self._n_for(chars), len(chars) - 1)
        drop = set(self.rng.choice(len(chars), size=n, replace=False).tolist())
        return [c for i, c in enumerate(chars) if i not in drop]
