"""Classical sequence-to-vector encoders.

Counterpart of ``paddlenlp/seq2vec/encoder.py`` (``BoWEncoder`` :23,
``CNNEncoder`` :125, ``GRUEncoder`` :292, ``LSTMEncoder`` :477, ``RNNEncoder``
:661 — the legacy text-classification building blocks). TPU-native: recurrent
encoders unroll with ``flax.linen`` RNN cells under ``lax.scan``; conv windows
are shifted adds (kernels are tiny).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

__all__ = ["BoWEncoder", "CNNEncoder", "GRUEncoder", "LSTMEncoder", "RNNEncoder"]


def _mask3(mask, like):
    return mask[..., None].astype(like.dtype)


class BoWEncoder(nn.Module):
    """Sum of embeddings (masked)."""

    emb_dim: int

    def __call__(self, inputs, mask: Optional[jnp.ndarray] = None):
        if mask is not None:
            inputs = inputs * _mask3(mask, inputs)
        return inputs.sum(axis=1)

    def get_output_dim(self) -> int:
        return self.emb_dim


class CNNEncoder(nn.Module):
    """Parallel 1D convs (one per ngram size) + max-pool, concatenated."""

    emb_dim: int
    num_filter: int = 128
    ngram_filter_sizes: Sequence[int] = (2, 3, 4, 5)

    @nn.compact
    def __call__(self, inputs, mask: Optional[jnp.ndarray] = None):
        if mask is not None:
            inputs = inputs * _mask3(mask, inputs)
        B, T, D = inputs.shape
        outs = []
        for k in self.ngram_filter_sizes:
            w = self.param(f"conv_{k}_kernel", nn.initializers.lecun_normal(),
                           (k, D, self.num_filter))
            b = self.param(f"conv_{k}_bias", nn.initializers.zeros, (self.num_filter,))
            n_win = T - k + 1
            if n_win <= 0:
                outs.append(jnp.zeros((B, self.num_filter), inputs.dtype))
                continue
            conv = sum(inputs[:, j : j + n_win] @ w[j] for j in range(k)) + b
            outs.append(jnp.tanh(conv).max(axis=1))
        return jnp.concatenate(outs, axis=-1)

    def get_output_dim(self) -> int:
        return self.num_filter * len(self.ngram_filter_sizes)


class _RecurrentEncoder(nn.Module):
    """Shared driver over ``nn.RNN`` (native seq-length masking + reverse);
    subclasses pick the cell."""

    input_size: int
    hidden_size: int
    num_layers: int = 1
    direction: str = "forward"  # forward | bidirect
    pooling_type: Optional[str] = None  # None (last state) | sum | max | mean

    def _cell(self, name):
        raise NotImplementedError

    @nn.compact
    def __call__(self, inputs, mask: Optional[jnp.ndarray] = None):
        B, T, _ = inputs.shape
        lengths = mask.sum(-1) if mask is not None else jnp.full((B,), T, jnp.int32)
        h = inputs
        last_states = []
        for layer in range(self.num_layers):
            rnn_f = nn.RNN(self._cell(f"l{layer}_fwd"), name=f"l{layer}_fwd_rnn")
            carry_f, ys_f = rnn_f(h, seq_lengths=lengths, return_carry=True)
            if self.direction == "bidirect":
                rnn_b = nn.RNN(self._cell(f"l{layer}_bwd"), name=f"l{layer}_bwd_rnn")
                carry_b, ys_b = rnn_b(h, seq_lengths=lengths, return_carry=True, reverse=True,
                                      keep_order=True)
                h = jnp.concatenate([ys_f, ys_b], axis=-1)
                last_states.append((carry_f, carry_b))
            else:
                h = ys_f
                last_states.append((carry_f,))
        if self.pooling_type is None:
            finals = []
            for c in last_states[-1]:
                hidden = c[1] if isinstance(c, tuple) and len(c) == 2 else c
                finals.append(hidden)
            return jnp.concatenate(finals, axis=-1)
        if mask is not None:
            h = h * _mask3(mask, h)
        if self.pooling_type == "sum":
            return h.sum(axis=1)
        if self.pooling_type == "max":
            return jnp.where(_mask3(mask, h) > 0, h, -jnp.inf).max(axis=1) if mask is not None else h.max(axis=1)
        if self.pooling_type == "mean":
            denom = mask.sum(-1, keepdims=True).astype(h.dtype) if mask is not None else h.shape[1]
            return h.sum(axis=1) / jnp.maximum(denom, 1)
        raise ValueError(f"pooling_type must be None|sum|max|mean, got {self.pooling_type!r}")

    def get_output_dim(self) -> int:
        return self.hidden_size * (2 if self.direction == "bidirect" else 1)


class LSTMEncoder(_RecurrentEncoder):
    def _cell(self, name):
        return nn.OptimizedLSTMCell(self.hidden_size, name=name)


class GRUEncoder(_RecurrentEncoder):
    def _cell(self, name):
        return nn.GRUCell(self.hidden_size, name=name)


class RNNEncoder(_RecurrentEncoder):
    def _cell(self, name):
        return nn.SimpleCell(self.hidden_size, name=name)
