from .encoder import BoWEncoder, CNNEncoder, GRUEncoder, LSTMEncoder, RNNEncoder

__all__ = ["BoWEncoder", "CNNEncoder", "GRUEncoder", "LSTMEncoder", "RNNEncoder"]
