"""PromptTrainer + soft prompt tuning.

Counterpart of ``paddlenlp/prompt/`` (2.4k LoC: PromptTrainer, PromptModel,
templates/verbalizers). Two pieces:

- ``PromptModelForClassification``: masked-LM model + template + verbalizer;
  classification logits are the verbalized vocab logits at the mask position.
- ``SoftPromptModelForCausalLM``: p-tuning-style trainable virtual-token
  embeddings prepended via ``inputs_embeds``; only the prompt matrix trains
  (facade design like peft/prefix).
- ``PromptTrainer``: Trainer whose loss is CE over verbalized class scores.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..trainer.trainer import Trainer
from ..transformers.conversion_utils import flatten_params, unflatten_params
from ..utils.log import logger
from ..utils.safetensors_io import SafeFile, save_file

__all__ = ["PromptModelForClassification", "SoftPromptModelForCausalLM", "PromptTrainer"]

SOFT_PROMPT_WEIGHTS_NAME = "soft_prompt.safetensors"


class PromptModelForClassification:
    """Masked-LM + verbalizer head (frozen or full finetune both work)."""

    def __init__(self, model, template, verbalizer):
        self.model = model
        self.template = template
        self.verbalizer = verbalizer
        self.config = model.config
        self.params = model.params
        self.module = model.module

    def class_logits(self, params, input_ids, attention_mask, mask_position):
        out = self.model.module.apply({"params": params}, input_ids=input_ids,
                                      attention_mask=attention_mask, deterministic=True)
        logits = out.logits if hasattr(out, "logits") else out[0]
        mask_logits = jnp.take_along_axis(
            logits, mask_position[:, None, None].astype(jnp.int32), axis=1
        )[:, 0]
        return self.verbalizer.process_logits(mask_logits.astype(jnp.float32))

    def num_parameters(self, params=None):
        return self.model.num_parameters(params)


class SoftPromptModelForCausalLM:
    """Prepends ``n_prompt_tokens`` trainable embeddings to the input embedding
    sequence; labels/attention are host-extended by the caller (the Trainer's
    built-in loss sees -100 over the virtual span via compute_loss below)."""

    def __init__(self, model, n_prompt_tokens: int = 16, init_std: float = 0.02,
                 params: Optional[dict] = None):
        self.model = model
        self.config = model.config
        self.dtype = model.dtype
        self.n_prompt_tokens = n_prompt_tokens
        if params is not None:
            self.params = params
        else:
            rng = np.random.default_rng(0)
            prompt = rng.normal(0.0, init_std,
                                (n_prompt_tokens, model.config.hidden_size)).astype(np.float32)
            self.params = dict(model.params)
            self.params["soft_prompt"] = jnp.asarray(prompt)
        self.module = self
        self.mesh = model.mesh
        self.generation_config = model.generation_config

    # duck-typed module.apply used by the Trainer loss
    def apply(self, variables, input_ids=None, attention_mask=None, deterministic=True, **kw):
        params = variables["params"] if "params" in variables else variables
        prompt = params["soft_prompt"]
        base = {k: v for k, v in params.items() if k != "soft_prompt"}
        B, T = input_ids.shape
        embed = self._embedding(base)
        tok = jnp.take(embed, input_ids, axis=0).astype(self.model.module.dtype)
        virt = jnp.broadcast_to(prompt[None], (B,) + prompt.shape).astype(tok.dtype)
        inputs_embeds = jnp.concatenate([virt, tok], axis=1)
        if attention_mask is not None:
            attention_mask = jnp.concatenate(
                [jnp.ones((B, self.n_prompt_tokens), attention_mask.dtype), attention_mask], axis=1
            )
        out = self.model.module.apply({"params": base}, inputs_embeds=inputs_embeds,
                                      attention_mask=attention_mask,
                                      deterministic=deterministic, **kw)
        # slice the virtual-token span off so logits align with the caller's
        # [B, T] labels (the built-in causal-LM loss shifts against them)
        if hasattr(out, "logits"):
            import dataclasses as _dc

            return _dc.replace(out, logits=out.logits[:, self.n_prompt_tokens:])
        return out

    def _embedding(self, params):
        prefix = type(self.model).base_model_prefix
        node = params.get(prefix, params)
        for key in ("embed_tokens", "wte", "word_embeddings"):
            if key in node:
                return node[key]["embedding"]
        raise ValueError("could not locate the token embedding table for soft prompts")

    def trainable_mask(self) -> dict:
        flat = flatten_params(self.params)
        return unflatten_params({p: p == "soft_prompt" for p in flat})

    def get_partition_rules_instance(self):
        from ..parallel.partition import P

        base = list(type(self.model).get_partition_rules(self.config))
        return base + [(r"^soft_prompt$", P(None, "embed"))]

    def __call__(self, *args, params=None, **kwargs):
        return self.apply({"params": params if params is not None else self.params}, *args, **kwargs)

    def num_parameters(self, params=None):
        return self.model.num_parameters()

    def get_model_flops(self, *a, **kw):
        return self.model.get_model_flops(*a, **kw)

    def save_pretrained(self, save_directory: str, **kw):
        os.makedirs(save_directory, exist_ok=True)
        save_file({"soft_prompt": np.asarray(jax.device_get(self.params["soft_prompt"]))},
                  os.path.join(save_directory, SOFT_PROMPT_WEIGHTS_NAME), metadata={"format": "np"})
        logger.info(f"soft prompt saved to {save_directory}")

    @classmethod
    def from_pretrained(cls, model, path: str, n_prompt_tokens: int = 16) -> "SoftPromptModelForCausalLM":
        obj = cls(model, n_prompt_tokens=n_prompt_tokens)
        with SafeFile(os.path.join(path, SOFT_PROMPT_WEIGHTS_NAME)) as sf:
            obj.params["soft_prompt"] = jnp.asarray(sf.get_tensor("soft_prompt"))
        return obj


class PromptTrainer(Trainer):
    """Trains a PromptModelForClassification with CE over verbalized scores
    (reference PromptTrainer). Batches carry input_ids/attention_mask/
    mask_position/labels(int class index)."""

    def __init__(self, model: PromptModelForClassification = None, **kwargs):
        self.prompt_model = model
        super().__init__(model=model.model, **kwargs)

    def compute_loss(self, params, inputs: Dict[str, Any], dropout_rng=None):
        labels = inputs["labels"]
        scores = self.prompt_model.class_logits(
            params, inputs["input_ids"], inputs.get("attention_mask"), inputs["mask_position"]
        )
        logp = jax.nn.log_softmax(scores, axis=-1)
        return -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1).mean()
