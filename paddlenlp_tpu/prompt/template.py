"""Prompt templates (reference: paddlenlp/prompt/template.py — ManualTemplate /
SoftTemplate over PET-style format strings)."""

from __future__ import annotations

import re
from typing import Dict, List

__all__ = ["ManualTemplate"]


class ManualTemplate:
    """Hard-text template: ``"{'text': 'text_a'} It was {'mask'}."`` or a plain
    python format string with named fields + ``{mask}``."""

    def __init__(self, template: str, tokenizer, max_length: int = 128):
        self.template = template
        self.tokenizer = tokenizer
        self.max_length = max_length
        if tokenizer.mask_token is None:
            raise ValueError("template requires a tokenizer with a mask token")

    def render(self, example: Dict) -> str:
        text = self.template
        # PET-style {'text': 'field'} and {'mask'} pieces
        def sub(m):
            body = m.group(1)
            if "mask" in body:
                return self.tokenizer.mask_token
            f = re.search(r"'text'\s*:\s*'(\w+)'", body)
            if f:
                return str(example[f.group(1)])
            return m.group(0)

        text = re.sub(r"\{([^{}]*)\}", lambda m: sub(m) if ("'" in m.group(1) or m.group(1) == "mask")
                      else str(example.get(m.group(1), m.group(0))), text)
        return text

    def __call__(self, example: Dict) -> Dict:
        enc = self.tokenizer(self.render(example), max_length=self.max_length, truncation=True)
        ids = enc["input_ids"]
        mask_positions = [i for i, t in enumerate(ids) if t == self.tokenizer.mask_token_id]
        if not mask_positions:
            raise ValueError(f"template produced no mask token: {self.render(example)!r}")
        out = {"input_ids": ids, "attention_mask": enc.get("attention_mask", [1] * len(ids)),
               "mask_position": mask_positions[0]}
        if "label" in example:
            out["label"] = example["label"]
        return out
