"""Verbalizers (reference: paddlenlp/prompt/verbalizer.py — ManualVerbalizer:
label -> label words -> vocab logits aggregation)."""

from __future__ import annotations

from typing import Dict, List, Sequence

import jax.numpy as jnp
import numpy as np

__all__ = ["ManualVerbalizer"]


class ManualVerbalizer:
    """Maps each class label to one or more label words; class score = mean of
    the (first-token) vocab logits of its words at the mask position."""

    def __init__(self, label_words: Dict, tokenizer):
        self.labels = sorted(label_words)
        self.tokenizer = tokenizer
        self.word_ids: List[List[int]] = []
        for label in self.labels:
            words = label_words[label]
            words = [words] if isinstance(words, str) else list(words)
            ids = []
            for w in words:
                toks = tokenizer(w, add_special_tokens=False)["input_ids"]
                if not toks:
                    raise ValueError(f"label word {w!r} tokenizes to nothing")
                ids.append(toks[0])
            self.word_ids.append(ids)

    def label_index(self, label) -> int:
        return self.labels.index(label)

    def process_logits(self, mask_logits: jnp.ndarray) -> jnp.ndarray:
        """[B, vocab] logits at the mask position -> [B, n_labels] class scores."""
        cols = [jnp.mean(mask_logits[:, jnp.asarray(ids)], axis=-1) for ids in self.word_ids]
        return jnp.stack(cols, axis=-1)
