from .prompt_trainer import (  # noqa: F401
    PromptModelForClassification,
    PromptTrainer,
    SoftPromptModelForCausalLM,
)
from .template import ManualTemplate  # noqa: F401
from .verbalizer import ManualVerbalizer  # noqa: F401
