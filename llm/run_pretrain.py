"""Causal-LM pretraining entry point.

Counterpart of ``/root/reference/llm/run_pretrain.py`` (main :358): JSON/CLI config
-> tokenizer/config -> LlmMetaConfig bridge -> model -> mmap GPT dataset ->
Trainer. Launch: ``python llm/run_pretrain.py config.json`` or CLI flags.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlenlp_tpu.data import build_train_valid_test_datasets
from paddlenlp_tpu.trainer import PdArgumentParser, Trainer, TrainingArguments, get_last_checkpoint
from paddlenlp_tpu.transformers import AutoConfig, AutoModelForCausalLM, AutoTokenizer, LlmMetaConfig
from paddlenlp_tpu.utils.log import logger


@dataclass
class ModelArguments:
    model_name_or_path: str = field(default="__internal_testing__/tiny-random-llama")
    tokenizer_name_or_path: Optional[str] = None
    dtype: str = "bfloat16"
    from_scratch: bool = field(default=True, metadata={"help": "init weights instead of loading"})
    num_hidden_layers: Optional[int] = None
    vocab_size: Optional[int] = None


@dataclass
class DataArguments:
    input_dir: str = field(default="data", metadata={"help": "dir or prefix of .bin/.idx corpus"})
    data_prefix: Optional[List[str]] = field(default=None, metadata={"help": "[w1, prefix1, w2, prefix2...]"})
    split: str = "949,50,1"
    max_seq_length: int = 2048
    data_cache_dir: Optional[str] = None


@dataclass
class PreTrainingArguments(TrainingArguments):
    min_learning_rate: float = 1e-5
    decay_steps: int = 0


def create_pretrained_dataset(data_args: DataArguments, training_args: TrainingArguments, tokenizer=None):
    """reference run_pretrain.py:193."""
    train_samples = training_args.max_steps * training_args.global_train_batch_size
    eval_steps = max(training_args.eval_steps, 1)
    eval_samples = (
        (training_args.max_steps // eval_steps + 1) * training_args.global_eval_batch_size
        if training_args.evaluation_strategy != "no"
        else training_args.global_eval_batch_size
    )
    prefix = data_args.data_prefix or _resolve_prefix(data_args.input_dir)
    return build_train_valid_test_datasets(
        prefix,
        seq_length=data_args.max_seq_length,
        train_valid_test_num_samples=(train_samples, eval_samples, 0),
        splits_string=data_args.split,
        seed=training_args.seed,
        cache_dir=data_args.data_cache_dir,
    )


def _resolve_prefix(input_dir: str) -> str:
    if os.path.isfile(input_dir + ".bin"):
        return input_dir
    if os.path.isdir(input_dir):
        bins = [f[:-4] for f in os.listdir(input_dir) if f.endswith(".bin")]
        if len(bins) == 1:
            return os.path.join(input_dir, bins[0])
        if bins:
            raise ValueError(f"multiple corpora in {input_dir}; pass data_prefix with weights")
    raise FileNotFoundError(f"no .bin/.idx corpus found at {input_dir}")


def main():
    parser = PdArgumentParser((ModelArguments, DataArguments, PreTrainingArguments))
    model_args, data_args, training_args = parser.parse_args_into_dataclasses()

    tokenizer = None
    if model_args.tokenizer_name_or_path or not model_args.from_scratch:
        tokenizer = AutoTokenizer.from_pretrained(
            model_args.tokenizer_name_or_path or model_args.model_name_or_path
        )

    config = AutoConfig.from_pretrained(model_args.model_name_or_path)
    LlmMetaConfig.set_llm_config(config, training_args)
    if model_args.num_hidden_layers is not None:
        config.num_hidden_layers = model_args.num_hidden_layers
    if model_args.vocab_size is not None:
        config.vocab_size = model_args.vocab_size
    config.use_cache = False

    if model_args.from_scratch:
        model = AutoModelForCausalLM.from_config(
            config, dtype=model_args.dtype, param_dtype="float32", seed=training_args.seed
        )
    else:
        model = AutoModelForCausalLM.from_pretrained(
            model_args.model_name_or_path, config=config, dtype=model_args.dtype, param_dtype="float32"
        )
    logger.info(f"model: {type(model).__name__} ({model.num_parameters():,} params)")

    train_ds, valid_ds, _ = create_pretrained_dataset(data_args, training_args, tokenizer)

    trainer = Trainer(
        model=model,
        args=training_args,
        train_dataset=train_ds,
        eval_dataset=valid_ds,
        tokenizer=tokenizer,
    )

    checkpoint = training_args.resume_from_checkpoint
    if checkpoint is None and not training_args.overwrite_output_dir:
        checkpoint = get_last_checkpoint(training_args.output_dir)
    if training_args.do_train:
        result = trainer.train(resume_from_checkpoint=checkpoint)
        trainer.save_model()
        logger.info(f"training done: {result.metrics}")
    if training_args.do_eval:
        metrics = trainer.evaluate()
        logger.info(f"eval: {metrics}")
    return trainer


if __name__ == "__main__":
    main()
