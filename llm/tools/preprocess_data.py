"""Corpus preprocessing: text/jsonl -> .bin/.idx mmap dataset.

Counterpart of ``/root/reference/llm/tools/preprocess/create_pretraining_data.py``.

Usage:
    python llm/tools/preprocess_data.py --input corpus.jsonl --output_prefix data/corpus \
        --tokenizer_name_or_path <dir> [--json_key text] [--append_eos]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import numpy as np

from paddlenlp_tpu.data import MMapIndexedDatasetBuilder
from paddlenlp_tpu.transformers import AutoTokenizer
from paddlenlp_tpu.utils.log import logger


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", required=True, help="txt (one doc per line) or jsonl")
    ap.add_argument("--output_prefix", required=True)
    ap.add_argument("--tokenizer_name_or_path", required=True)
    ap.add_argument("--json_key", default="text")
    ap.add_argument("--append_eos", action="store_true")
    ap.add_argument("--dtype", default="uint16", choices=["uint16", "uint32", "int32"])
    args = ap.parse_args()

    tokenizer = AutoTokenizer.from_pretrained(args.tokenizer_name_or_path)
    if np.dtype(args.dtype).itemsize == 2 and tokenizer.vocab_size > 65535:
        logger.warning("vocab > 65535: forcing uint32 token storage")
        args.dtype = "uint32"
    builder = MMapIndexedDatasetBuilder(args.output_prefix, dtype=np.dtype(args.dtype))
    eos = tokenizer.eos_token_id
    t0, n_docs, n_tokens = time.time(), 0, 0
    with open(args.input) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            text = json.loads(line).get(args.json_key, "") if args.input.endswith((".json", ".jsonl")) else line
            if not text:
                continue
            ids = tokenizer.encode(text)
            if args.append_eos and eos is not None:
                ids = ids + [eos]
            builder.add_document(ids)
            n_docs += 1
            n_tokens += len(ids)
            if n_docs % 10000 == 0:
                logger.info(f"{n_docs} docs, {n_tokens} tokens ({n_tokens / (time.time() - t0):.0f} tok/s)")
    builder.finalize()
    logger.info(f"wrote {args.output_prefix}.bin/.idx: {n_docs} docs, {n_tokens} tokens")


if __name__ == "__main__":
    main()
