"""Inference predictors + CLI.

Counterpart of ``/root/reference/llm/predict/predictor.py`` (1725 LoC):
``PredictorArgument`` :54, the class ladder Dygraph/Static/Block predictors
:232-1023, ``create_predictor`` :1163, ``predict()`` :1620, ``benchmark()`` :1687.
TPU-native: "static graph export" is just jit (no to_static split), so the ladder
collapses to two predictors:

- ``EagerPredictor``  — training-side ``model.generate`` (jitted while_loop);
- ``BlockPredictor``  — the paged continuous-batching ``InferenceEngine``.
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass, field
from typing import List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

from paddlenlp_tpu.trainer import PdArgumentParser
from paddlenlp_tpu.transformers import AutoConfig, AutoModelForCausalLM, AutoTokenizer
from paddlenlp_tpu.utils.log import logger


@dataclass
class PredictorArgument:
    model_name_or_path: str = "facebook/llama-7b"
    dtype: str = "bfloat16"
    mode: str = field(default="block", metadata={"help": "eager | block (paged continuous batching)"})
    src_length: int = 1024
    max_length: int = 256
    batch_size: int = 4
    top_k: int = 0
    top_p: float = 0.7
    temperature: float = 0.95
    decode_strategy: str = field(default="sampling", metadata={"help": "sampling | greedy_search"})
    block_size: int = 16
    num_kv_blocks: int = 1024
    max_blocks_per_seq: int = 128
    cachekv_int8_type: Optional[str] = field(
        default=None,
        metadata={"help": "quantize the paged KV cache: 'dynamic' (int8) or 'fp8' "
                          "(reference predictor.py:775-791 cachekv_int8 knob)"})
    speculate_method: Optional[str] = field(
        default=None,
        metadata={"help": "speculative decoding: 'ngram' (prompt-lookup drafts, greedy "
                          "only) or 'draft_model' (small-model proposer; greedy OR plain "
                          "temperature sampling via rejection-sampling acceptance — the "
                          "reference's csrc/gpu/append_attn + top_p_sampling_reject path)"})
    speculate_max_draft_tokens: int = 4
    draft_model_name_or_path: Optional[str] = field(
        default=None, metadata={"help": "checkpoint for the draft model (speculate_method=draft_model)"})
    enable_prefix_cache: bool = field(
        default=True,
        metadata={"help": "share KV blocks across requests with a common prompt prefix "
                          "(refcounted blocks + copy-on-write; prefill runs only on the "
                          "uncached suffix). Disable to force full prefill per request."})
    prefill_chunk_tokens: Optional[int] = field(
        default=None,
        metadata={"help": "split prompt processing into chunks of at most this many "
                          "tokens, interleaved with decode tokens in ragged mixed "
                          "engine steps (256-512 is a good TPU range) — a long prompt "
                          "no longer stalls running decodes for its whole prefill. "
                          "None/0 = monolithic prefill."})
    mesh_shape: Optional[str] = field(
        default=None,
        metadata={"help": "shard the serving forward + KV pool over a device mesh: "
                          "'R,C' (dp x tp) or a bare tp degree 'T'. Weights/KV lay "
                          "out with NamedSharding on the tp axis and the jitted "
                          "steps compile with explicit in/out shardings — one "
                          "replica spans several chips (CPU smoke: "
                          "XLA_FLAGS=--xla_force_host_platform_device_count=N). "
                          "None = single device."})
    disagg_stages: Optional[str] = field(
        default=None,
        metadata={"help": "disaggregated prefill/decode serving: 'P,D' device counts "
                          "— prompt work runs on a P-device prefill stage, decode on "
                          "a D-device decode stage, KV blocks migrating between the "
                          "stage pools (mutually exclusive with --mesh_shape; CPU "
                          "smoke: XLA_FLAGS=--xla_force_host_platform_device_count="
                          "P+D). None = single-stage."})
    data_file: Optional[str] = None
    output_file: Optional[str] = None
    benchmark: bool = False
    apply_chat_template: bool = False
    lora_path: Optional[str] = None
    weight_quantize_algo: Optional[str] = field(
        default=None,
        metadata={"help": "weight-only serving quantization: wint8 | wint4 | fp8 "
                          "(fp8 = float8_e4m3fn weights + per-channel scales, the "
                          "XLA-native twin of the reference's cutlass fp8 GEMM)"})


class BasePredictor:
    def __init__(self, args: PredictorArgument, model=None, tokenizer=None):
        self.args = args
        self.tokenizer = tokenizer or AutoTokenizer.from_pretrained(args.model_name_or_path)
        self.tokenizer.padding_side = "left"
        if model is None:
            config = AutoConfig.from_pretrained(args.model_name_or_path)
            config.use_scan_layers = True
            model = AutoModelForCausalLM.from_pretrained(
                args.model_name_or_path, config=config, dtype=args.dtype, param_dtype=args.dtype
            )
            if args.lora_path:
                from paddlenlp_tpu.peft import LoRAModel

                model = LoRAModel.from_pretrained(model, args.lora_path).merge_and_unload()
        if args.weight_quantize_algo:
            from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel

            model = QuantizedModel(
                model, QuantizationConfig(weight_quantize_algo=args.weight_quantize_algo))
        self.model = model

    def _preprocess(self, texts: List[str]):
        if self.args.apply_chat_template and self.tokenizer.chat_template:
            texts = [
                self.tokenizer.apply_chat_template([{"role": "user", "content": t}]) for t in texts
            ]
        enc = self.tokenizer(texts, padding=True, truncation=True, max_length=self.args.src_length,
                             padding_side="left", return_tensors="np")
        return enc

    def _postprocess(self, token_lists: List[List[int]]) -> List[str]:
        return [self.tokenizer.decode(t, skip_special_tokens=True) for t in token_lists]


class EagerPredictor(BasePredictor):
    """reference DygraphPredictor (:232): plain model.generate."""

    def predict(self, texts: List[str]) -> List[str]:
        import jax.numpy as jnp

        enc = self._preprocess(texts)
        out, _ = self.model.generate(
            jnp.asarray(enc["input_ids"]),
            attention_mask=jnp.asarray(enc["attention_mask"]),
            max_new_tokens=self.args.max_length,
            do_sample=self.args.decode_strategy == "sampling",
            top_p=self.args.top_p,
            top_k=self.args.top_k,
            temperature=self.args.temperature,
        )
        return self._postprocess([np.asarray(o) for o in out])


class BlockPredictor(BasePredictor):
    """reference Dygraph/StaticBlockInferencePredictor (:953/:1023): paged engine."""

    def __init__(self, args: PredictorArgument, model=None, tokenizer=None):
        super().__init__(args, model, tokenizer)
        import jax.numpy as jnp

        from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams

        if args.speculate_method not in (None, "ngram", "draft_model"):
            raise ValueError(f"speculate_method={args.speculate_method!r} unsupported "
                             "(pick 'ngram' or 'draft_model')")
        if args.speculate_method == "draft_model" and args.decode_strategy == "sampling" \
                and (args.top_p < 1.0 or args.top_k):
            logger.warning(
                "speculate_method=draft_model with top_p<1.0/top_k>0: rejection-sampling "
                "acceptance only covers PLAIN temperature sampling, so speculation will "
                "be bypassed at runtime. Set --top_p 1.0 --top_k 0 (or greedy_search) "
                "to actually engage the draft model.")
        draft_model = None
        if args.speculate_method == "draft_model":
            if not args.draft_model_name_or_path:
                raise ValueError("speculate_method=draft_model needs --draft_model_name_or_path")
            from paddlenlp_tpu.transformers.auto import AutoModelForCausalLM as _Auto

            draft_model = _Auto.from_pretrained(args.draft_model_name_or_path,
                                                dtype=args.dtype, param_dtype=args.dtype)
        self.engine = InferenceEngine(
            self.model,
            tokenizer=self.tokenizer,
            max_batch_size=args.batch_size,
            block_size=args.block_size,
            num_blocks=args.num_kv_blocks,
            max_blocks_per_seq=args.max_blocks_per_seq,
            dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
            kv_cache_quant=self._kv_quant(args.cachekv_int8_type),
            enable_prefix_cache=args.enable_prefix_cache,
            prefill_chunk_tokens=args.prefill_chunk_tokens,
            mesh_shape=self._parse_mesh_shape(args.mesh_shape),
            disagg_stages=self._parse_disagg_stages(args.disagg_stages),
            use_speculative=args.speculate_method == "ngram",
            spec_draft_len=args.speculate_max_draft_tokens,
            draft_model=draft_model,
        )
        self._sampling = SamplingParams(
            max_new_tokens=args.max_length,
            do_sample=args.decode_strategy == "sampling",
            top_p=args.top_p,
            top_k=args.top_k,
            temperature=args.temperature,
        )

    @staticmethod
    def _parse_mesh_shape(raw: Optional[str]):
        """'R,C' -> (dp, tp); bare 'T' -> (1, T); None stays single-device."""
        if not raw:
            return None
        parts = [int(x) for x in str(raw).split(",")]
        if len(parts) == 1:
            parts = [1, parts[0]]
        if len(parts) != 2 or any(p < 1 for p in parts):
            raise ValueError(
                f"--mesh_shape must be 'T' or 'R,C' with positive degrees, got {raw!r}")
        return tuple(parts)

    @staticmethod
    def _parse_disagg_stages(raw: Optional[str]):
        """'P,D' -> (prefill_devices, decode_devices); None stays single-stage."""
        if not raw:
            return None
        parts = [int(x) for x in str(raw).split(",")]
        if len(parts) != 2 or any(p < 1 for p in parts):
            raise ValueError(
                f"--disagg_stages must be 'P,D' with positive device counts, got {raw!r}")
        return tuple(parts)

    @staticmethod
    def _kv_quant(cachekv_int8_type):
        if cachekv_int8_type is None:
            return None
        mapping = {"dynamic": "int8", "int8": "int8", "fp8": "fp8"}
        if cachekv_int8_type not in mapping:
            raise ValueError(
                f"cachekv_int8_type={cachekv_int8_type!r} unsupported; pick from "
                f"{sorted(mapping)} (the reference's 'static' calibrated scales are "
                "not implemented — dynamic per-token scales quantize at write time)")
        return mapping[cachekv_int8_type]

    def predict(self, texts: List[str]) -> List[str]:
        prompts = [self.tokenizer.encode(t)[-self.args.src_length:] for t in texts]
        outs = self.engine.generate(prompts, self._sampling)
        return self._postprocess(outs)

    def stream_predict(self, text: str):
        """Yield decoded text pieces as tokens land (serving path)."""
        import queue

        q: "queue.Queue" = queue.Queue()
        prompt = self.tokenizer.encode(text)[-self.args.src_length:]
        self.engine.add_request(prompt, self._sampling, stream_cb=lambda tok, done: q.put((tok, done)))
        toks: List[int] = []
        emitted = 0
        while True:
            while self.engine.has_work() and q.empty():
                self.engine.step()
            tok, done = q.get()
            toks.append(tok)
            text_so_far = self.tokenizer.decode(toks, skip_special_tokens=True)
            if len(text_so_far) > emitted:
                yield text_so_far[emitted:]
                emitted = len(text_so_far)
            if done:
                break


def create_predictor(args: PredictorArgument, model=None, tokenizer=None) -> BasePredictor:
    """reference create_predictor (:1163)."""
    if args.mode == "eager":
        return EagerPredictor(args, model, tokenizer)
    if args.mode == "block":
        return BlockPredictor(args, model, tokenizer)
    raise ValueError(f"unknown predictor mode {args.mode!r} (eager|block)")


def benchmark(predictor: BasePredictor, texts: List[str], warmup: int = 1, iters: int = 3):
    """reference benchmark (:1687): tokens/sec + latency stats."""
    for _ in range(warmup):
        predictor.predict(texts[: predictor.args.batch_size])
    t0 = time.time()
    n_tokens = 0
    for _ in range(iters):
        outs = predictor.predict(texts[: predictor.args.batch_size])
        n_tokens += sum(len(predictor.tokenizer.encode(o)) for o in outs)
    dt = time.time() - t0
    stats = {"output_tokens_per_second": round(n_tokens / dt, 2), "latency_s": round(dt / iters, 3)}
    logger.info(f"benchmark: {stats}")
    return stats


def main():
    parser = PdArgumentParser((PredictorArgument,))
    (args,) = parser.parse_args_into_dataclasses()
    predictor = create_predictor(args)
    if args.data_file:
        with open(args.data_file) as f:
            texts = [json.loads(line).get("src", "") for line in f if line.strip()]
    else:
        texts = ["hello"]
    if args.benchmark:
        benchmark(predictor, texts)
        return
    outputs = []
    bs = args.batch_size
    for i in range(0, len(texts), bs):
        outputs.extend(predictor.predict(texts[i : i + bs]))
    if args.output_file:
        with open(args.output_file, "w") as f:
            for src, out in zip(texts, outputs):
                f.write(json.dumps({"src": src, "output": out}, ensure_ascii=False) + "\n")
    else:
        for src, out in zip(texts, outputs):
            print(json.dumps({"src": src, "output": out}, ensure_ascii=False))


if __name__ == "__main__":
    main()
