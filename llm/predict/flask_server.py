"""Streaming HTTP chat server.

Counterpart of ``/root/reference/llm/predict/flask_server.py`` (235 LoC: streaming
HTTP on flask + the get_output SysV message queue). Stdlib-only (no flask in this
image): ``ThreadingHTTPServer`` + server-sent-event streaming straight from the
engine's token callbacks — the IPC hop disappears because the engine is in-process.

POST /generate  {"src": str, "max_length"?: int, "stream"?: bool}
GET  /health
"""

from __future__ import annotations

import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from paddlenlp_tpu.trainer import PdArgumentParser
from paddlenlp_tpu.utils.log import logger
from predictor import BlockPredictor, PredictorArgument, create_predictor


def make_handler(predictor, lock: threading.Lock):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug(fmt % args)

        def do_GET(self):
            if self.path == "/health":
                body = json.dumps({"status": "ok"}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

        def do_POST(self):
            if self.path != "/generate":
                self.send_response(404)
                self.end_headers()
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
                text = payload["src"]
            except (json.JSONDecodeError, KeyError) as e:
                body = json.dumps({"error": f"bad request: {e}"}).encode()
                self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            stream = bool(payload.get("stream", False))
            if "max_length" in payload:
                predictor.args.max_length = int(payload["max_length"])
            with lock:  # one generation at a time per engine (batching inside)
                if stream and isinstance(predictor, BlockPredictor):
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    for piece in predictor.stream_predict(text):
                        self.wfile.write(f"data: {json.dumps({'token': piece})}\n\n".encode())
                        self.wfile.flush()
                    self.wfile.write(b"data: [DONE]\n\n")
                else:
                    out = predictor.predict([text])[0]
                    body = json.dumps({"output": out}, ensure_ascii=False).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

    return Handler


def serve(predictor, port: int = 8011):
    server = ThreadingHTTPServer(("0.0.0.0", port), make_handler(predictor, threading.Lock()))
    logger.info(f"serving on :{port} (POST /generate)")
    server.serve_forever()


def serve_v1(predictor, port: int = 8011):
    """Serve through the continuous-batching runtime (paddlenlp_tpu.serving):
    concurrent requests share the engine's running batch instead of taking
    turns behind the legacy per-request lock; adds /v1/completions SSE
    streaming, admission control (429/503) and /metrics. Needs --mode block."""
    from paddlenlp_tpu.serving import SchedulerConfig, ServingServer

    if not isinstance(predictor, BlockPredictor):
        raise ValueError("--api v1 needs the paged engine: run with --mode block")
    server = ServingServer(
        predictor.engine,
        tokenizer=predictor.tokenizer,
        scheduler_config=SchedulerConfig(max_inflight=4 * predictor.args.batch_size),
        max_src_tokens=predictor.args.src_length,
    )
    server.run(port=port)


def main():
    parser = PdArgumentParser((PredictorArgument,))
    (args, remaining) = parser.parse_args_into_dataclasses(return_remaining_strings=True)
    port, api = 8011, "legacy"
    for i, r in enumerate(remaining):
        if r in ("--port", "--api"):
            if i + 1 >= len(remaining):
                raise SystemExit(f"{r} requires a value")
            if r == "--port":
                port = int(remaining[i + 1])
            else:
                api = remaining[i + 1]
    predictor = create_predictor(args)
    if api == "v1":
        serve_v1(predictor, port)
    else:
        serve(predictor, port)


if __name__ == "__main__":
    main()
