"""Streaming HTTP chat server.

Counterpart of ``/root/reference/llm/predict/flask_server.py`` (235 LoC: streaming
HTTP on flask + the get_output SysV message queue). Stdlib-only (no flask in this
image): ``ThreadingHTTPServer`` + server-sent-event streaming straight from the
engine's token callbacks — the IPC hop disappears because the engine is in-process.

POST /generate  {"src": str, "max_length"?: int, "stream"?: bool}
GET  /health
"""

from __future__ import annotations

import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from paddlenlp_tpu.trainer import PdArgumentParser
from paddlenlp_tpu.utils.log import logger
from predictor import BlockPredictor, PredictorArgument, create_predictor


def make_handler(predictor, lock: threading.Lock):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug(fmt % args)

        def do_GET(self):
            if self.path == "/health":
                body = json.dumps({"status": "ok"}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

        def do_POST(self):
            if self.path != "/generate":
                self.send_response(404)
                self.end_headers()
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
                text = payload["src"]
            except (json.JSONDecodeError, KeyError) as e:
                body = json.dumps({"error": f"bad request: {e}"}).encode()
                self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            stream = bool(payload.get("stream", False))
            if "max_length" in payload:
                predictor.args.max_length = int(payload["max_length"])
            with lock:  # one generation at a time per engine (batching inside)
                if stream and isinstance(predictor, BlockPredictor):
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    for piece in predictor.stream_predict(text):
                        self.wfile.write(f"data: {json.dumps({'token': piece})}\n\n".encode())
                        self.wfile.flush()
                    self.wfile.write(b"data: [DONE]\n\n")
                else:
                    out = predictor.predict([text])[0]
                    body = json.dumps({"output": out}, ensure_ascii=False).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

    return Handler


def serve(predictor, port: int = 8011):
    server = ThreadingHTTPServer(("0.0.0.0", port), make_handler(predictor, threading.Lock()))
    logger.info(f"serving on :{port} (POST /generate)")
    server.serve_forever()


def main():
    parser = PdArgumentParser((PredictorArgument,))
    (args, remaining) = parser.parse_args_into_dataclasses(return_remaining_strings=True)
    port = 8011
    for i, r in enumerate(remaining):
        if r == "--port":
            port = int(remaining[i + 1])
    serve(create_predictor(args), port)


if __name__ == "__main__":
    main()
