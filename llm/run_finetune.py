"""SFT / LoRA finetuning entry point.

Counterpart of ``/root/reference/llm/run_finetune.py`` (main :77): chat-template
tokenization, ZeroPadding packing (+ segment-mask attention = the flashmask path),
optional LoRA/prefix wrapping, Trainer.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass, field
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from paddlenlp_tpu.data import DataCollatorForSeq2Seq
from paddlenlp_tpu.datasets import ZeroPaddingMapDataset
from paddlenlp_tpu.trainer import PdArgumentParser, Trainer, TrainingArguments
from paddlenlp_tpu.transformers import AutoConfig, AutoModelForCausalLM, AutoTokenizer, LlmMetaConfig
from paddlenlp_tpu.utils.log import logger


@dataclass
class ModelArguments:
    model_name_or_path: str = "facebook/llama-7b"
    dtype: str = "bfloat16"
    # PEFT (reference run_finetune.py:437; peft/lora/lora_config.py)
    lora: bool = False
    lora_rank: int = 8
    lora_alpha: int = 16
    lora_dropout: float = 0.0
    lora_target_modules: Optional[List[str]] = None
    rslora: bool = False
    prefix_tuning: bool = False
    num_prefix_tokens: int = 64


@dataclass
class DataArguments:
    dataset_name_or_path: str = field(default="data", metadata={"help": "dir with train.json/dev.json (jsonl)"})
    max_length: int = 2048
    src_length: int = 1024
    zero_padding: bool = True
    eval_with_do_generation: bool = False


def load_sft_dataset(path: str, tokenizer, data_args: DataArguments):
    """jsonl rows {src,tgt} or {messages:[...]} -> token dicts with masked prompts
    (reference llm/utils/data.py tokenization)."""
    examples = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if "messages" in row:
                text = tokenizer.apply_chat_template(row["messages"], add_generation_prompt=False)
                ids = tokenizer.encode(text)[: data_args.max_length]
                labels = list(ids)
            else:
                src = tokenizer.encode(str(row.get("src", row.get("instruction", ""))))[: data_args.src_length]
                tgt = tokenizer.encode(str(row.get("tgt", row.get("output", ""))))
                eos = tokenizer.eos_token_id
                tgt = (tgt + ([eos] if eos is not None else []))[: data_args.max_length - len(src)]
                ids = src + tgt
                labels = [-100] * len(src) + list(tgt)  # prompt tokens excluded from loss
            examples.append({
                "input_ids": np.asarray(ids, dtype=np.int32),
                "labels": np.asarray(labels, dtype=np.int32),
            })
    return examples


class ListDataset:
    def __init__(self, rows):
        self.rows = rows

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        return self.rows[i]


def main():
    parser = PdArgumentParser((ModelArguments, DataArguments, TrainingArguments))
    model_args, data_args, training_args = parser.parse_args_into_dataclasses()

    tokenizer = AutoTokenizer.from_pretrained(model_args.model_name_or_path)
    config = AutoConfig.from_pretrained(model_args.model_name_or_path)
    LlmMetaConfig.set_llm_config(config, training_args)
    model = AutoModelForCausalLM.from_pretrained(
        model_args.model_name_or_path, config=config, dtype=model_args.dtype, param_dtype="float32"
    )

    if model_args.lora:
        from paddlenlp_tpu.peft import LoRAConfig, LoRAModel

        lora_config = LoRAConfig(
            r=model_args.lora_rank,
            lora_alpha=model_args.lora_alpha,
            lora_dropout=model_args.lora_dropout,
            target_modules=model_args.lora_target_modules,
            rslora=model_args.rslora,
        )
        model = LoRAModel(model, lora_config)
        model.mark_only_lora_as_trainable()
        model.print_trainable_parameters()
    elif model_args.prefix_tuning:
        from paddlenlp_tpu.peft import PrefixConfig, PrefixModelForCausalLM

        model = PrefixModelForCausalLM(model, PrefixConfig(num_prefix_tokens=model_args.num_prefix_tokens))

    train_rows = load_sft_dataset(os.path.join(data_args.dataset_name_or_path, "train.json"), tokenizer, data_args)
    dev_path = os.path.join(data_args.dataset_name_or_path, "dev.json")
    eval_rows = load_sft_dataset(dev_path, tokenizer, data_args) if os.path.isfile(dev_path) else None

    if data_args.zero_padding:
        train_ds = ZeroPaddingMapDataset(ListDataset(train_rows), tokenizer, data_args.max_length)
        eval_ds = ZeroPaddingMapDataset(ListDataset(eval_rows), tokenizer, data_args.max_length) if eval_rows else None
        collator = None  # packed rows are already fixed-length
    else:
        train_ds, eval_ds = ListDataset(train_rows), ListDataset(eval_rows) if eval_rows else None
        collator = DataCollatorForSeq2Seq(tokenizer, pad_to_multiple_of=8)

    trainer = Trainer(
        model=model,
        args=training_args,
        train_dataset=train_ds,
        eval_dataset=eval_ds,
        tokenizer=tokenizer,
        data_collator=collator,
    )
    if training_args.do_train:
        result = trainer.train(resume_from_checkpoint=training_args.resume_from_checkpoint)
        trainer.save_model()
        logger.info(f"finetune done: {result.metrics}")
    if training_args.do_eval and eval_ds is not None:
        logger.info(f"eval: {trainer.evaluate()}")
    return trainer


if __name__ == "__main__":
    main()
