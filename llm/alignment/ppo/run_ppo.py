"""PPO alignment entry point (reference: /root/reference/llm/alignment/ppo/run_ppo.py).

Data: jsonl rows {"src": prompt}. The reward comes from a trained reward model
checkpoint (sequence-classification head, see run_rm.py); the value model is
initialized from the policy backbone when ``use_value_model`` is on.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass, field
from typing import Optional

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from data_utils import ListDataset

from paddlenlp_tpu.trainer import PdArgumentParser, TrainingArguments
from paddlenlp_tpu.transformers import AutoConfig, AutoModelForCausalLM, AutoTokenizer, LlmMetaConfig
from paddlenlp_tpu.transformers.auto.modeling import AutoModelForSequenceClassification
from paddlenlp_tpu.trl import PPOConfig, PPOTrainer
from paddlenlp_tpu.utils.log import logger


@dataclass
class ModelArguments:
    model_name_or_path: str = "facebook/llama-7b"
    reward_model_name_or_path: Optional[str] = None
    ref_model_name_or_path: Optional[str] = None
    dtype: str = "bfloat16"


@dataclass
class PPOArguments:
    dataset_name_or_path: str = "data"
    max_prompt_length: int = 512
    max_new_tokens: int = 128
    num_rollouts_per_prompt: int = 4
    temperature: float = 1.0
    top_p: float = 1.0
    clip_ratio: float = 0.2
    kl_coef: float = 0.05
    ppo_epochs: int = 1
    entropy_coef: float = 0.0
    use_value_model: bool = field(
        default=False,
        metadata={"help": "train a value model with GAE (the reference quartet) "
                          "instead of the group-relative baseline"})
    gamma: float = 1.0
    gae_lambda: float = 0.95
    value_lr: float = 1e-5


def load_prompt_dataset(path: str, tokenizer, ppo_args: PPOArguments):
    rows = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            r = json.loads(line)
            ids = tokenizer.encode(str(r["src"]))[: ppo_args.max_prompt_length]
            rows.append({"input_ids": np.asarray(ids, np.int32)})
    return rows


def main():
    parser = PdArgumentParser((ModelArguments, PPOArguments, TrainingArguments))
    model_args, ppo_args, training_args = parser.parse_args_into_dataclasses()

    tokenizer = AutoTokenizer.from_pretrained(model_args.model_name_or_path)
    config = AutoConfig.from_pretrained(model_args.model_name_or_path)
    config.use_scan_layers = True  # rollout through the paged engine
    LlmMetaConfig.set_llm_config(config, training_args)
    model = AutoModelForCausalLM.from_pretrained(
        model_args.model_name_or_path, config=config, dtype=model_args.dtype, param_dtype="float32"
    )
    ref_model = None
    if model_args.ref_model_name_or_path:
        ref_model = AutoModelForCausalLM.from_pretrained(
            model_args.ref_model_name_or_path, config=config, dtype=model_args.dtype,
            param_dtype="float32",
        )
    if not model_args.reward_model_name_or_path:
        raise ValueError("run_ppo.py requires --reward_model_name_or_path (train one with run_rm.py)")
    reward_model = AutoModelForSequenceClassification.from_pretrained(
        model_args.reward_model_name_or_path, dtype=model_args.dtype, param_dtype="float32"
    )

    rows = load_prompt_dataset(
        os.path.join(ppo_args.dataset_name_or_path, "train.json"), tokenizer, ppo_args
    )
    ppo_config = PPOConfig(
        num_rollouts_per_prompt=ppo_args.num_rollouts_per_prompt,
        max_new_tokens=ppo_args.max_new_tokens,
        max_prompt_length=ppo_args.max_prompt_length,
        temperature=ppo_args.temperature,
        top_p=ppo_args.top_p,
        clip_ratio=ppo_args.clip_ratio,
        kl_coef=ppo_args.kl_coef,
        ppo_epochs=ppo_args.ppo_epochs,
        entropy_coef=ppo_args.entropy_coef,
        use_value_model=ppo_args.use_value_model,
        gamma=ppo_args.gamma,
        gae_lambda=ppo_args.gae_lambda,
        value_lr=ppo_args.value_lr,
    )
    trainer = PPOTrainer(
        model=model,
        ref_model=ref_model,
        reward_model=reward_model,
        args=training_args,
        train_dataset=ListDataset(rows),
        tokenizer=tokenizer,
        ppo_config=ppo_config,
    )
    if training_args.do_train:
        result = trainer.train(resume_from_checkpoint=training_args.resume_from_checkpoint)
        trainer.save_model()
        logger.info(f"ppo done: {result.metrics}")
    return trainer


if __name__ == "__main__":
    main()
