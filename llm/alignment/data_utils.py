"""Shared data plumbing for the alignment entry points (dpo/rm/ppo).

One copy of the jsonl preference loader ({"src", "chosen", "rejected"} rows)
and the list-backed dataset the three run_*.py scripts feed their trainers.
"""

from __future__ import annotations

import json

import numpy as np

__all__ = ["ListDataset", "load_preference_rows"]


class ListDataset:
    def __init__(self, rows):
        self.rows = rows

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        return self.rows[i]


def load_preference_rows(path: str, tokenizer, max_length: int, max_prompt_length: int,
                         mode: str = "dpo"):
    """jsonl {"src", "chosen", "rejected"} -> per-pair token rows.

    mode="dpo": chosen/rejected input_ids + prompt-masked labels (DPOTrainer).
    mode="rm":  chosen/rejected input_ids + attention masks (RewardTrainer).
    Prompts are clamped so a long prompt can never push a row past
    ``max_length`` (a negative pad width crashed the old per-script loaders).
    """
    if mode not in ("dpo", "rm"):
        raise ValueError(f"mode must be dpo|rm, got {mode!r}")
    prompt_cap = min(max_prompt_length, max_length - 1)  # always leaves >=1 response slot
    eos = [tokenizer.eos_token_id] if tokenizer.eos_token_id is not None else []
    rows = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            r = json.loads(line)
            prompt = tokenizer.encode(str(r["src"]))[:prompt_cap]

            def build(resp):
                resp_ids = (tokenizer.encode(str(resp)) + eos)[: max_length - len(prompt)]
                ids = np.asarray(prompt + resp_ids, dtype=np.int32)
                pad = max_length - len(ids)
                if mode == "dpo":
                    labels = np.asarray([-100] * len(prompt) + resp_ids, dtype=np.int32)
                    return (np.pad(ids, (0, pad)), np.pad(labels, (0, pad), constant_values=-100))
                mask = np.concatenate([np.ones(len(ids), np.int32), np.zeros(pad, np.int32)])
                return (np.pad(ids, (0, pad)), mask)

            c0, c1 = build(r["chosen"])
            r0, r1 = build(r["rejected"])
            if mode == "dpo":
                rows.append({"chosen_input_ids": c0, "chosen_labels": c1,
                             "rejected_input_ids": r0, "rejected_labels": r1})
            else:
                rows.append({"chosen_input_ids": c0, "chosen_attention_mask": c1,
                             "rejected_input_ids": r0, "rejected_attention_mask": r1})
    return rows
