"""DPO alignment entry point (reference: /root/reference/llm/alignment/dpo/run_dpo.py :58).

Data: jsonl rows {"src": prompt, "chosen": ..., "rejected": ...}.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass, field
from typing import Optional

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from data_utils import ListDataset, load_preference_rows

from paddlenlp_tpu.trainer import PdArgumentParser, TrainingArguments
from paddlenlp_tpu.transformers import AutoConfig, AutoModelForCausalLM, AutoTokenizer, LlmMetaConfig
from paddlenlp_tpu.trl import DPOCriterion, DPOTrainer
from paddlenlp_tpu.utils.log import logger


@dataclass
class ModelArguments:
    model_name_or_path: str = "facebook/llama-7b"
    ref_model_name_or_path: Optional[str] = None
    dtype: str = "bfloat16"


@dataclass
class DPOArguments:
    dataset_name_or_path: str = "data"
    max_length: int = 1024
    max_prompt_length: int = 512
    beta: float = 0.1
    loss_type: str = "sigmoid"
    label_smoothing: float = 0.0
    simpo_gamma: float = 0.5
    sft_loss_ratio: float = 0.0


def main():
    parser = PdArgumentParser((ModelArguments, DPOArguments, TrainingArguments))
    model_args, dpo_args, training_args = parser.parse_args_into_dataclasses()

    tokenizer = AutoTokenizer.from_pretrained(model_args.model_name_or_path)
    config = AutoConfig.from_pretrained(model_args.model_name_or_path)
    LlmMetaConfig.set_llm_config(config, training_args)
    model = AutoModelForCausalLM.from_pretrained(
        model_args.model_name_or_path, config=config, dtype=model_args.dtype, param_dtype="float32"
    )
    ref_model = None
    if model_args.ref_model_name_or_path:
        ref_model = AutoModelForCausalLM.from_pretrained(
            model_args.ref_model_name_or_path, dtype=model_args.dtype, param_dtype="float32"
        )

    rows = load_preference_rows(
        os.path.join(dpo_args.dataset_name_or_path, "train.json"), tokenizer,
        dpo_args.max_length, dpo_args.max_prompt_length, mode="dpo",
    )
    eval_dataset = None
    dev_path = os.path.join(dpo_args.dataset_name_or_path, "dev.json")
    if os.path.isfile(dev_path):
        eval_dataset = ListDataset(load_preference_rows(
            dev_path, tokenizer, dpo_args.max_length, dpo_args.max_prompt_length, mode="dpo"))
    elif training_args.do_eval or training_args.evaluation_strategy != "no":
        logger.warning(f"no dev.json under {dpo_args.dataset_name_or_path}; disabling evaluation")
        training_args.do_eval = False
        training_args.evaluation_strategy = "no"
    criterion = DPOCriterion(
        beta=dpo_args.beta,
        loss_type=dpo_args.loss_type,
        label_smoothing=dpo_args.label_smoothing,
        simpo_gamma=dpo_args.simpo_gamma,
        sft_loss_ratio=dpo_args.sft_loss_ratio,
    )
    trainer = DPOTrainer(
        model=model,
        ref_model=ref_model,
        dpo_criterion=criterion,
        args=training_args,
        train_dataset=ListDataset(rows),
        eval_dataset=eval_dataset,
        tokenizer=tokenizer,
    )
    if training_args.do_train:
        result = trainer.train(resume_from_checkpoint=training_args.resume_from_checkpoint)
        trainer.save_model()
        logger.info(f"dpo done: {result.metrics}")
    return trainer


if __name__ == "__main__":
    main()
