"""Reward-model training entry point (reference: /root/reference/llm/alignment/rm/).

Data: jsonl rows {"src": prompt, "chosen": ..., "rejected": ...} — the same
preference format as DPO; the reward model is a sequence-classification head
(num_labels=1) trained with the pairwise Bradley-Terry loss.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass
from typing import Optional

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from data_utils import ListDataset, load_preference_rows

from paddlenlp_tpu.trainer import PdArgumentParser, TrainingArguments
from paddlenlp_tpu.transformers import AutoConfig, AutoTokenizer, LlmMetaConfig
from paddlenlp_tpu.transformers.auto.modeling import AutoModelForSequenceClassification
from paddlenlp_tpu.trl import RewardTrainer
from paddlenlp_tpu.utils.log import logger


@dataclass
class ModelArguments:
    model_name_or_path: str = "facebook/llama-7b"
    dtype: str = "bfloat16"


@dataclass
class RMArguments:
    dataset_name_or_path: str = "data"
    max_length: int = 1024
    max_prompt_length: int = 512


def main():
    parser = PdArgumentParser((ModelArguments, RMArguments, TrainingArguments))
    model_args, rm_args, training_args = parser.parse_args_into_dataclasses()

    tokenizer = AutoTokenizer.from_pretrained(model_args.model_name_or_path)
    config = AutoConfig.from_pretrained(model_args.model_name_or_path)
    config.num_labels = 1
    LlmMetaConfig.set_llm_config(config, training_args)
    model = AutoModelForSequenceClassification.from_pretrained(
        model_args.model_name_or_path, config=config, dtype=model_args.dtype, param_dtype="float32"
    )
    rows = load_preference_rows(
        os.path.join(rm_args.dataset_name_or_path, "train.json"), tokenizer,
        rm_args.max_length, rm_args.max_prompt_length, mode="rm",
    )
    trainer = RewardTrainer(
        model=model,
        args=training_args,
        train_dataset=ListDataset(rows),
        tokenizer=tokenizer,
    )
    if training_args.do_train:
        result = trainer.train(resume_from_checkpoint=training_args.resume_from_checkpoint)
        trainer.save_model()
        logger.info(f"rm done: {result.metrics}")
    return trainer


if __name__ == "__main__":
    main()
