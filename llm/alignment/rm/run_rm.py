"""Reward-model training entry point (reference: /root/reference/llm/alignment/rm/).

Data: jsonl rows {"src": prompt, "chosen": ..., "rejected": ...} — the same
preference format as DPO; the reward model is a sequence-classification head
(num_labels=1) trained with the pairwise Bradley-Terry loss.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass
from typing import Optional

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", ".."))

import numpy as np

from paddlenlp_tpu.trainer import PdArgumentParser, TrainingArguments
from paddlenlp_tpu.transformers import AutoConfig, AutoTokenizer, LlmMetaConfig
from paddlenlp_tpu.transformers.auto.modeling import AutoModelForSequenceClassification
from paddlenlp_tpu.trl import RewardTrainer
from paddlenlp_tpu.utils.log import logger


@dataclass
class ModelArguments:
    model_name_or_path: str = "facebook/llama-7b"
    dtype: str = "bfloat16"


@dataclass
class RMArguments:
    dataset_name_or_path: str = "data"
    max_length: int = 1024
    max_prompt_length: int = 512


def load_pairwise_dataset(path: str, tokenizer, rm_args: RMArguments):
    rows = []
    max_len = rm_args.max_length
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            r = json.loads(line)
            prompt = tokenizer.encode(str(r["src"]))[: rm_args.max_prompt_length]
            eos = [tokenizer.eos_token_id] if tokenizer.eos_token_id is not None else []

            def build(resp):
                resp_ids = (tokenizer.encode(str(resp)) + eos)[: max_len - len(prompt)]
                ids = np.asarray(prompt + resp_ids, dtype=np.int32)
                pad = max_len - len(ids)
                mask = np.concatenate([np.ones(len(ids), np.int32), np.zeros(pad, np.int32)])
                return np.pad(ids, (0, pad)), mask

            ci, cm = build(r["chosen"])
            ri, rm_ = build(r["rejected"])
            rows.append({"chosen_input_ids": ci, "chosen_attention_mask": cm,
                         "rejected_input_ids": ri, "rejected_attention_mask": rm_})
    return rows


class ListDataset:
    def __init__(self, rows):
        self.rows = rows

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        return self.rows[i]


def main():
    parser = PdArgumentParser((ModelArguments, RMArguments, TrainingArguments))
    model_args, rm_args, training_args = parser.parse_args_into_dataclasses()

    tokenizer = AutoTokenizer.from_pretrained(model_args.model_name_or_path)
    config = AutoConfig.from_pretrained(model_args.model_name_or_path)
    config.num_labels = 1
    LlmMetaConfig.set_llm_config(config, training_args)
    model = AutoModelForSequenceClassification.from_pretrained(
        model_args.model_name_or_path, config=config, dtype=model_args.dtype, param_dtype="float32"
    )
    rows = load_pairwise_dataset(
        os.path.join(rm_args.dataset_name_or_path, "train.json"), tokenizer, rm_args
    )
    trainer = RewardTrainer(
        model=model,
        args=training_args,
        train_dataset=ListDataset(rows),
        tokenizer=tokenizer,
    )
    if training_args.do_train:
        result = trainer.train(resume_from_checkpoint=training_args.resume_from_checkpoint)
        trainer.save_model()
        logger.info(f"rm done: {result.metrics}")
    return trainer


if __name__ == "__main__":
    main()
