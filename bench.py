"""Benchmark: LLaMA pretraining step throughput on the attached TPU chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
Always — even when the TPU backend is wedged or the run times out, a structured
failure record (value 0, "error" field) is emitted instead of a traceback.

Architecture: the top-level process never imports jax. It (1) probes the
backend with a tiny matmul in a subprocess under a hard timeout (a hung TPU
tunnel cannot block `subprocess.run(timeout=...)`), retrying across the whole
PROBE_BUDGET_S window since tunnel outages are transient, then (2) runs the
real benchmark in a second subprocess under its own timeout (one mid-run
retry) and relays the JSON line. jax's `block_until_ready` on a wedged backend
hangs uninterruptibly in-process; process isolation is the only reliable
watchdog. Successful real-TPU measurements persist to BENCH_LASTGOOD.json and
are embedded (labeled stale) in any later failure record.

Baseline: the reference's published LLaMA-7B pretrain number — 3754.73
tokens/card/sec on A100-80G (llm/docs/pretrain.rst:188, BASELINE.md), which is
~52.5% MFU (6*6.7e9*3754.7 / 312e12). A single v5e chip (197 bf16 TFLOP/s,
16 GB) cannot hold 7B training state, so the comparison is MFU-normalized: we
run a ~350M-param LLaMA at seq 2048 and report achieved MFU;
vs_baseline = our_MFU / 0.525.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time

METRIC = "llama350m_pretrain_mfu"
UNIT = "model_flops_utilization (vs A100 llama7b baseline MFU 0.525)"
PROBE_TIMEOUT_S = float(os.environ.get("PDNLP_BENCH_PROBE_TIMEOUT", 75))
# Total wall budget for the probe phase: attempts are spread across this window
# (VERDICT r3: 2 probes ~190s apart lost a tunnel that came back 40 min later).
PROBE_BUDGET_S = float(os.environ.get("PDNLP_BENCH_PROBE_BUDGET", 1500))
PROBE_RETRY_SLEEP_S = float(os.environ.get("PDNLP_BENCH_PROBE_SLEEP", 90))
RUN_TIMEOUT_S = float(os.environ.get("PDNLP_BENCH_RUN_TIMEOUT", 1500))
LASTGOOD_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_LASTGOOD.json")


def _read_last_good() -> dict | None:
    """Last real TPU measurement, persisted across rounds (BENCH_LASTGOOD.json).

    A transient tunnel wedge at bench time must not erase real data: the record
    is embedded (clearly labeled stale) in failure output; the round value
    stays 0.0."""
    try:
        with open(LASTGOOD_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_last_good(rec: dict) -> None:
    import datetime

    keep = {k: rec[k] for k in ("metric", "value", "tokens_per_second_per_chip", "device") if k in rec}
    keep["measured_at"] = datetime.datetime.now(datetime.timezone.utc).isoformat()
    try:
        with open(LASTGOOD_PATH, "w") as f:
            json.dump(keep, f)
    except OSError:
        pass


def _fail(reason: str, extra: dict | None = None) -> None:
    last_good = _read_last_good()
    record = {
        "metric": METRIC,
        "value": 0.0,
        "unit": UNIT,
        "vs_baseline": 0.0,
        **(extra or {}),
        "error": reason[:2000],
    }
    if last_good:
        record["stale_last_good"] = {**last_good, "stale": True}
    print(json.dumps(record))
    sys.exit(1)


def _force_platform_if_requested() -> None:
    """Make JAX_PLATFORMS=cpu effective despite the axon sitecustomize.

    The sitecustomize registers the axon PJRT plugin at interpreter start, so
    the env var alone is not enough — the in-process config update is.
    """
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def probe() -> None:
    """Tiny-op backend probe: compile + run a 256x256 matmul, print device."""
    _force_platform_if_requested()
    import jax
    import jax.numpy as jnp

    x = jnp.ones((256, 256), dtype=jnp.bfloat16)
    y = jax.jit(lambda a: a @ a)(x)
    jax.block_until_ready(y)
    print(json.dumps({"ok": True, "device": str(jax.devices()[0])}))


def run_bench(tiny: bool) -> None:
    _force_platform_if_requested()
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
    from paddlenlp_tpu.utils.env import device_peak_flops

    use_flash = "--no-flash" not in sys.argv

    if tiny:
        config = LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=512,
            use_flash_attention=use_flash,
        )
        batch, seq_len, steps = 2, 256, 3
    else:
        # scan-stacked layers (the default) keep the HLO small: one traced layer
        # body regardless of depth — large unrolled compiles once wedged the
        # axon relay, scan avoids that class of failure entirely.
        # recompute_granularity: the v5e-lite chip has 16 GB HBM. "full" remat
        # (save only layer boundaries) is the safe default; the save_only_*
        # tiers (save_core_attn / save_qkv_attn / save_attn_mlp) keep a few
        # named activations to cut backward recompute — sweepable via
        # PDNLP_BENCH_REMAT (see --sweep). MFU is accounted on the useful 6N
        # FLOPs, so remat overhead shows up as (honestly) lower reported MFU.
        remat = os.environ.get("PDNLP_BENCH_REMAT", "full")
        use_scan = os.environ.get("PDNLP_BENCH_SCAN", "1") != "0"
        config = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816, num_hidden_layers=24,
            num_attention_heads=16, num_key_value_heads=16, max_position_embeddings=4096,
            recompute=remat != "none", recompute_granularity=remat if remat != "none" else "full",
            use_flash_attention=use_flash, use_scan_layers=use_scan,
        )
        batch, seq_len, steps = 8, 2048, 10

    from paddlenlp_tpu.ops.cross_entropy import fused_linear_cross_entropy
    from paddlenlp_tpu.transformers.llama.modeling import LlamaModule

    def mark(msg):
        print(f"[bench] {time.time():.0f} {msg}", file=sys.stderr, flush=True)

    mark("init weights")
    model = LlamaForCausalLM(config, dtype=jnp.bfloat16, param_dtype=jnp.float32)
    params = model.init_weights(seed=0)
    n_params = model.num_parameters()

    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(3e-4))
    opt_state = jax.jit(tx.init)(params)
    mark(f"params ready n={n_params}")

    backbone = LlamaModule(config, dtype=jnp.bfloat16, param_dtype=jnp.float32)

    def loss_fn(params, ids):
        # backbone-only forward + fused head/CE: full [B,T,V] logits never
        # materialize (the 16GB-HBM cliff at B8/T2048/V32k)
        h = backbone.apply(
            {"params": params["model"]}, input_ids=ids[:, :-1], deterministic=True
        ).last_hidden_state
        loss, _ = fused_linear_cross_entropy(h, params["lm_head"]["kernel"], ids[:, 1:])
        return loss

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, config.vocab_size, (batch, seq_len + 1)), dtype=jnp.int32)

    # warmup / compile. NOTE: the axon relay's block_until_ready returns
    # before execution completes (measured: 10 full steps "finished" in 10ms);
    # only an actual value transfer (float()) is a reliable fence.
    mark("compiling train_step")
    params, opt_state, loss = train_step(params, opt_state, ids)
    float(loss)
    mark("compiled; timing")

    trace_dir = os.environ.get("PDNLP_BENCH_TRACE", "")
    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    t0 = time.time()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, ids)
    float(loss)
    dt = time.time() - t0
    if trace_dir:
        jax.profiler.stop_trace()
    mark(f"done dt={dt:.2f}s")

    tokens = batch * seq_len * steps
    tok_per_sec = tokens / dt
    # 6N matmul + attention FLOPs (causal: halved)
    attn_flops = 6 * config.num_hidden_layers * config.num_attention_heads * config.head_dim * seq_len
    flops_per_token = 6.0 * n_params + attn_flops
    peak = device_peak_flops() or 197e12
    mfu = tok_per_sec * flops_per_token / peak
    baseline_mfu = 0.525
    result = {
        "metric": METRIC,
        "value": round(mfu, 4),
        "unit": UNIT,
        "vs_baseline": round(mfu / baseline_mfu, 4),
        "tokens_per_second_per_chip": round(tok_per_sec, 1),
        "n_params": n_params,
        "seq_len": seq_len,
        "device": str(jax.devices()[0]),
        "loss": float(loss),
    }
    print(json.dumps(result))


def _spawn(argv: list[str], timeout: float, env: dict | None = None) -> tuple[int, str, str]:
    merged = {**os.environ, **(env or {})}
    if merged.get("JAX_PLATFORMS") == "cpu" and merged.get("PYTHONPATH"):
        # a wedged tunnel can BLOCK jax init even under JAX_PLATFORMS=cpu (the
        # axon plugin registers at discovery): drop its site dir for cpu runs
        merged["PYTHONPATH"] = os.pathsep.join(
            p for p in merged["PYTHONPATH"].split(os.pathsep) if "axon" not in p)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *argv],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=merged,
        )
        return proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        return -1, out, err + f"\n[timeout after {timeout}s]"


def _json_line(out: str) -> str:
    for candidate in reversed(out.strip().splitlines()):
        if candidate.startswith("{"):
            return candidate
    return ""


def _cpu_diag() -> float:
    """Tiny CPU-path run, invoked only on failure paths: a trendable
    tokens/sec number for rounds where the TPU tunnel is wedged (VERDICT r2:
    two rounds logged no signal; ADVICE r3: don't pay its latency on success)."""
    rc, out, _ = _spawn(["--run", "--tiny"], 600, env={"JAX_PLATFORMS": "cpu"})
    line = _json_line(out)
    if rc == 0 and line:
        try:
            return float(json.loads(line).get("tokens_per_second_per_chip", 0.0))
        except (ValueError, KeyError):
            return 0.0
    return 0.0


def main() -> None:
    tiny = "--tiny" in sys.argv

    # 1. backend probe: keep retrying across the whole probe budget — tunnel
    #    outages are transient (r3: wedged at 14:25Z, bench ran at 16:45Z).
    t_start = time.time()
    attempt = 0
    probe_ok = False
    rc, out, err = -1, "", "no probe attempt ran (PROBE_BUDGET_S <= 0?)"
    while time.time() - t_start < PROBE_BUDGET_S:
        attempt += 1
        rc, out, err = _spawn(["--probe"], PROBE_TIMEOUT_S)
        if rc == 0:
            probe_ok = True
            break
        remaining = PROBE_BUDGET_S - (time.time() - t_start)
        print(
            f"[bench] probe attempt {attempt} failed rc={rc}; {remaining:.0f}s of budget left",
            file=sys.stderr, flush=True,
        )
        if remaining > PROBE_RETRY_SLEEP_S:
            time.sleep(PROBE_RETRY_SLEEP_S)
        else:
            break
    if not probe_ok:
        extra = {"probe_attempts": attempt, "cpu_tokens_per_sec": _cpu_diag()}
        tail = "\n".join((out.strip().splitlines() + err.strip().splitlines())[-6:])
        _fail(f"backend probe failed rc={rc}: {tail}", extra)

    # 2. real benchmark, one retry if the tunnel wedges mid-run
    argv = ["--run"] + (["--tiny"] if tiny else [])
    for run_attempt in range(2):
        rc, out, err = _spawn(argv, RUN_TIMEOUT_S)
        line = _json_line(out)
        if rc == 0 and line:
            try:
                rec = json.loads(line)
            except ValueError:
                if run_attempt == 0:
                    time.sleep(30)
                    continue
                _fail(
                    f"bench subprocess printed unparseable result line: {line[:500]}",
                    {"cpu_tokens_per_sec": _cpu_diag()},
                )
            if rec.get("value", 0) > 0 and "cpu" not in rec.get("device", "").lower():
                # only real-TPU measurements become the stale-fallback record
                _write_last_good(rec)
            print(json.dumps(rec))
            return
        if run_attempt == 0:
            print(f"[bench] run attempt 1 failed rc={rc}; retrying once", file=sys.stderr, flush=True)
            time.sleep(30)
    tail = "\n".join((out.strip().splitlines() + err.strip().splitlines())[-8:])
    _fail(f"bench run failed rc={rc}: {tail}", {"cpu_tokens_per_sec": _cpu_diag()})


def sweep() -> None:
    """Hardware tuning sweep: run the full bench across (remat, scan, flash
    blocks) configs, appending each result to BENCH_SWEEP.jsonl. Resumable —
    configs already recorded (ok or failed) are skipped. Budget-aware via
    PDNLP_BENCH_SWEEP_BUDGET (default 3600 s)."""
    budget = float(os.environ.get("PDNLP_BENCH_SWEEP_BUDGET", 3600))
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_SWEEP.jsonl")
    done = set()
    if os.path.exists(path):
        with open(path) as f:
            for ln in f:
                try:
                    done.add(json.loads(ln)["config_key"])
                except (ValueError, KeyError):
                    pass

    configs = []
    # remat tiers first (biggest expected lever), default 128x128 blocks
    for remat in ("save_attn_mlp", "save_qkv_attn", "save_core_attn", "save_dots", "full"):
        configs.append({"remat": remat, "scan": "1", "bq": 128, "bkv": 128})
    # flash tile sweep on the default remat
    for bq, bkv in ((256, 256), (512, 512), (256, 512), (128, 512), (512, 256), (128, 1024)):
        configs.append({"remat": "save_qkv_attn", "scan": "1", "bq": bq, "bkv": bkv})
    # unrolled-layer comparison (VERDICT r3 1d: is scan blocking XLA overlap?)
    configs.append({"remat": "save_qkv_attn", "scan": "0", "bq": 128, "bkv": 128})
    configs.append({"remat": "none", "scan": "1", "bq": 128, "bkv": 128})

    t0 = time.time()
    for cfg in configs:
        key = f"{cfg['remat']}|scan{cfg['scan']}|bq{cfg['bq']}|bkv{cfg['bkv']}"
        if key in done:
            continue
        if time.time() - t0 > budget:
            print(f"[sweep] budget exhausted; stopping before {key}", file=sys.stderr)
            break
        env = {
            "PDNLP_BENCH_REMAT": cfg["remat"],
            "PDNLP_BENCH_SCAN": cfg["scan"],
            "PDNLP_FLASH_BLOCK_Q": str(cfg["bq"]),
            "PDNLP_FLASH_BLOCK_KV": str(cfg["bkv"]),
        }
        print(f"[sweep] running {key}", file=sys.stderr, flush=True)
        rc, out, err = _spawn(["--run"], min(RUN_TIMEOUT_S, 600), env=env)
        line = _json_line(out)
        rec = {"config_key": key, **cfg, "rc": rc, "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
        if rc == 0 and line:
            try:
                rec.update(json.loads(line))
            except ValueError:
                rec["error"] = f"unparseable: {line[:200]}"
        else:
            rec["error"] = "\n".join((out.strip().splitlines() + err.strip().splitlines())[-4:])[:500]
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        val = rec.get("value", 0.0)
        print(f"[sweep] {key} -> mfu={val} rc={rc}", file=sys.stderr, flush=True)
    # summary: best config
    best = None
    with open(path) as f:
        for ln in f:
            try:
                r = json.loads(ln)
            except ValueError:
                continue
            if r.get("value", 0) > (best or {}).get("value", 0):
                best = r
    print(json.dumps({"sweep_best": best}))


if __name__ == "__main__":
    if "--probe" in sys.argv:
        probe()
    elif "--sweep" in sys.argv:
        sweep()
    elif "--run" in sys.argv:
        run_bench("--tiny" in sys.argv)
    else:
        main()
