"""Benchmark: LLaMA pretraining step throughput on the attached TPU chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Baseline: the reference's published LLaMA-7B pretrain number — 3754.73
tokens/card/sec on A100-80G (llm/docs/pretrain.rst:188, BASELINE.md), which is
~52.5% MFU (6*6.7e9*3754.7 / 312e12). A single v5e chip (197 bf16 TFLOP/s, 16 GB)
cannot hold 7B training state, so the comparison is MFU-normalized: we run a
~350M-param LLaMA at seq 2048 and report achieved MFU; vs_baseline = our_MFU / 0.525.
"""

from __future__ import annotations

import json
import sys
import time


def main():
    tiny = "--tiny" in sys.argv
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
    from paddlenlp_tpu.utils.env import device_peak_flops

    if tiny:
        config = LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=512,
        )
        batch, seq_len, steps = 2, 256, 3
    else:
        config = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816, num_hidden_layers=24,
            num_attention_heads=16, num_key_value_heads=16, max_position_embeddings=4096,
            recompute=True, recompute_granularity="core_attn",
        )
        batch, seq_len, steps = 8, 2048, 10

    model = LlamaForCausalLM(config, dtype=jnp.bfloat16, param_dtype=jnp.float32)
    params = model.init_weights(seed=0)
    n_params = model.num_parameters()

    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(3e-4))
    opt_state = jax.jit(tx.init)(params)

    def loss_fn(params, ids):
        logits = model.module.apply({"params": params}, input_ids=ids[:, :-1], deterministic=True).logits
        logits = logits.astype(jnp.float32)
        labels = ids[:, 1:]
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        return (lse - picked).mean()

    @jax.jit
    def train_step(params, opt_state, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, config.vocab_size, (batch, seq_len + 1)), dtype=jnp.int32)

    # warmup / compile
    params, opt_state, loss = train_step(params, opt_state, ids)
    jax.block_until_ready(loss)

    t0 = time.time()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, ids)
    jax.block_until_ready(loss)
    dt = time.time() - t0

    tokens = batch * seq_len * steps
    tok_per_sec = tokens / dt
    # 6N matmul + attention FLOPs (causal: halved)
    attn_flops = 6 * config.num_hidden_layers * config.num_attention_heads * config.head_dim * seq_len
    flops_per_token = 6.0 * n_params + attn_flops
    peak = device_peak_flops() or 197e12
    mfu = tok_per_sec * flops_per_token / peak
    baseline_mfu = 0.525
    result = {
        "metric": "llama350m_pretrain_mfu",
        "value": round(mfu, 4),
        "unit": "model_flops_utilization (vs A100 llama7b baseline MFU 0.525)",
        "vs_baseline": round(mfu / baseline_mfu, 4),
        "tokens_per_second_per_chip": round(tok_per_sec, 1),
        "n_params": n_params,
        "seq_len": seq_len,
        "device": str(jax.devices()[0]),
        "loss": float(loss),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
