"""``python -m tools.analyze`` — run the static-analysis suite.

Default output is ONE machine-readable JSON line (the same contract as
``tools/check_metrics.py`` / ``tools/check_faults.py``), consumed by
``tests/tools/test_analyze.py`` so tier-1 enforces the ratchet on every PR.
Exit status: 0 = no new findings (stale baseline entries only warn),
1 = new findings, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    # make `python tools/analyze/__main__.py` work too, not just -m
    root_default = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if root_default not in sys.path:
        sys.path.insert(0, root_default)
    from tools.analyze import AnalysisContext, CHECKERS, run_checkers
    from tools.analyze.baseline import (DEFAULT_BASELINE_PATH, apply_baseline,
                                        load_baseline, write_baseline)

    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="AST-based static-analysis suite (jit purity, host syncs, "
                    "sharding contracts, lock discipline, catalogs)")
    ap.add_argument("--root", default=root_default, help="repo root to analyze")
    ap.add_argument("--checker", action="append", default=None,
                    help="run only this checker (repeatable)")
    ap.add_argument("--list", action="store_true", help="list checkers and exit")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE_PATH,
                    help="baseline file (ratchet state)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding as new (ignore the ratchet)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze current findings into the baseline file")
    ap.add_argument("--format", choices=("json", "text"), default="json")
    ap.add_argument("--max-new", type=int, default=50,
                    help="cap on new findings echoed into the JSON line")
    args = ap.parse_args(argv)

    ctx = AnalysisContext(args.root)
    if args.list:
        from tools.analyze import checkers  # noqa: F401 — trigger registration
        for name in sorted(CHECKERS):
            print(f"{name:20s} {CHECKERS[name].description}")
        return 0

    t0 = time.perf_counter()
    try:
        findings, per = run_checkers(ctx, args.checker)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2
    if args.write_baseline:
        # on a filtered run, entries belonging to checkers that did NOT run
        # are preserved verbatim — freezing one checker must not wipe the
        # rest of the ratchet (or its hand-written justifications)
        ran = set(per) | {c for c in (args.checker or [])}
        keep = (lambda e: e.get("rule") not in ran) if args.checker else None
        write_baseline(findings, args.baseline, keep_entry=keep)
        print(f"baseline written: {args.baseline} ({len(findings)} findings)",
              file=sys.stderr)
    baseline = {"version": 1, "entries": {}} if args.no_baseline \
        else load_baseline(args.baseline)
    new, baselined, stale = apply_baseline(findings, baseline)
    dur = time.perf_counter() - t0

    if args.format == "text":
        for f in new:
            print(f"NEW  {f.render()}")
        for s in stale:
            print(f"STALE baseline entry {s['fingerprint']}: "
                  f"{s.get('file')}: {s.get('message')}")
        print(f"{len(CHECKERS) if not args.checker else len(args.checker)} checkers, "
              f"{len(findings)} findings ({len(new)} new, {baselined} baselined, "
              f"{len(stale)} stale) in {dur:.2f}s")
    else:
        print(json.dumps({
            "ok": not new,
            "checkers": len(per),
            "per_checker": per,
            "findings": len(findings),
            "new": len(new),
            "baselined": baselined,
            "stale": len(stale),
            "new_findings": [f.to_dict() for f in new[: args.max_new]],
            "stale_entries": stale,
            "duration_s": round(dur, 3),
        }))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
