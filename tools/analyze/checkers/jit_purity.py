"""jit-purity checker: functions reachable from a jit/pjit/Pallas entry point
must be side-effect free.

Impurity inside traced code is the classic silent-wrong class of jax bug: the
side effect runs once at trace time (so smoke tests pass) and never again, or
— for instance-state mutation — runs at trace time against tracers and
poisons host state with abstract values. Banned inside the traced set:

- ``print`` / ``input`` / ``breakpoint`` / ``open`` / ``exec`` / ``eval``;
- ``time.*`` (trace-time constant folded into the compiled program);
- ``np.random.*`` / stdlib ``random.*`` (ditto — use ``jax.random`` keys);
- ``logging.*`` / ``logger.*`` calls;
- stores to ``self.<attr>`` and ``global``/``nonlocal`` declarations.

Entry points (seeds) are discovered statically:

- ``jax.jit(f, ...)`` / ``jit(f)`` / ``pjit(f)`` calls — including the
  ``_build_jits`` pattern, ``jax.jit(self._x_impl, ...)``;
- ``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` decorators;
- ``pl.pallas_call(kernel, ...)`` (a ``functools.partial(kernel, ...)`` first
  arg unwraps to the kernel).

Reachability is a name-based call graph over the configured ``jit_graph_dirs``
(kept narrow on purpose — a whole-package name graph would alias unrelated
helpers): ``self.x()`` resolves through the textual class hierarchy (the
class, its ancestors AND descendants — an override must be as pure as the
base), plain names resolve to same-module functions or relative-import
targets inside the scanned set. External calls (jnp/jax/lax/...) are leaves.

Suppress a deliberate trace-time effect with ``# jit-ok: <reason>`` on the
offending line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .. import AnalysisContext, Finding, dotted_name, register

RULE = "jit-purity"

_BANNED_CALLS = {"print", "input", "breakpoint", "exec", "eval", "open"}
_BANNED_ROOTS = ("time.", "logging.", "logger.", "random.")
_BANNED_CHAINS = ("np.random.", "numpy.random.")
_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}


class _Func:
    __slots__ = ("path", "qual", "cls", "name", "node")

    def __init__(self, path, qual, cls, name, node):
        self.path = path
        self.qual = qual  # "Class.method" or "func"
        self.cls = cls  # class name or None
        self.name = name
        self.node = node


class _Graph:
    """Name-indexed universe of functions/classes across the scanned files."""

    def __init__(self):
        self.funcs: Dict[Tuple[str, str], _Func] = {}  # (path, qual) -> _Func
        #: module-level functions per path: path -> {name: _Func}
        self.module_funcs: Dict[str, Dict[str, _Func]] = {}
        #: class name -> [(path, {method: _Func}, [base names])]
        self.classes: Dict[str, List[Tuple[str, Dict[str, _Func], List[str]]]] = {}
        #: (path, imported name) -> (target path or None, source name)
        self.imports: Dict[Tuple[str, str], Tuple[Optional[str], str]] = {}

    def methods_named(self, cls: str, name: str) -> List[_Func]:
        """Methods called ``name`` on ``cls``, its textual ancestors and its
        descendants (conservative: an override anywhere must stay pure)."""
        out, seen_cls = [], set()

        def ancestors(c):
            if c in seen_cls or c not in self.classes:
                return
            seen_cls.add(c)
            for _path, methods, bases in self.classes[c]:
                if name in methods:
                    out.append(methods[name])
                for b in bases:
                    ancestors(b)

        ancestors(cls)
        for other, defs in self.classes.items():
            if other in seen_cls:
                continue
            for _path, methods, bases in defs:
                if any(b in seen_cls for b in bases) and name in methods:
                    out.append(methods[name])
        return out


def _resolve_relative(path: str, level: int, module: Optional[str]) -> Optional[str]:
    """'pkg/sub/mod.py' + ``from ..x.y import z`` -> 'pkg/x/y.py'."""
    parts = path.split("/")[:-1]  # drop the module filename
    if level > 1:
        if level - 1 > len(parts):  # deeper than the path — unresolvable
            return None
        parts = parts[: len(parts) - (level - 1)]
    if module:
        parts = parts + module.split(".")
    return "/".join(parts) + ".py"


def _build_graph(ctx: AnalysisContext, paths: List[str]) -> _Graph:
    g = _Graph()
    path_set = set(paths)
    for path in paths:
        tree = ctx.tree(path)
        if tree is None:
            continue
        g.module_funcs[path] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = _Func(path, node.name, None, node.name, node)
                g.funcs[(path, node.name)] = fn
                g.module_funcs[path][node.name] = fn
            elif isinstance(node, ast.ClassDef):
                methods = {}
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fn = _Func(path, f"{node.name}.{sub.name}", node.name,
                                   sub.name, sub)
                        g.funcs[(path, fn.qual)] = fn
                        methods[sub.name] = fn
                bases = [dotted_name(b) or "" for b in node.bases]
                bases = [b.split(".")[-1] for b in bases if b]
                g.classes.setdefault(node.name, []).append((path, methods, bases))
        # imports can be nested (function-level `from ..quantization...`): walk
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                target = _resolve_relative(path, node.level, node.module) \
                    if node.level else ((node.module or "").replace(".", "/") + ".py")
                target = target if target in path_set else None
                for alias in node.names:
                    g.imports[(path, alias.asname or alias.name)] = \
                        (target, alias.name)
    return g


def _first_callable(call: ast.Call) -> Optional[ast.AST]:
    """First positional arg, unwrapping ``functools.partial(f, ...)``."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Call) and dotted_name(arg.func) in _PARTIAL_NAMES:
        return arg.args[0] if arg.args else None
    return arg


def _partial_aliases(tree: ast.Module) -> Dict[str, ast.AST]:
    """``kernel = functools.partial(_fa_kernel, ...)`` anywhere in the file:
    alias name -> the wrapped callable node."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and dotted_name(node.value.func) in _PARTIAL_NAMES \
                and node.value.args:
            out[node.targets[0].id] = node.value.args[0]
    return out


def _seed_targets(g: _Graph, path: str, cls: Optional[str],
                  target: Optional[ast.AST],
                  aliases: Optional[Dict[str, ast.AST]] = None) -> List[_Func]:
    if target is None:
        return []
    if isinstance(target, ast.Name) and aliases and target.id in aliases:
        target = aliases[target.id]
    if isinstance(target, ast.Name):
        fn = g.module_funcs.get(path, {}).get(target.id)
        if fn is not None:
            return [fn]
        imp = g.imports.get((path, target.id))
        if imp and imp[0]:
            fn = g.module_funcs.get(imp[0], {}).get(imp[1])
            return [fn] if fn else []
        # a method referenced as a bare name inside its own class body
        if cls:
            return g.methods_named(cls, target.id)
        return []
    if isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name) \
            and target.value.id == "self" and cls:
        return g.methods_named(cls, target.attr)
    return []


def _find_seeds(ctx: AnalysisContext, g: _Graph, paths: List[str]) -> List[_Func]:
    seeds: List[_Func] = []
    for path in paths:
        tree = ctx.tree(path)
        if tree is None:
            continue
        # enclosing class for each node (one level: methods in classes)
        cls_of: Dict[ast.AST, Optional[str]] = {}
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    cls_of[sub] = node.name
        aliases = _partial_aliases(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                if name in _JIT_NAMES or name.endswith(".pallas_call") \
                        or name == "pallas_call":
                    seeds.extend(_seed_targets(
                        g, path, cls_of.get(node), _first_callable(node), aliases))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dname = dotted_name(dec if not isinstance(dec, ast.Call) else dec.func)
                    if dname in _JIT_NAMES:
                        fn = g.funcs.get((path, node.name)) \
                            or next((f for f in g.funcs.values()
                                     if f.path == path and f.node is node), None)
                        if fn:
                            seeds.append(fn)
                    elif isinstance(dec, ast.Call) and dname in _PARTIAL_NAMES \
                            and dec.args and dotted_name(dec.args[0]) in _JIT_NAMES:
                        fn = next((f for f in g.funcs.values()
                                   if f.path == path and f.node is node), None)
                        if fn:
                            seeds.append(fn)
    return seeds


def _callees(g: _Graph, fn: _Func) -> List[_Func]:
    out: List[_Func] = []
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            target = g.module_funcs.get(fn.path, {}).get(f.id)
            if target is not None and target is not fn:
                out.append(target)
                continue
            imp = g.imports.get((fn.path, f.id))
            if imp and imp[0]:
                t = g.module_funcs.get(imp[0], {}).get(imp[1])
                if t:
                    out.append(t)
        elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self" and fn.cls:
            out.extend(g.methods_named(fn.cls, f.attr))
    return out


def _impurities(ctx: AnalysisContext, fn: _Func) -> List[Finding]:
    out = []

    def flag(node, msg):
        if not ctx.allowed(fn.path, node.lineno, "jit-ok"):
            out.append(Finding(RULE, fn.path, node.lineno, fn.qual, msg))

    for node in ast.walk(fn.node):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if name in _BANNED_CALLS:
                flag(node, f"calls {name}() inside jit-traced code "
                           "(runs at trace time only)")
            elif name.startswith(_BANNED_ROOTS) or name.startswith(_BANNED_CHAINS):
                flag(node, f"calls {name}() inside jit-traced code "
                           "(trace-time side effect / constant-folded)")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)) and not (
                        isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"):
                    base = base.value
                if isinstance(base, ast.Attribute) and isinstance(base.value, ast.Name) \
                        and base.value.id == "self":
                    flag(node, f"mutates instance state self.{base.attr} inside "
                               "jit-traced code")
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            flag(node, f"{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                       f"declaration ({', '.join(node.names)}) inside jit-traced code")
    return out


@register(RULE, "functions reachable from jax.jit/pjit/pallas_call must be pure")
def check(ctx: AnalysisContext) -> List[Finding]:
    paths = ctx.iter_py(ctx.config["jit_graph_dirs"])
    g = _build_graph(ctx, paths)
    seeds = _find_seeds(ctx, g, paths)
    # BFS over the call graph
    reach: Set[Tuple[str, str]] = set()
    queue = list(seeds)
    while queue:
        fn = queue.pop()
        key = (fn.path, fn.qual)
        if key in reach:
            continue
        reach.add(key)
        queue.extend(_callees(g, fn))
    findings: List[Finding] = []
    for path, qual in sorted(reach):
        findings.extend(_impurities(ctx, g.funcs[(path, qual)]))
    return findings
