"""host-sync checker: no silent device→host syncs in the engine step path.

The serving hot path's contract (engine.py module docstring, PR 1 onward) is
that one engine step costs ONE device→host transfer of int32 ids + flags.
Every extra sync — an ``.item()``, a stray ``np.asarray`` on a device array, a
``float()`` on a logit — serializes the host against the device and has
historically crept in silently (the PR 5/7 ``one_hot``/host-bincount
regressions were caught by hand). This checker flags, inside the configured
hot-path functions (``host_sync_paths`` config: file → function qualnames):

- ``.item()`` / ``.block_until_ready()`` / ``jax.device_get(...)``;
- ``np.asarray(...)`` / ``np.array(...)`` / ``np.bincount(...)`` — the
  device→host materialization points (and the host-side O(vocab) work the
  bincount regression rode in on);
- ``int(x)`` / ``float(x)`` where ``x`` is a subscript or call — the classic
  per-token device read (``int(tokens[i])`` on a live jax array syncs).

Static analysis cannot see types, so host-side numpy hits too; that is the
point — every sync-shaped construct on the hot path must be **documented**:
mark the deliberate ones with ``# sync-ok: <reason>`` on (or directly above)
the line. The allowlist is the documentation; an unmarked construct is a
finding and fails the ratchet.
"""

from __future__ import annotations

import ast
from typing import List

from .. import AnalysisContext, Finding, dotted_name, qualname_index, register

RULE = "host-sync"

_NP_SYNCS = {"np.asarray", "np.array", "np.bincount",
             "numpy.asarray", "numpy.array", "numpy.bincount"}
_ALWAYS = {"jax.device_get", "device_get", "jax.block_until_ready"}
_METHOD_SYNCS = {"item", "block_until_ready"}


def _is_host_builtin(node: ast.AST) -> bool:
    """int(sum(...)) / float(len(...)) over Python builtins is host math on
    host scalars, not a device read."""
    return isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
        and node.func.id in ("sum", "len", "min", "max", "abs", "round")


def _snippet(ctx: AnalysisContext, rel: str, lineno: int) -> str:
    lines = ctx.lines(rel)
    text = lines[lineno - 1].strip() if 1 <= lineno <= len(lines) else ""
    return text.split("#")[0].strip()[:90]


@register(RULE, "engine step path must not grow undocumented device->host syncs")
def check(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for rel, hot_quals in sorted(ctx.config["host_sync_paths"].items()):
        if not ctx.exists(rel):
            findings.append(Finding(RULE, rel, 0, "<config>",
                                    "configured hot-path file does not exist"))
            continue
        tree = ctx.tree(rel)
        if tree is None:
            continue
        quals = qualname_index(tree)
        hot = set(hot_quals)
        matched = {q for q in quals.values() if q in hot}
        for missing in sorted(hot - matched):
            findings.append(Finding(
                RULE, rel, 0, "<config>",
                f"configured hot-path function {missing!r} not found "
                "(renamed? update host_sync_paths)"))
        for node, qual in quals.items():
            if qual not in hot or not isinstance(node, (ast.FunctionDef,
                                                        ast.AsyncFunctionDef)):
                continue
            findings.extend(_scan_function(ctx, rel, qual, node))
    return findings


def _scan_function(ctx: AnalysisContext, rel: str, qual: str, fn) -> List[Finding]:
    out: List[Finding] = []

    def flag(node, what):
        if ctx.allowed(rel, node.lineno, "sync-ok"):
            return
        out.append(Finding(
            RULE, rel, node.lineno, qual,
            f"{what} in hot path `{_snippet(ctx, rel, node.lineno)}` — "
            "document with `# sync-ok: <reason>` if deliberate"))

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        if name in _NP_SYNCS:
            out_name = name.split(".")[-1]
            flag(node, f"host materialization np.{out_name}()")
        elif name in _ALWAYS:
            flag(node, f"explicit device sync {name}()")
        elif isinstance(node.func, ast.Attribute) and node.func.attr in _METHOD_SYNCS \
                and not node.args:
            flag(node, f".{node.func.attr}() device sync")
        elif name in ("int", "float") and len(node.args) == 1 \
                and isinstance(node.args[0], (ast.Subscript, ast.Call)) \
                and not _is_host_builtin(node.args[0]):
            flag(node, f"{name}() on an array element (per-token device read)")
    return out
