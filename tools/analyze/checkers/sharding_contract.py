"""sharding-contract checker: the PR 8 jit-compilation contract.

The sharded serving backend's correctness story (sharded_backend.py module
docstring) rests on every jitted step program being compiled with EXPLICIT
placement — ``in_shardings`` + ``out_shardings`` so GSPMD never invents a
layout, ``donate_argnums`` so the KV pool updates in place instead of
doubling HBM. A new step program added to the base ``_build_jits`` without a
sharded override compiles with default (replicated or GSPMD-chosen) layouts
and *works*, slowly and only until a mesh-shape change — the silent-drift
failure mode pjit-at-scale reports. Enforced:

- every ``jax.jit`` call inside the sharded file (``sharding_sharded_file``,
  classes overriding ``_build_jits``) declares ``in_shardings``,
  ``out_shardings`` AND ``donate_argnums``;
- every ``jax.jit`` call anywhere under ``sharding_extra_dirs`` (the
  experimental engine tree) declares at least ``donate_argnums`` — a step
  program that forgets donation doubles the pool per step;
- the SET of ``_impl`` functions jitted by the sharded ``_build_jits``
  equals the base class's set (``sharding_base_file``): adding a step to one
  side only is the contract break this checker exists for.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .. import AnalysisContext, Finding, dotted_name, qualname_index, register

RULE = "sharding-contract"

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_REQUIRED_SHARDED = ("in_shardings", "out_shardings", "donate_argnums")


def _jit_calls(tree: ast.Module):
    """Yield (call, enclosing-qualname) for every jax.jit call, including
    ``functools.partial(jax.jit, ...)`` decorator forms (as pseudo-calls)."""
    quals = qualname_index(tree)

    def scope_of(lineno: int) -> str:
        best, span = "<module>", None
        for node, q in quals.items():
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end and (span is None or end - node.lineno <= span):
                best, span = q, end - node.lineno
        return best

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and dotted_name(node.func) in _JIT_NAMES:
            yield node, scope_of(node.lineno)
        elif isinstance(node, ast.Call) and node.args \
                and dotted_name(node.func) in ("functools.partial", "partial") \
                and dotted_name(node.args[0]) in _JIT_NAMES:
            yield node, scope_of(node.lineno)


def _kwarg_names(call: ast.Call) -> Set[str]:
    return {kw.arg for kw in call.keywords if kw.arg}


def _target_impl(call: ast.Call) -> Optional[str]:
    """Name of the function being jitted (``self._prefill_impl`` -> that)."""
    args = call.args
    # partial(jax.jit, ...) has no target; jax.jit(target, ...) does
    if args and dotted_name(args[0]) in _JIT_NAMES:
        return None
    if not args:
        return None
    t = args[0]
    if isinstance(t, ast.Attribute):
        return t.attr
    if isinstance(t, ast.Name):
        return t.id
    return None


def _build_jits_sets(tree: ast.Module) -> Dict[str, Set[str]]:
    """class name -> set of impl names jitted inside its ``_build_jits``."""
    out: Dict[str, Set[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in node.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub.name == "_build_jits":
                impls = set()
                for call in ast.walk(sub):
                    if isinstance(call, ast.Call) and dotted_name(call.func) in _JIT_NAMES:
                        name = _target_impl(call)
                        if name:
                            impls.add(name)
                out[node.name] = impls
    return out


@register(RULE, "sharded jitted steps declare in/out shardings + donation; "
                "sharded and base jit sets stay in lockstep")
def check(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    base_file = ctx.config["sharding_base_file"]
    sharded_file = ctx.config["sharding_sharded_file"]
    # files under the FULL contract: the sharded backend plus the disagg
    # backend (whose migration gather/scatter programs move KV between the
    # two stage pools — an implicit-layout migration copy would silently
    # reshard the whole pool per handoff). The primary sharded_file is ALWAYS
    # strict, whatever the configured list says — a context that overrides
    # only sharding_sharded_file (tests) keeps the historical behavior.
    strict_files = list(dict.fromkeys(
        [sharded_file, *ctx.config.get("sharding_strict_files", [])]))

    # 1) full contract inside every strict file
    for strict in strict_files:
        if not ctx.exists(strict):
            if strict == sharded_file:
                findings.append(Finding(RULE, strict, 0, "<config>",
                                        "configured sharded backend file does not exist"))
            continue
        tree = ctx.tree(strict)
        if tree is None:
            continue
        for call, scope in _jit_calls(tree):
            missing = [k for k in _REQUIRED_SHARDED if k not in _kwarg_names(call)]
            if missing:
                target = _target_impl(call) or "<jit>"
                findings.append(Finding(
                    RULE, strict, call.lineno, scope,
                    f"jax.jit({target}) missing explicit {', '.join(missing)} "
                    "(every sharded step program compiles with declared "
                    "placement + donation — PR 8 contract)"))

    # 2) donation everywhere under the engine tree
    for rel in ctx.iter_py(ctx.config["sharding_extra_dirs"]):
        if rel in strict_files:  # already held to the stricter rule above
            continue
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for call, scope in _jit_calls(tree):
            if "donate_argnums" not in _kwarg_names(call):
                target = _target_impl(call) or "<jit>"
                findings.append(Finding(
                    RULE, rel, call.lineno, scope,
                    f"jax.jit({target}) without donate_argnums — an engine-tree "
                    "jit that skips donation doubles its buffers per step"))

    # 3) base vs sharded _build_jits set equality
    base_sets = _build_jits_sets(ctx.tree(base_file)) if ctx.exists(base_file) \
        and ctx.tree(base_file) is not None else {}
    sharded_sets = _build_jits_sets(ctx.tree(sharded_file)) if ctx.exists(sharded_file) \
        and ctx.tree(sharded_file) is not None else {}
    if base_sets and sharded_sets:
        # compare every sharded override against the union of base sets (the
        # base file defines one canonical builder today; union keeps this
        # stable if it ever splits)
        base_all: Set[str] = set().union(*base_sets.values())
        for cls, impls in sorted(sharded_sets.items()):
            for name in sorted(base_all - impls):
                findings.append(Finding(
                    RULE, sharded_file, 0, f"{cls}._build_jits",
                    f"base _build_jits compiles {name} but the sharded override "
                    "does not — the new step program would run with implicit "
                    "GSPMD layout"))
            for name in sorted(impls - base_all):
                findings.append(Finding(
                    RULE, sharded_file, 0, f"{cls}._build_jits",
                    f"sharded _build_jits compiles {name} with no base "
                    "counterpart — single-device parity has no such step"))
    return findings
