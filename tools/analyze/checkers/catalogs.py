"""Catalog-consistency checkers: faults, trace spans, metric names.

Three catalogs in this repo are stable string API — chaos tests arm fault
points by name, trace tooling filters spans by name, dashboards and
``bench_serve`` scrape metrics by name. Drift between the catalog and the
call sites means a chaos test that silently never fires, a span rename that
breaks every saved Perfetto query, a dashboard panel that flatlines. Each
sub-checker enforces both directions (used ⊆ documented, documented ⊆ used):

- **faults-catalog** — every ``FaultPoint("x")`` / ``FAULTS.arm|fire("x")``
  under ``paddlenlp_tpu/`` names a ``utils.faults.CATALOG`` entry with a real
  doc, and every entry has a call site (generalizes ``tools/check_faults.py``,
  which is now a thin shim over this module);
- **span-catalog** — every literal ``TRACER.span/instant/add_span`` name is
  registered in ``observability/span_catalog.py`` (and vice versa); a call
  site with a *dynamic* name declares its names with ``# span-names: a b c``;
- **event-catalog** — every literal ``RECORDER.record`` decision-event name
  is registered + documented in ``observability/event_catalog.py`` (and vice
  versa) — the same both-directions contract as the span catalog, for the
  flight recorder's postmortem vocabulary;
- **metrics-catalog** — the static half of the metrics lint (the runtime
  HELP/TYPE/exposition lint stays in ``tools/check_metrics.py``, which needs
  jax to instantiate the catalog): every literal metric name registered via
  ``registry.counter/gauge/histogram`` is a valid Prometheus name, counters
  end in ``_total``, and the name is documented in a README metrics table.

All three load repo modules (``faults.py``, ``span_catalog.py``) by FILE PATH
— importing through the package would execute ``paddlenlp_tpu.__init__``
(jax and all); both modules are stdlib-only by contract.
"""

from __future__ import annotations

import ast
import importlib.util
import re
import sys
from typing import Dict, List, Optional, Tuple

from .. import AnalysisContext, Finding, dotted_name, enclosing_scope, register, str_arg

_RE_FAULT_POINT = re.compile(r'FaultPoint\(\s*[\'"]([\w.]+)[\'"]')
_RE_FAULT_REG = re.compile(r'FAULTS\.(?:arm|fire)\(\s*[\'"]([\w.]+)[\'"]')
_RE_SPAN_NAMES = re.compile(r"#\s*span-names:\s*([\w\- ]+)")
_RE_SPAN_DYNAMIC = re.compile(r"#\s*span-dynamic:\s*\S")
_RE_METRIC_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SPAN_METHODS = {"span", "instant", "add_span"}
_REG_METHODS = {"counter", "gauge", "histogram"}


def load_module_by_path(path: str, alias: str):
    """Import a stdlib-only repo module by file path (no package __init__)."""
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # dataclass field resolution looks here
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------------ faults
def faults_scan_call_sites(ctx_or_none, src_dir: str, rel_to: str) -> Dict[str, List[str]]:
    """name -> [relpath, ...] for every fault-point reference under
    ``src_dir`` (absolute), relpaths relative to ``rel_to``. Kept
    framework-free so the ``check_faults.py`` shim can call it directly."""
    import os

    sites: Dict[str, List[str]] = {}
    for root, _dirs, names in os.walk(src_dir):
        for name in names:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, rel_to)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for rx in (_RE_FAULT_POINT, _RE_FAULT_REG):
                for m in rx.finditer(text):
                    sites.setdefault(m.group(1), []).append(rel)
    return sites


def faults_problems(catalog: Dict[str, str], sites: Dict[str, List[str]]) -> List[str]:
    """The check_faults.py contract, shared verbatim by shim and checker."""
    problems = []
    for used, where in sorted(sites.items()):
        if used not in catalog:
            problems.append(f"fault point {used!r} used in {sorted(set(where))} "
                            "but not registered in faults.CATALOG")
    for name, doc in sorted(catalog.items()):
        if not doc or len(doc.strip()) < 20:
            problems.append(f"catalog entry {name!r} has no meaningful doc")
        if name not in sites:
            problems.append(f"catalog entry {name!r} has no call site under paddlenlp_tpu/ "
                            "(dead chaos coverage — wire it or drop it)")
    return problems


@register("faults-catalog", "fault points used == registered == documented")
def check_faults(ctx: AnalysisContext) -> List[Finding]:
    path = ctx.abspath(ctx.config["faults_module"])
    try:
        catalog = dict(load_module_by_path(path, "_analyze_faults").CATALOG)
    except Exception as e:
        return [Finding("faults-catalog", ctx.config["faults_module"], 0, "<module>",
                        f"cannot load fault catalog: {e!r}")]
    sites = faults_scan_call_sites(ctx, ctx.abspath(ctx.config["catalog_src_dir"]),
                                   ctx.root)
    return [Finding("faults-catalog", ctx.config["faults_module"], 0, "CATALOG", p)
            for p in faults_problems(catalog, sites)]


# ------------------------------------------------------------------ spans
def _is_tracer_call(func: ast.AST) -> bool:
    """TRACER.span / tracer.instant / self.tracer.add_span / pool.tracer.*"""
    if not (isinstance(func, ast.Attribute) and func.attr in _SPAN_METHODS):
        return False
    v = func.value
    if isinstance(v, ast.Name):
        return v.id in ("TRACER", "tracer")
    if isinstance(v, ast.Attribute):
        return v.attr in ("tracer", "_tracer")
    return False


def span_call_sites(ctx: AnalysisContext) -> Tuple[Dict[str, List[Tuple[str, int]]],
                                                   List[Finding]]:
    """Literal span names used under the catalog source dir (name ->
    [(relpath, lineno), ...]), plus findings for dynamic-name call sites
    missing a ``# span-names:`` declaration."""
    used: Dict[str, List[Tuple[str, int]]] = {}
    findings: List[Finding] = []
    for rel in ctx.iter_py([ctx.config["catalog_src_dir"]]):
        src = ctx.source(rel)
        if "TRACER" not in src and "tracer" not in src:
            continue
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_tracer_call(node.func)):
                continue
            name = str_arg(node)
            if name is not None:
                used.setdefault(name, []).append((rel, node.lineno))
                continue
            declared = _declared_span_names(ctx, rel, node.lineno)
            if declared:
                for n in declared:
                    used.setdefault(n, []).append((rel, node.lineno))
            elif not _declared_span_dynamic(ctx, rel, node.lineno):
                findings.append(Finding(
                    "span-catalog", rel, node.lineno,
                    enclosing_scope(tree, node.lineno),
                    f"dynamic span name in {node.func.attr}() call — declare the "
                    "possible names with `# span-names: a b c`, or mark a "
                    "deliberately open namespace with `# span-dynamic: <reason>`"))
    return used, findings


def _annotation_lines(ctx: AnalysisContext, rel: str, line: int):
    """The call line itself, plus the line above ONLY when it is comment-only
    (a trailing annotation on the previous construct must not bleed down)."""
    lines = ctx.lines(rel)
    if 1 <= line <= len(lines):
        yield lines[line - 1]
    if 2 <= line <= len(lines) + 1 and lines[line - 2].strip().startswith("#"):
        yield lines[line - 2]


def _declared_span_names(ctx: AnalysisContext, rel: str, line: int) -> List[str]:
    for text in _annotation_lines(ctx, rel, line):
        m = _RE_SPAN_NAMES.search(text)
        if m:
            return m.group(1).split()
    return []


def _declared_span_dynamic(ctx: AnalysisContext, rel: str, line: int) -> bool:
    return any(_RE_SPAN_DYNAMIC.search(text)
               for text in _annotation_lines(ctx, rel, line))


@register("span-catalog", "trace span/instant names used == documented in "
                          "observability/span_catalog.py")
def check_spans(ctx: AnalysisContext) -> List[Finding]:
    rel = ctx.config["span_catalog_module"]
    try:
        catalog = dict(load_module_by_path(ctx.abspath(rel), "_analyze_spans").SPAN_CATALOG)
    except Exception as e:
        return [Finding("span-catalog", rel, 0, "<module>",
                        f"cannot load span catalog: {e!r}")]
    used, findings = span_call_sites(ctx)
    for name, where in sorted(used.items()):
        if name not in catalog:
            # message stays line-number-free (fingerprint contract); the first
            # call site's line rides in Finding.line for display only
            files = sorted({f for f, _ in where})
            findings.append(Finding(
                "span-catalog", where[0][0], where[0][1], "SPAN_CATALOG",
                f"span name {name!r} (used in {files[:3]}) not in "
                "SPAN_CATALOG — trace names are stable API, register + document it"))
    for name, doc in sorted(catalog.items()):
        if not doc or len(doc.strip()) < 15:
            findings.append(Finding("span-catalog", rel, 0, "SPAN_CATALOG",
                                    f"span catalog entry {name!r} has no meaningful doc"))
        if name not in used:
            findings.append(Finding(
                "span-catalog", rel, 0, "SPAN_CATALOG",
                f"span catalog entry {name!r} has no call site — stale entry, "
                "prune it or wire the span back in"))
    return findings


# ------------------------------------------------------------------ events
def _is_recorder_call(func: ast.AST) -> bool:
    """RECORDER.record / recorder.record / self.recorder.record — the flight
    recorder's one recording entry point. The deliberately narrow receiver
    set keeps unrelated ``.record()`` methods out of the checker."""
    if not (isinstance(func, ast.Attribute) and func.attr == "record"):
        return False
    v = func.value
    if isinstance(v, ast.Name):
        return v.id in ("RECORDER", "recorder")
    if isinstance(v, ast.Attribute):
        return v.attr in ("recorder", "_recorder")
    return False


def event_call_sites(ctx: AnalysisContext) -> Tuple[Dict[str, List[Tuple[str, int]]],
                                                    List[Finding]]:
    """Literal decision-event names used under the catalog source dir, plus
    findings for dynamic-name call sites (declare with ``# event-names:``)."""
    used: Dict[str, List[Tuple[str, int]]] = {}
    findings: List[Finding] = []
    for rel in ctx.iter_py([ctx.config["catalog_src_dir"]]):
        src = ctx.source(rel)
        if "RECORDER" not in src and "recorder" not in src:
            continue
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_recorder_call(node.func)):
                continue
            name = str_arg(node)
            if name is not None:
                used.setdefault(name, []).append((rel, node.lineno))
                continue
            declared = _declared_event_names(ctx, rel, node.lineno)
            if declared:
                for n in declared:
                    used.setdefault(n, []).append((rel, node.lineno))
            else:
                findings.append(Finding(
                    "event-catalog", rel, node.lineno,
                    enclosing_scope(tree, node.lineno),
                    "dynamic decision-event name in record() call — declare "
                    "the possible names with `# event-names: a b c`"))
    return used, findings


_RE_EVENT_NAMES = re.compile(r"#\s*event-names:\s*([\w.\- ]+)")


def _declared_event_names(ctx: AnalysisContext, rel: str, line: int) -> List[str]:
    for text in _annotation_lines(ctx, rel, line):
        m = _RE_EVENT_NAMES.search(text)
        if m:
            return m.group(1).split()
    return []


@register("event-catalog", "flight-recorder decision-event names used == "
                           "documented in observability/event_catalog.py")
def check_events(ctx: AnalysisContext) -> List[Finding]:
    rel = ctx.config["event_catalog_module"]
    try:
        mod = load_module_by_path(ctx.abspath(rel), "_analyze_events")
        catalog = dict(mod.EVENT_CATALOG)
        reasons = dict(getattr(mod, "EVENT_REASONS", {}))
    except Exception as e:
        return [Finding("event-catalog", rel, 0, "<module>",
                        f"cannot load event catalog: {e!r}")]
    used, findings = event_call_sites(ctx)
    for name, where in sorted(used.items()):
        if name not in catalog:
            # message stays line-number-free (fingerprint contract); the first
            # call site's line rides in Finding.line for display only
            files = sorted({f for f, _ in where})
            findings.append(Finding(
                "event-catalog", where[0][0], where[0][1], "EVENT_CATALOG",
                f"decision event {name!r} (used in {files[:3]}) not in "
                "EVENT_CATALOG — event names are stable postmortem API, "
                "register + document it"))
    for name, doc in sorted(catalog.items()):
        if not doc or len(doc.strip()) < 15:
            findings.append(Finding("event-catalog", rel, 0, "EVENT_CATALOG",
                                    f"event catalog entry {name!r} has no meaningful doc"))
        if name not in used:
            findings.append(Finding(
                "event-catalog", rel, 0, "EVENT_CATALOG",
                f"event catalog entry {name!r} has no call site — stale "
                "entry, prune it or wire the event back in"))
    for name in sorted(reasons):
        if name not in catalog:
            findings.append(Finding(
                "event-catalog", rel, 0, "EVENT_REASONS",
                f"EVENT_REASONS entry {name!r} names an event missing from "
                "EVENT_CATALOG"))
    return findings


# ------------------------------------------------------------------ metrics
def _module_str_constants(tree: ast.Module) -> Dict[str, str]:
    out = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def metric_registrations(ctx: AnalysisContext):
    """Yield (rel, lineno, kind, name) for every static metric registration
    under the catalog source dir. Module-level string constants used as names
    (``registry.counter(TRACES_DROPPED_METRIC, ...)``) are resolved."""
    for rel in ctx.iter_py([ctx.config["catalog_src_dir"]]):
        src = ctx.source(rel)
        if ".counter(" not in src and ".gauge(" not in src and ".histogram(" not in src:
            continue
        tree = ctx.tree(rel)
        if tree is None:
            continue
        consts = _module_str_constants(tree)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REG_METHODS and node.args):
                continue
            name = str_arg(node)
            if name is None and isinstance(node.args[0], ast.Name):
                name = consts.get(node.args[0].id)
                # a constant imported from another module resolves there; an
                # unresolvable name arg is skipped (the runtime lint in
                # check_metrics.py still covers whatever it registers)
            if name is None:
                continue
            # heuristic guard: metric names in this codebase are snake_case
            # with >= 1 underscore; skips unrelated .counter() methods
            if "_" not in name:
                continue
            yield rel, node.lineno, node.func.attr, name


@register("metrics-catalog", "registered metric names are valid, suffixed by "
                             "convention, and documented in a README table")
def check_metrics(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    readmes = [ctx.source(p) for p in ctx.config["readme_paths"] if ctx.exists(p)]
    if not readmes:
        return [Finding("metrics-catalog", "<config>", 0, "<config>",
                        "no configured README found to check metric docs against")]
    docs = "\n".join(readmes)
    for rel, lineno, kind, name in metric_registrations(ctx):
        scope = enclosing_scope(ctx.tree(rel), lineno)
        if not _RE_METRIC_NAME.match(name):
            findings.append(Finding(
                "metrics-catalog", rel, lineno, scope,
                f"metric name {name!r} is not a valid Prometheus name"))
            continue
        if kind == "counter" and not name.endswith("_total"):
            findings.append(Finding(
                "metrics-catalog", rel, lineno, scope,
                f"counter {name!r} does not end in _total (Prometheus convention "
                "this catalog follows everywhere else)"))
        if f"`{name}`" not in docs and f"`{name}{{" not in docs:
            findings.append(Finding(
                "metrics-catalog", rel, lineno, scope,
                f"metric {name!r} not documented in any README metrics table "
                f"({', '.join(ctx.config['readme_paths'])}) — names are stable "
                "API, add a row"))
    return findings
