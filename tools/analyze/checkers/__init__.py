"""Checker modules. Importing this package registers every checker in
``tools.analyze.CHECKERS`` — keep this import list as the single place a new
checker gets wired in (add the module here and it rides every run, the
tier-1 smoke test, and ``--list``)."""

from . import (  # noqa: F401
    catalogs,
    host_sync,
    jit_purity,
    lock_discipline,
    sharding_contract,
)
