"""lock-discipline checker: annotated shared state is only touched under its
lock.

The serving stack's shared mutable state (Scheduler's admission window, the
router pool's replica set, the tracer's span ring) is guarded by informal
convention: "take ``self._lock`` around it". This checker makes the
convention machine-checked via a tiny annotation language:

- ``self._inflight = 0  # guarded-by: _lock`` in ``__init__`` registers the
  attribute as protected by ``self._lock`` (any ``self.<lock>`` attribute);
- every OTHER read/write of ``self._inflight`` inside the class must sit
  lexically inside a ``with self._lock:`` block;
- ``# lock-ok: <reason>`` on the access line (or above) documents a
  deliberate unguarded access (e.g. a tolerated racy read);
- a method whose ``def`` line (or the line above) carries
  ``# holds-lock: _lock`` is treated as running with the lock held (callers
  acquire it) — the annotation documents the calling convention.

Scope is intra-class and lexical on purpose: cross-module aliasing and
thread-confinement ("only the loop thread touches this") are documented in
each module's "Concurrency model" docstring instead — this checker enforces
exactly the part a machine can see, which is where the drift happens.

``__init__`` is exempt (the object is not shared during construction).
A ``guarded-by`` naming a lock the class never creates is itself a finding.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .. import AnalysisContext, Finding, register

RULE = "lock-discipline"

_RE_GUARD = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_RE_ATTR = re.compile(r"self\.([A-Za-z_]\w*)\s*[:=]")
_RE_HOLDS = re.compile(r"#\s*holds-lock:\s*([A-Za-z_]\w*)")


def _holds_lock(ctx: AnalysisContext, rel: str, fn) -> Optional[str]:
    lines = ctx.lines(rel)
    for ln, standalone in ((fn.lineno, False), (fn.lineno - 1, True)):
        if not 1 <= ln <= len(lines):
            continue
        text = lines[ln - 1]
        if standalone and not text.strip().startswith("#"):
            continue  # a trailing comment on code above must not bleed down
        m = _RE_HOLDS.search(text)
        if m:
            return m.group(1)
    return None


class _AccessVisitor(ast.NodeVisitor):
    """Walk one method tracking the lexical stack of held ``self.X`` locks."""

    def __init__(self, guarded: Dict[str, str], held: Set[str]):
        self.guarded = guarded  # attr -> lock name
        self.held = set(held)
        self.violations: List[ast.Attribute] = []

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            e = item.context_expr
            if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
                    and e.value.id == "self":
                acquired.append(e.attr)
            # also scan the context expressions themselves (e.g. a guarded
            # attr used to *build* the cm) before the lock is held
            self.generic_visit_expr(e)
        self.held.update(acquired)
        for stmt in node.body:
            self.visit(stmt)
        self.held.difference_update(acquired)

    visit_AsyncWith = visit_With

    def generic_visit_expr(self, node):
        for child in ast.walk(node):
            self._check(child)

    def visit_Attribute(self, node: ast.Attribute):
        self._check(node)
        self.generic_visit(node)

    def _check(self, node):
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
                and node.value.id == "self" and node.attr in self.guarded \
                and self.guarded[node.attr] not in self.held:
            self.violations.append(node)


def _class_guards(ctx: AnalysisContext, rel: str, cls: ast.ClassDef):
    """(attr -> lock, lock attrs created in the class, annotation findings)."""
    lines = ctx.lines(rel)
    guarded: Dict[str, str] = {}
    findings: List[Finding] = []
    end = getattr(cls, "end_lineno", cls.lineno)
    for ln in range(cls.lineno, min(end, len(lines)) + 1):
        m = _RE_GUARD.search(lines[ln - 1])
        if not m:
            continue
        attr = _RE_ATTR.search(lines[ln - 1].split("#")[0])
        if attr is None:
            findings.append(Finding(
                RULE, rel, ln, cls.name,
                "malformed `# guarded-by:` annotation — must sit on a "
                "`self.<attr> = ...` line"))
            continue
        guarded[attr.group(1)] = m.group(1)
    locks_created: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    locks_created.add(t.attr)
    return guarded, locks_created, findings


@register(RULE, "attributes annotated `# guarded-by: <lock>` are only accessed "
                "inside `with self.<lock>:`")
def check(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for rel in ctx.iter_py():
        if "guarded-by:" not in ctx.source(rel):
            continue
        tree = ctx.tree(rel)
        if tree is None:
            continue
        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            guarded, created, notes = _class_guards(ctx, rel, cls)
            findings.extend(notes)
            if not guarded:
                continue
            for attr, lock in sorted(guarded.items()):
                if lock not in created:
                    findings.append(Finding(
                        RULE, rel, cls.lineno, cls.name,
                        f"`# guarded-by: {lock}` on self.{attr} but the class "
                        f"never creates self.{lock}"))
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__":
                    continue  # not shared during construction
                held: Set[str] = set()
                holds = _holds_lock(ctx, rel, fn)
                if holds:
                    held.add(holds)
                v = _AccessVisitor(guarded, held)
                for stmt in fn.body:
                    v.visit(stmt)
                for node in v.violations:
                    if ctx.allowed(rel, node.lineno, "lock-ok"):
                        continue
                    lock = guarded[node.attr]
                    findings.append(Finding(
                        RULE, rel, node.lineno, f"{cls.name}.{fn.name}",
                        f"self.{node.attr} (guarded-by {lock}) accessed outside "
                        f"`with self.{lock}:` — annotate `# lock-ok: <reason>` "
                        "if the race is deliberate"))
    return findings
