"""Static-analysis suite for the repo's hand-rolled correctness contracts.

Three contracts in this codebase historically held only by reviewer
vigilance, and each has been broken (and caught by hand) at least once:

- **jit purity** — every function reachable from a ``jax.jit``/``pjit`` call
  or a Pallas kernel must stay side-effect free (no ``print``/``time.*``/
  ``np.random``/logging, no instance-state mutation): impurity silently runs
  at trace time only, so "it worked once" is exactly the failure mode;
- **host-sync discipline** — the engine step path must not grow silent
  device→host syncs (``.item()``, ``np.asarray``, ``block_until_ready``,
  host bincounts): the PR 5/7 perf work caught ``one_hot``/host-bincount
  regressions by hand, twice;
- **sharding contract** — every jitted step program of the sharded backend
  carries explicit ``in_shardings``/``out_shardings``/``donate_argnums``
  (the PR 8 contract), and the sharded jit set never drifts from the base;
- plus **lock discipline** over the serving stack's shared state and the
  **catalog consistency** lints (faults / trace spans / metric names).

This package turns those contracts into machines: an AST-based (stdlib
``ast``, **no jax import**, no repo imports at package scope) checker
framework with a pluggable registry, per-checker findings carrying
``file:line`` + a rule id, and a committed baseline file implementing a
**ratchet** — existing violations are frozen in ``BASELINE.json`` with a
justification; any NEW violation fails tier-1
(``tests/tools/test_analyze.py`` runs the suite).

Run it::

    python -m tools.analyze                 # one JSON summary line, rc=1 on new findings
    python -m tools.analyze --format text   # human-readable findings
    python -m tools.analyze --checker jit-purity
    python -m tools.analyze --write-baseline  # freeze current findings (justify by hand!)

Inline allowlists (each requires a reason, read by humans in review):

- ``# sync-ok: <reason>`` — a documented host-sync point (host_sync checker);
- ``# lock-ok: <reason>`` — a deliberate unguarded access (lock_discipline);
- ``# jit-ok: <reason>``  — a deliberate trace-time side effect (jit_purity);
- ``# span-names: a b c`` — literal names behind a dynamic span call site.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
from typing import Callable, Dict, List, Optional

__all__ = ["Finding", "Checker", "AnalysisContext", "CHECKERS", "register",
           "run_checkers", "DEFAULT_CONFIG"]


# --------------------------------------------------------------------- config
#: Per-checker knobs, overridable via AnalysisContext(config=...). Paths are
#: repo-root-relative with "/" separators (normalized at use).
DEFAULT_CONFIG: Dict = {
    # directories the generic scanners walk
    "scan_dirs": ["paddlenlp_tpu", "tools"],
    # jit_purity: where the call graph is built (keep this bounded — a
    # name-based graph over the whole package would alias unrelated helpers)
    "jit_graph_dirs": [
        "paddlenlp_tpu/experimental",
        "paddlenlp_tpu/ops",
        "paddlenlp_tpu/quantization",
        "paddlenlp_tpu/parallel",
    ],
    # host_sync: file -> hot-path function qualnames ("Class.method" / "func").
    # These are the engine step path: everything that runs once per engine
    # step under serving traffic. Host-side-by-design code (the speculative
    # proposers / rejection sampler, admission bookkeeping off the step loop)
    # is deliberately NOT listed — its host math is the documented algorithm.
    "host_sync_paths": {
        "paddlenlp_tpu/experimental/engine.py": [
            "InferenceEngine.step", "InferenceEngine._admit",
            "InferenceEngine._admit_slots", "InferenceEngine._admit_chunked",
            "InferenceEngine._mixed_step", "InferenceEngine._decode_running",
            "InferenceEngine._decode_spec", "InferenceEngine._settle_sampled",
            "InferenceEngine._advance_migrations",
            "InferenceEngine._advance_promotions",
            "InferenceEngine._drain_spills",
            "InferenceEngine._emit", "InferenceEngine._free_kv",
            "InferenceEngine._preempt",
        ],
        "paddlenlp_tpu/experimental/kv_host_tier.py": [
            "HostKVTier.put", "HostKVTier.take", "_SpillBatch.settle",
        ],
        "paddlenlp_tpu/experimental/backend.py": [
            "ModelBackend.migration_ready", "ModelBackend.kv_writeback",
            "SingleDeviceBackend.prefill", "SingleDeviceBackend.decode",
            "SingleDeviceBackend.verify", "SingleDeviceBackend.mixed_step",
            "SingleDeviceBackend.mixed_step_begin",
            "SingleDeviceBackend._mixed_padded_launch",
            "SingleDeviceBackend._mixed_flat_launch",
            "SingleDeviceBackend._cached_counts", "SingleDeviceBackend.seed_counts",
            "SingleDeviceBackend.reset_counts", "SingleDeviceBackend.apply_cow",
            "SingleDeviceBackend.kv_spill", "SingleDeviceBackend.kv_promote",
        ],
        "paddlenlp_tpu/experimental/sharded_backend.py": [
            "ShardedBackend.params",
        ],
        "paddlenlp_tpu/experimental/disagg_backend.py": [
            "DisaggBackend.prefill", "DisaggBackend.decode",
            "DisaggBackend.verify", "DisaggBackend.mixed_step",
            "DisaggBackend.seed_counts", "DisaggBackend.reset_counts",
            "DisaggBackend.apply_cow", "DisaggBackend.kv_migrate",
            "DisaggBackend.kv_spill", "DisaggBackend.kv_promote",
            "DisaggBackend.kv_writeback",
        ],
        "paddlenlp_tpu/serving/engine_loop.py": [
            "EngineLoop._run_iteration", "EngineLoop._drain_cmds",
            "EngineLoop._finish", "EngineLoop._make_stream_cb",
        ],
    },
    # sharding_contract: the base jit builder and the sharded overrides
    "sharding_base_file": "paddlenlp_tpu/experimental/inference_model.py",
    "sharding_sharded_file": "paddlenlp_tpu/experimental/sharded_backend.py",
    # files held to the FULL contract (in/out shardings + donation on every
    # jit): the sharded backend's step programs and the disagg backend's
    # migration gather/scatter programs (both stages' step programs are the
    # sharded file's — each stage IS a ShardedBackend)
    "sharding_strict_files": [
        "paddlenlp_tpu/experimental/sharded_backend.py",
        "paddlenlp_tpu/experimental/disagg_backend.py",
    ],
    "sharding_extra_dirs": ["paddlenlp_tpu/experimental"],
    # lock_discipline scans every file in scan_dirs for "# guarded-by:" lines
    # catalogs
    "faults_module": "paddlenlp_tpu/utils/faults.py",
    "span_catalog_module": "paddlenlp_tpu/observability/span_catalog.py",
    "event_catalog_module": "paddlenlp_tpu/observability/event_catalog.py",
    "catalog_src_dir": "paddlenlp_tpu",
    "readme_paths": ["README.md", "paddlenlp_tpu/serving/README.md"],
}


# -------------------------------------------------------------------- findings
@dataclasses.dataclass
class Finding:
    """One rule violation. ``fingerprint`` deliberately excludes the line
    number so baselined findings survive unrelated edits above them; the
    ``message`` should therefore carry a stable snippet of the offending
    construct, not positional info."""

    rule: str
    file: str  # repo-root-relative, "/" separators
    line: int
    scope: str  # enclosing qualname ("Class.method", "func", or "<module>")
    message: str

    @property
    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.file}|{self.scope}|{self.message}"
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "scope": self.scope, "message": self.message,
                "fingerprint": self.fingerprint}

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.scope}: {self.message}"


@dataclasses.dataclass
class Checker:
    name: str
    description: str
    run: Callable[["AnalysisContext"], List[Finding]]


#: name -> Checker; populated by importing tools.analyze.checkers
CHECKERS: Dict[str, Checker] = {}


def register(name: str, description: str):
    """Decorator: register ``fn(ctx) -> [Finding]`` as a named checker."""

    def deco(fn):
        CHECKERS[name] = Checker(name, description, fn)
        return fn

    return deco


# --------------------------------------------------------------------- context
class AnalysisContext:
    """Shared parse cache + config for one analysis run.

    Checkers see one immutable-ish facade: ``iter_py`` to enumerate sources,
    ``tree``/``lines`` cached per file (every checker walking the same file
    parses it once), ``allowed(relpath, line, marker)`` for the inline
    allowlist convention (marker comment on the flagged line or the line
    directly above it, reason required).
    """

    def __init__(self, root: str, config: Optional[Dict] = None):
        self.root = os.path.abspath(root)
        self.config: Dict = dict(DEFAULT_CONFIG)
        if config:
            self.config.update(config)
        self._sources: Dict[str, str] = {}
        self._lines: Dict[str, List[str]] = {}
        self._trees: Dict[str, Optional[ast.Module]] = {}
        self.parse_errors: List[Finding] = []

    # ------------------------------------------------------------- file access
    def abspath(self, rel: str) -> str:
        return os.path.join(self.root, rel.replace("/", os.sep))

    def exists(self, rel: str) -> bool:
        return os.path.isfile(self.abspath(rel))

    def iter_py(self, subdirs: Optional[List[str]] = None) -> List[str]:
        """Repo-relative paths of every .py under ``subdirs`` (default: the
        configured scan_dirs), sorted for deterministic output."""
        out = []
        for sub in subdirs if subdirs is not None else self.config["scan_dirs"]:
            base = self.abspath(sub)
            if os.path.isfile(base) and base.endswith(".py"):
                out.append(sub)
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for name in filenames:
                    if name.endswith(".py"):
                        full = os.path.join(dirpath, name)
                        out.append(os.path.relpath(full, self.root).replace(os.sep, "/"))
        return sorted(set(out))

    def source(self, rel: str) -> str:
        if rel not in self._sources:
            with open(self.abspath(rel), encoding="utf-8") as f:
                self._sources[rel] = f.read()
        return self._sources[rel]

    def lines(self, rel: str) -> List[str]:
        if rel not in self._lines:
            self._lines[rel] = self.source(rel).splitlines()
        return self._lines[rel]

    def tree(self, rel: str) -> Optional[ast.Module]:
        """Parsed AST (cached); None (plus a parse-error finding) on a file
        that does not parse — a syntax error must fail the suite loudly, not
        silently skip every checker."""
        if rel not in self._trees:
            try:
                self._trees[rel] = ast.parse(self.source(rel), filename=rel)
            except SyntaxError as e:
                self._trees[rel] = None
                self.parse_errors.append(Finding(
                    rule="parse-error", file=rel, line=e.lineno or 0,
                    scope="<module>", message=f"file does not parse: {e.msg}"))
        return self._trees[rel]

    # ------------------------------------------------------------- allowlists
    def allowed(self, rel: str, line: int, marker: str) -> bool:
        """True if the 1-indexed ``line`` carries the inline allowlist
        ``marker`` ("sync-ok" / "lock-ok" / "jit-ok") with a non-empty
        reason, or the line above is a comment-only line carrying it. The
        comment-only requirement stops a trailing annotation on one construct
        from silently allowlisting whatever lands on the next line."""
        lines = self.lines(rel)
        for ln, standalone in ((line, False), (line - 1, True)):
            if not 1 <= ln <= len(lines):
                continue
            text = lines[ln - 1]
            if standalone and not text.strip().startswith("#"):
                continue
            idx = text.find(f"# {marker}:")
            if idx >= 0 and text[idx + len(marker) + 3:].strip():
                return True
        return False


# --------------------------------------------------------------------- helpers
def qualname_index(tree: ast.Module) -> Dict[ast.AST, str]:
    """Map every function/class def node to its dotted qualname."""
    out: Dict[ast.AST, str] = {}

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                out[child] = q
                walk(child, q)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def enclosing_scope(tree: ast.Module, lineno: int) -> str:
    """Qualname of the innermost def/class containing ``lineno``."""
    best, best_span = "<module>", None
    for node, q in qualname_index(tree).items():
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= lineno <= end:
            span = end - node.lineno
            if best_span is None or span <= best_span:
                best, best_span = q, span
    return best


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_arg(call: ast.Call, index: int = 0) -> Optional[str]:
    """The ``index``-th positional arg if it is a string literal."""
    if len(call.args) > index and isinstance(call.args[index], ast.Constant) \
            and isinstance(call.args[index].value, str):
        return call.args[index].value
    return None


# ------------------------------------------------------------------ orchestration
def run_checkers(ctx: AnalysisContext, names: Optional[List[str]] = None):
    """Run the selected (default: all) checkers. Returns
    ``(findings, per_checker_counts)`` with parse errors folded in."""
    # checkers self-register on import; do it lazily so the framework module
    # stays importable without the checker set (unit tests stub their own)
    from . import checkers  # noqa: F401

    selected = names or sorted(CHECKERS)
    unknown = [n for n in selected if n not in CHECKERS]
    if unknown:
        raise KeyError(f"unknown checker(s) {unknown}; have {sorted(CHECKERS)}")
    findings: List[Finding] = []
    per: Dict[str, int] = {}
    for name in selected:
        got = list(CHECKERS[name].run(ctx))
        per[name] = len(got)
        findings.extend(got)
    if ctx.parse_errors:
        findings.extend(ctx.parse_errors)
        per["parse-error"] = len(ctx.parse_errors)
    return findings, per
