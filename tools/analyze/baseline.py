"""Baseline ratchet: freeze existing findings, fail only on NEW ones.

``BASELINE.json`` (committed next to this module) maps finding fingerprints to
a one-line justification. The ratchet contract:

- a finding whose fingerprint (+ occurrence slot, for repeated identical
  constructs in one scope) appears in the baseline is **baselined** — reported
  but not failing;
- a finding not in the baseline is **new** — the run fails (rc=1);
- a baseline entry no longer matched by any finding is **stale** — surfaced as
  a warning so dead entries get pruned, never a failure (deleting fixed code
  must not break the build).

Fingerprints exclude line numbers (see :class:`tools.analyze.Finding`), so the
ratchet survives unrelated edits; they include a snippet of the offending
construct, so fixing the construct retires the entry.

``--write-baseline`` regenerates the file from the current findings,
preserving justifications for fingerprints that already had one and stamping
``"TODO: justify"`` on new entries — the diff review is where the
justification gets written, on purpose.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

DEFAULT_BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                     "BASELINE.json")
_TODO = "TODO: justify"


def load_baseline(path: str = DEFAULT_BASELINE_PATH) -> Dict:
    """{"version": 1, "entries": {fingerprint: {"count", "justification",
    "rule", "file", "scope", "message"}}} — missing file = empty baseline."""
    if not os.path.isfile(path):
        return {"version": 1, "entries": {}}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data.get("entries"), dict):
        raise ValueError(f"malformed baseline {path}: no 'entries' object")
    return data


def apply_baseline(findings: List, baseline: Dict) -> Tuple[List, int, List[Dict]]:
    """Split ``findings`` against the ratchet.

    Returns ``(new_findings, baselined_count, stale_entries)``. An entry's
    ``count`` allows that many identical-fingerprint findings (repeated
    identical constructs in one scope hash alike); finding N+1 is new.
    """
    entries = baseline.get("entries", {})
    seen: Dict[str, int] = {}
    new, baselined = [], 0
    for f in findings:
        fp = f.fingerprint
        seen[fp] = seen.get(fp, 0) + 1
        allowed = int(entries.get(fp, {}).get("count", 0))
        if seen[fp] <= allowed:
            baselined += 1
        else:
            new.append(f)
    stale = []
    for fp, entry in entries.items():
        missing = int(entry.get("count", 1)) - seen.get(fp, 0)
        if missing > 0:
            stale.append({"fingerprint": fp, "missing": missing,
                          **{k: entry.get(k) for k in ("rule", "file", "scope", "message")}})
    return new, baselined, stale


def write_baseline(findings: List, path: str = DEFAULT_BASELINE_PATH,
                   previous: Dict = None, keep_entry=None) -> Dict:
    """Freeze ``findings`` as the new baseline, carrying over justifications
    from ``previous`` (default: whatever is on disk) by fingerprint.

    ``keep_entry(entry) -> bool`` preserves prior entries verbatim even when
    no current finding matches them — the runner passes it on a filtered
    ``--checker`` run so freezing one checker's findings cannot wipe every
    other checker's (justified) entries."""
    prev_entries = (previous if previous is not None else load_baseline(path)).get("entries", {})
    entries: Dict[str, Dict] = {}
    if keep_entry is not None:
        for fp, entry in prev_entries.items():
            if keep_entry(entry):
                entries[fp] = dict(entry)
    for f in findings:
        fp = f.fingerprint
        if fp in entries:
            entries[fp]["count"] += 1
            continue
        just = prev_entries.get(fp, {}).get("justification", _TODO)
        entries[fp] = {"rule": f.rule, "file": f.file, "scope": f.scope,
                       "message": f.message, "count": 1, "justification": just}
    data = {"version": 1, "entries": dict(sorted(entries.items()))}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=False)
        f.write("\n")
    return data
