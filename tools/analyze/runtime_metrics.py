"""Runtime half of the metrics lint — the part that NEEDS the real catalog.

The static ``metrics-catalog`` checker (checkers/catalogs.py) validates metric
*names* without importing anything heavy; this module instantiates the actual
metric objects — ``ServingMetrics`` on a stub engine, ``RouterMetrics``, the
SLO tracker, the training catalog — renders the Prometheus exposition and
lints it (HELP/TYPE present, bucket hygiene, federation merge). That requires
importing ``paddlenlp_tpu`` (jax and all), so it is deliberately NOT a
registered checker: ``python -m tools.analyze`` stays jax-free and <1s, while
``tools/check_metrics.py`` (a thin shim over this module) runs the runtime
lint in its own tier-1-enforced subprocess.
"""

from __future__ import annotations


def _stub_engine():
    """Just enough engine surface for ServingMetrics' pull-mode gauges."""

    class _Mgr:
        num_free = 42
        total_usable_blocks = 64
        max_blocks_per_seq = 8
        num_cached_blocks = 3
        cache_hits = 0
        cached_tokens_total = 0
        evictions = 0

    class _Backend:
        @staticmethod
        def describe():
            # a sharded-shaped describe() so the per-axis mesh gauge's labeled
            # exposition path is linted too
            return {"kind": "sharded", "devices": 8, "tp_degree": 4,
                    "mesh": {"dp": 2, "tp": 4}}

    class _Engine:
        mgr = _Mgr()
        waiting = []
        slots = [None] * 4
        max_batch_size = 4
        spec_stats = {"drafted": 0, "accepted": 0}
        chunk_stats = {"chunks": 0, "chunk_tokens": 0}
        recent_chunk_sizes = []  # (seq, n_tokens) chunked-prefill event ring
        recent_decode_stalls = []  # (seq, seconds)
        recent_step_times = []  # (seq, gap_s, device_s, host_s) anatomy ring
        backend = _Backend()

        def __init__(self):
            # a real ledger so the goodput pull gauges exercise their actual
            # read paths (ratio / NaN-MFU / shape-bucket cardinality)
            from paddlenlp_tpu.observability.goodput import GoodputLedger

            self.ledger = GoodputLedger()

        @staticmethod
        def kv_fragmentation():
            return 0.25

    return _Engine()


def catalog_exposition() -> str:
    """Render the full serving + router + SLO + training metric catalog from a
    fresh registry."""
    from paddlenlp_tpu.observability.exporter import TRACES_DROPPED_METRIC
    from paddlenlp_tpu.observability.slo import SLOInputs, SLOTracker
    from paddlenlp_tpu.serving.engine_loop import ServingMetrics
    from paddlenlp_tpu.serving.metrics import MetricsRegistry
    from paddlenlp_tpu.serving.router.metrics import AutoscalerMetrics, RouterMetrics
    from paddlenlp_tpu.trainer.integrations import register_training_metrics

    registry = MetricsRegistry()
    serving = ServingMetrics(_stub_engine(), registry=registry)
    router = RouterMetrics(registry)
    autoscaler = AutoscalerMetrics(registry)
    # labeled series expose no samples until touched — exercise one labelset
    # of each so the lint sees real sample lines, not just HELP/TYPE headers
    serving.latency_attribution.observe(0.01, phase="queue")
    serving.shed.inc(reason="shed", priority="best_effort", tenant="default")
    serving.requests.inc(status="stop", priority="interactive", tenant="default")
    serving.wasted_tokens.inc(3, kind="padding")
    serving.compiles.inc(program="prefill")
    serving.compile_seconds.inc(0.5, program="prefill")
    serving.step_gap.observe(0.002)
    serving.usage_tokens.inc(5, tenant="default", adapter="base", kind="prompt")
    serving.usage_records.inc(tenant="default")
    serving.weights_info.set(1.0, version="v0")
    router.latency_attribution.observe(0.02, phase="hedge_race")
    router.replica_healthy.set(1.0, replica="replica-0")
    router.requests.inc(replica="replica-0", outcome="ok")
    router.health_polls.inc(replica="replica-0", outcome="ok")
    router.fleet_scrape_errors.inc(replica="replica-0")
    router.hedges.inc(outcome="brownout")
    autoscaler.decisions.inc(action="up")
    slo = SLOTracker(registry=registry)
    slo.observe(SLOInputs(total=10.0, errors=1.0, ttft_count=10.0,
                          ttft_violations=2.0), now=100.0)
    slo.report(now=100.0)  # populates the per-window gauge labelsets
    registry.counter(TRACES_DROPPED_METRIC,
                     "Spans evicted from the bounded trace ring (oldest-first overflow)")
    register_training_metrics(registry)
    return registry.expose()


def federation_problems() -> list:
    """Lint the federated-exposition path: merge two synthetic replica
    catalogs through ``federate_expositions`` and run both the standard
    exposition lint over the merge and ``lint_federation`` over the inputs
    (duplicate-family TYPE conflicts, pre-existing ``replica`` labels)."""
    from paddlenlp_tpu.observability import lint_exposition
    from paddlenlp_tpu.serving.engine_loop import ServingMetrics
    from paddlenlp_tpu.serving.metrics import MetricsRegistry
    from paddlenlp_tpu.serving.router.metrics import federate_expositions, lint_federation

    expositions = {}
    for rid in ("replica-0", "replica-1"):
        registry = MetricsRegistry()
        metrics = ServingMetrics(_stub_engine(), registry=registry)
        metrics.requests.inc(status="stop", priority="interactive", tenant="default")
        metrics.ttft.observe(0.05)
        expositions[rid] = registry.expose()
    problems = [f"federation: {p}" for p in lint_federation(expositions)]
    merged = federate_expositions(expositions)
    problems += [f"federated exposition: {p}" for p in lint_exposition(merged)]
    return problems
