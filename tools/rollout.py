"""Fleet weight-rollout driver: the zero-downtime hot-swap plane as an
operator CLI.

Submits ``POST /admin/weights/rollout`` to a running router and follows the
rollout to its terminal state, emitting one JSONL decision line per observed
transition (submitted, per-replica completion, skew detection, terminal).
Exit code ``0`` when the rollout lands, ``1`` when it aborts and rolls back
(or ``--abort-on-skew`` rolled the fleet back), ``2`` on usage errors.

Stdlib-only on purpose — this talks to the router over HTTP exactly like any
external orchestrator would::

    python tools/rollout.py --router 127.0.0.1:8010 \\
        --ckpt-dir /ckpts/step-9000 --rollback-ckpt-dir /ckpts/step-8000 \\
        --canary-digest 547d0132... --abort-on-skew

``--canary-digest`` pins the cross-replica canary reference (otherwise the
first swapped replica's digest becomes it). ``--abort-on-skew`` watches the
router's ``paddlenlp_router_version_skew_total`` counter during the rollout:
any client stream terminated for version skew marks the rollout harmful, and
once it lands the fleet is rolled BACK to ``--rollback-ckpt-dir`` (rc 1).
"""

from __future__ import annotations

import argparse
import http.client
import json
import sys
import time


def parse_args(argv):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--router", required=True, help="router HOST:PORT")
    ap.add_argument("--ckpt-dir", required=True,
                    help="committed checkpoint directory to roll out")
    ap.add_argument("--version", default=None,
                    help="weights version label (default: ckpt dir basename)")
    ap.add_argument("--rollback-ckpt-dir", default=None,
                    help="checkpoint already-swapped replicas reload on abort")
    ap.add_argument("--canary-digest", default=None,
                    help="expected canary token digest (pins the reference "
                         "every replica must reproduce)")
    ap.add_argument("--mode", default=None,
                    choices=("finish_old", "pause_resume"),
                    help="in-flight handling during each replica's swap")
    ap.add_argument("--drain-deadline", type=float, default=30.0)
    ap.add_argument("--rejoin-timeout", type=float, default=30.0)
    ap.add_argument("--swap-timeout", type=float, default=120.0)
    ap.add_argument("--poll-interval", type=float, default=0.2,
                    help="rollout status poll cadence, seconds")
    ap.add_argument("--abort-on-skew", action="store_true",
                    help="roll the fleet back (rc 1) if any stream was "
                         "terminated with finish_reason=version_skew during "
                         "the rollout (requires --rollback-ckpt-dir)")
    return ap.parse_args(argv)


def _request(host, port, method, path, payload=None, timeout=60.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = None if payload is None else json.dumps(payload).encode()
        headers = {} if body is None else {"Content-Type": "application/json"}
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _get_json(host, port, path, timeout=60.0):
    status, raw = _request(host, port, "GET", path, timeout=timeout)
    return status, json.loads(raw or b"{}")


def _skew_count(host, port) -> float:
    """Current value of the router's version-skew termination counter (0.0
    when the scrape fails or the series has not been incremented yet)."""
    try:
        status, raw = _request(host, port, "GET", "/metrics", timeout=30.0)
    except OSError:
        return 0.0
    if status != 200:
        return 0.0
    for line in raw.decode("utf-8", "replace").splitlines():
        if line.startswith("paddlenlp_router_version_skew_total"):
            try:
                return float(line.rsplit(None, 1)[1])
            except (IndexError, ValueError):
                return 0.0
    return 0.0


def _decision(event: str, **fields):
    print(json.dumps({"t": round(time.time(), 3), "event": event, **fields}),
          flush=True)


def _follow(host, port, poll_interval, *, watch_skew, skew_base):
    """Poll the rollout to a terminal state, emitting a decision line per
    replica completion. Returns (final_state_doc, skew_seen)."""
    seen_done, seen_skipped = set(), set()
    skew_seen = False
    while True:
        status, doc = _get_json(host, port, "/admin/weights/rollout")
        state = (doc or {}).get("rollout")
        if status != 200 or not state:
            _decision("poll_error", status=status)
            time.sleep(poll_interval)
            continue
        for rid in state.get("completed", []):
            if rid not in seen_done:
                seen_done.add(rid)
                _decision("replica_done", replica=rid, version=state["version"])
        for rid in state.get("skipped", []):
            if rid not in seen_skipped:
                seen_skipped.add(rid)
                _decision("replica_skipped", replica=rid,
                          version=state["version"])
        if watch_skew and not skew_seen:
            skew = _skew_count(host, port)
            if skew > skew_base:
                skew_seen = True
                _decision("skew_detected", terminations=skew - skew_base)
        if state.get("status") != "running":
            return state, skew_seen
        time.sleep(poll_interval)


def _submit(host, port, body, poll_interval, *, watch_skew=False, skew_base=0.0):
    status, doc = None, {}
    try:
        status, raw = _request(host, port, "POST", "/admin/weights/rollout",
                               body, timeout=60.0)
        doc = json.loads(raw or b"{}")
    except (OSError, ValueError) as e:
        _decision("submit_error", error=repr(e))
        return None, False
    if status != 200:
        _decision("submit_rejected", status=status, response=doc)
        return None, False
    _decision("submitted", version=doc["rollout"]["version"],
              replicas=doc["rollout"]["replicas"])
    return _follow(host, port, poll_interval,
                   watch_skew=watch_skew, skew_base=skew_base)


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    host, _, port_s = args.router.partition(":")
    if not port_s or not port_s.isdigit():
        print(json.dumps({"error": f"--router must be HOST:PORT, got {args.router!r}"}))
        return 2
    if args.abort_on_skew and not args.rollback_ckpt_dir:
        print(json.dumps({"error": "--abort-on-skew requires --rollback-ckpt-dir"}))
        return 2
    port = int(port_s)

    body = {"ckpt_dir": args.ckpt_dir,
            "drain_deadline_s": args.drain_deadline,
            "rejoin_timeout_s": args.rejoin_timeout,
            "swap_timeout_s": args.swap_timeout}
    for key, val in (("version", args.version),
                     ("rollback_ckpt_dir", args.rollback_ckpt_dir),
                     ("canary_digest", args.canary_digest),
                     ("mode", args.mode)):
        if val is not None:
            body[key] = val

    skew_base = _skew_count(host, port) if args.abort_on_skew else 0.0
    state, skew_seen = _submit(host, port, body, args.poll_interval,
                               watch_skew=args.abort_on_skew,
                               skew_base=skew_base)
    if state is None:
        return 2
    _decision("terminal", status=state["status"], version=state["version"],
              completed=state.get("completed", []),
              rolled_back=state.get("rolled_back", []),
              abort_reason=state.get("abort_reason"), wall_s=state.get("wall_s"))
    if state["status"] != "done":
        return 1
    if skew_seen:
        # the rollout landed but cost live client streams: treat it as
        # harmful and converge the fleet back onto the rollback checkpoint
        _decision("skew_rollback_start", ckpt_dir=args.rollback_ckpt_dir)
        back, _ = _submit(host, port,
                          {"ckpt_dir": args.rollback_ckpt_dir,
                           "drain_deadline_s": args.drain_deadline,
                           "rejoin_timeout_s": args.rejoin_timeout,
                           "swap_timeout_s": args.swap_timeout},
                          args.poll_interval)
        _decision("skew_rollback_done",
                  status=None if back is None else back["status"])
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
