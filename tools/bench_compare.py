"""Perf-regression gate over two ``bench_serve.py`` JSON lines.

Compares a CANDIDATE bench record against a BASELINE (default: the committed
``tools/BENCH_BASELINE.json``) with per-field tolerance bands and exits
nonzero on regression — the first consumer of the goodput-ledger fields and
the seed of the BENCH trajectory gate:

- throughput (``value`` req/s, ``tokens_per_sec``) must hold a fraction of
  baseline (``--min-throughput-ratio``, default 0.5 — CPU smoke numbers are
  noisy; the gate catches collapses, not jitter);
- latency tails (``p99_ttft_ms``, ``p99_inter_token_ms``,
  ``goodput.step_gap_p99_ms``) may grow by ``--max-latency-ratio`` (default
  2.5x) plus an absolute ``--latency-slack-ms`` floor (tiny baselines must
  not gate on scheduler noise);
- ``goodput.ratio`` may drop at most ``--max-goodput-drop`` (default 0.10,
  absolute) — the deterministic device-efficiency gate: a chunk-size or
  bucketing change that silently doubles padding fails here even when
  wall-clock noise hides it;
- the waste share (``sum(goodput.wasted_tokens) / goodput.fed_tokens``) may
  grow at most ``--max-waste-growth`` (default 0.10, absolute);
- ``goodput.compiles`` may grow to ``max(2x baseline, baseline + 8)`` —
  the compile-cache regression gate (a retrace storm fails before it ever
  shows up in latency);
- when the candidate carries a ``rollout`` record (``--swap-mid-run``),
  ``rollout.streams_lost`` must be exactly 0 — zero-downtime is an invariant,
  not a tolerance — and ``rollout.ttft_p99_during_swap_ms`` rides the same
  latency band, anchored on the baseline's own swap tail when present and on
  its overall ``p99_ttft_ms`` otherwise;
- when the candidate carries a ``multi_turn`` record (``--multi-turn K``),
  three invariants gate the conversation-lifetime hierarchy regardless of
  baseline: every turn >= 2 must show a cache-hit rate > 0 (a returning
  conversation that re-prefills its whole history is a cache regression, not
  noise), the last turn's TTFT must beat turn 1's (the whole point of
  keeping the history warm), and ``host_spills`` must be > 0 (the bench
  forces HBM pressure; zero spills means the pressure schedule broke and the
  hit rate proves nothing about the host tier).

Usage::

    python tools/bench_serve.py > /tmp/candidate.json
    python tools/bench_compare.py /tmp/candidate.json            # vs committed baseline
    python tools/bench_compare.py /tmp/candidate.json /tmp/base.json
    python tools/bench_serve.py | python tools/bench_compare.py -  # stdin candidate

Prints ONE JSON line ``{"ok": bool, "compared": N, "regressions": [...],
"skipped": [...]}``; rc 0 = pass, rc 1 = regression, rc 2 = usage/parse
error. Fields missing on either side are skipped (reported, not fatal) so
the gate tolerates bench-flag drift between the two records — but ZERO
comparable fields is rc 2: a gate that never ran must never read as passed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_BASELINE.json")


def _fail_usage(msg: str) -> None:
    print(json.dumps({"ok": False, "error": msg}))
    sys.exit(2)


class _JsonArgumentParser(argparse.ArgumentParser):
    """argparse with the tool's one-JSON-line error contract: an unknown or
    malformed flag prints ``{"ok": false, "error": ...}`` and exits 2 (a
    typo'd tolerance must never run the gate with defaults)."""

    def error(self, message):
        _fail_usage(message)


def load_record(source: str) -> Dict:
    """A bench record from a file path (last JSON-looking line wins — the
    bench prints exactly one, but logs may precede it) or '-' for stdin."""
    if source == "-":
        text = sys.stdin.read()
    else:
        try:
            with open(source, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            _fail_usage(f"cannot read {source!r}: {e}")
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError as e:
                _fail_usage(f"{source!r}: bad JSON line: {e}")
    _fail_usage(f"{source!r} contains no JSON line")
    raise AssertionError  # unreachable


def _get(record: Dict, dotted: str) -> Optional[float]:
    node = record
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def _waste_share(record: Dict) -> Optional[float]:
    fed = _get(record, "goodput.fed_tokens")
    wasted = record.get("goodput", {}).get("wasted_tokens")
    if fed is None or fed <= 0 or not isinstance(wasted, dict):
        return None
    return sum(v for v in wasted.values() if isinstance(v, (int, float))) / fed


def compare(candidate: Dict, baseline: Dict,
            min_throughput_ratio: float = 0.5,
            max_latency_ratio: float = 2.5,
            latency_slack_ms: float = 50.0,
            max_goodput_drop: float = 0.10,
            max_waste_growth: float = 0.10,
            ) -> Tuple[List[Dict], List[str], int]:
    """Returns ``(regressions, skipped_fields, compared_count)``. Pure so the
    tier-1 gate test drives it directly on synthetic records."""
    regressions: List[Dict] = []
    skipped: List[str] = []
    compared = 0

    def check(field: str, limit: float, direction: str,
              cand: Optional[float], base: Optional[float]):
        nonlocal compared
        if cand is None or base is None:
            skipped.append(field)
            return
        compared += 1
        bad = cand < limit if direction == "min" else cand > limit
        if bad:
            regressions.append({
                "field": field, "baseline": base, "candidate": cand,
                "limit": round(limit, 6),
                "direction": "below" if direction == "min" else "above"})

    for field in ("value", "tokens_per_sec"):
        base = _get(baseline, field)
        check(field, (base or 0.0) * min_throughput_ratio, "min",
              _get(candidate, field), base)
    for field in ("p99_ttft_ms", "p99_inter_token_ms", "goodput.step_gap_p99_ms"):
        base = _get(baseline, field)
        if base is not None:
            limit = base * max_latency_ratio + latency_slack_ms
        else:
            limit = 0.0
        check(field, limit, "max", _get(candidate, field), base)
    base_ratio = _get(baseline, "goodput.ratio")
    check("goodput.ratio", (base_ratio or 0.0) - max_goodput_drop, "min",
          _get(candidate, "goodput.ratio"), base_ratio)
    base_waste = _waste_share(baseline)
    check("goodput.waste_share",
          (base_waste if base_waste is not None else 0.0) + max_waste_growth,
          "max", _waste_share(candidate), base_waste)
    base_compiles = _get(baseline, "goodput.compiles")
    if base_compiles is not None:
        limit = max(base_compiles * 2.0, base_compiles + 8.0)
    else:
        limit = 0.0
    check("goodput.compiles", limit, "max",
          _get(candidate, "goodput.compiles"), base_compiles)
    # rollout arm (--swap-mid-run): streams_lost is an invariant, not a
    # tolerance — ANY stream lost to the hot-swap is a regression regardless
    # of what the baseline recorded
    if isinstance(candidate.get("rollout"), dict):
        lost = _get(candidate, "rollout.streams_lost")
        if lost is None:
            skipped.append("rollout.streams_lost")
        else:
            compared += 1
            if lost > 0:
                regressions.append({
                    "field": "rollout.streams_lost", "baseline": 0.0,
                    "candidate": lost, "limit": 0.0, "direction": "above"})
        base_swap = _get(baseline, "rollout.ttft_p99_during_swap_ms")
        if base_swap is None:
            # baseline ran without the arm: its overall TTFT tail still
            # bounds how much the swap window is allowed to cost
            base_swap = _get(baseline, "p99_ttft_ms")
        check("rollout.ttft_p99_during_swap_ms",
              (base_swap or 0.0) * max_latency_ratio + latency_slack_ms, "max",
              _get(candidate, "rollout.ttft_p99_during_swap_ms"), base_swap)
    # multi-turn arm (--multi-turn K): conversation-lifetime invariants, all
    # baseline-independent — the candidate record alone either demonstrates
    # the hierarchical cache worked or it doesn't
    if isinstance(candidate.get("multi_turn"), dict):
        mt = candidate["multi_turn"]
        rates = mt.get("per_turn_cache_hit_rate")
        if not isinstance(rates, list) or len(rates) < 2:
            skipped.append("multi_turn.per_turn_cache_hit_rate")
        else:
            compared += 1
            cold = [i + 1 for i, r in enumerate(rates[1:], start=1) if not r > 0]
            if cold:
                regressions.append({
                    "field": "multi_turn.per_turn_cache_hit_rate",
                    "baseline": None, "candidate": rates, "limit": 0.0,
                    "direction": "below",
                    "detail": f"turns {cold} re-prefilled with zero cache hits"})
        turn1 = _get(candidate, "multi_turn.ttft_turn1_ms")
        turnk = _get(candidate, "multi_turn.ttft_turnk_ms")
        if turn1 is None or turnk is None:
            skipped.append("multi_turn.ttft_turnk_ms")
        else:
            compared += 1
            if turnk >= turn1:
                regressions.append({
                    "field": "multi_turn.ttft_turnk_ms", "baseline": turn1,
                    "candidate": turnk, "limit": round(turn1, 6),
                    "direction": "above",
                    "detail": "warm turn-k TTFT did not beat cold turn-1 TTFT"})
        spills = _get(candidate, "multi_turn.host_spills")
        if spills is None:
            skipped.append("multi_turn.host_spills")
        else:
            compared += 1
            if spills <= 0:
                regressions.append({
                    "field": "multi_turn.host_spills", "baseline": None,
                    "candidate": spills, "limit": 0.0, "direction": "below",
                    "detail": "no HBM pressure reached the host tier — "
                              "hit rates prove nothing about spill/promote"})
    return regressions, skipped, compared


def main() -> None:
    parser = _JsonArgumentParser(
        prog="bench_compare.py", allow_abbrev=False,
        description="Gate a bench_serve JSON line against a baseline record.")
    parser.add_argument("candidate", help="candidate record file, or - for stdin")
    parser.add_argument("baseline", nargs="?", default=DEFAULT_BASELINE,
                        help=f"baseline record file (default: {DEFAULT_BASELINE})")
    parser.add_argument("--min-throughput-ratio", type=float, default=0.5)
    parser.add_argument("--max-latency-ratio", type=float, default=2.5)
    parser.add_argument("--latency-slack-ms", type=float, default=50.0)
    parser.add_argument("--max-goodput-drop", type=float, default=0.10)
    parser.add_argument("--max-waste-growth", type=float, default=0.10)
    opts = parser.parse_args()
    candidate = load_record(opts.candidate)
    baseline = load_record(opts.baseline)
    if candidate.get("error") or baseline.get("error"):
        _fail_usage("cannot gate on a failed bench record "
                    f"(candidate error={candidate.get('error')!r}, "
                    f"baseline error={baseline.get('error')!r})")
    regressions, skipped, compared = compare(
        candidate, baseline,
        min_throughput_ratio=opts.min_throughput_ratio,
        max_latency_ratio=opts.max_latency_ratio,
        latency_slack_ms=opts.latency_slack_ms,
        max_goodput_drop=opts.max_goodput_drop,
        max_waste_growth=opts.max_waste_growth)
    if compared == 0:
        # zero overlapping fields = the gate never ran (schema drift, wrong
        # artifact piped in) — that must be a loud failure, not a green pass
        _fail_usage("no comparable fields between candidate and baseline "
                    f"(skipped: {skipped}) — wrong artifact or schema drift")
    print(json.dumps({
        "ok": not regressions,
        "compared": compared,
        "baseline": opts.baseline,
        "regressions": regressions,
        "skipped": skipped,
    }))
    sys.exit(1 if regressions else 0)


if __name__ == "__main__":
    main()
