"""Serving smoke benchmark: N concurrent HTTP requests through the
continuous-batching runtime on CPU.

Prints ONE JSON line — always, in the same always-emit style as bench.py: on
any failure or timeout a structured record with value 0 and an "error" field
is emitted instead of a traceback. CPU-safe by construction (forces
JAX_PLATFORMS=cpu and drops the axon PJRT plugin from the import path before
jax loads, so a wedged TPU tunnel cannot block the run).

Usage::

    python tools/bench_serve.py                  # 16 requests, 8-way concurrency
    python tools/bench_serve.py --requests 32 --concurrency 16 --max-tokens 24
    python tools/bench_serve.py --replicas 2     # router front tier over 2 CPU
                                                 # replicas; the JSON line adds
                                                 # request_share/failovers/rerouted
                                                 # + /fleet/slo readouts (fleet
                                                 # availability, TTFT vs the
                                                 # objective, burn rates)
    python tools/bench_serve.py --prefix-share 0.75
                                                 # 75% of requests reuse one long
                                                 # common prefix; the JSON line's
                                                 # prefix_cache_hit_rate and
                                                 # cached_tokens track the win
    python tools/bench_serve.py --long-prompt-mix --prefill-chunk 64
                                                 # a few multi-thousand-token
                                                 # prompts injected into a stream
                                                 # of short chatty requests; the
                                                 # JSON line folds in client p99
                                                 # TTFT + p99 inter-token (decode
                                                 # stall) — rerun with
                                                 # --prefill-chunk 0 and the
                                                 # chunked-vs-monolithic tail is
                                                 # one flag flip to compare.
                                                 # (64 is the CPU-smoke sweet
                                                 # spot; 256-512 suits real TPU
                                                 # runs. Mixed steps default to
                                                 # the token-flattened layout
                                                 # off-TPU — cost scales with
                                                 # tokens actually fed;
                                                 # --token-flatten 0 forces the
                                                 # old padded B*chunk launch
                                                 # for an A/B)
    python tools/bench_serve.py --mesh-shape 2,4 # tensor-parallel sharded
                                                 # engine on a dp=2 x tp=4 mesh
                                                 # of virtual CPU devices —
                                                 # weights + KV pool sharded on
                                                 # tp; JSON adds mesh_shape/
                                                 # tp_degree (composes with
                                                 # --prefill-chunk and
                                                 # --prefix-share)
    python tools/bench_serve.py --adapters 3 --tenant-mix
                                                 # multi-tenant multi-LoRA arm:
                                                 # 3 rank-4 adapters registered
                                                 # in the engine's adapter pool;
                                                 # 3 of 4 requests decode with
                                                 # an adapter (round-robin), the
                                                 # 4th rides the base model in
                                                 # the SAME batches; --tenant-mix
                                                 # spreads requests over three
                                                 # tenants. JSON adds
                                                 # adapter_hit_rate /
                                                 # adapter_evictions + a
                                                 # multi_lora record and a
                                                 # per-tenant requests/shed
                                                 # breakdown. --adapters 6
                                                 # overcommits the 4-slot pool
                                                 # so LRU hot-load/evict churn
                                                 # shows up in the numbers. The
                                                 # default (no-adapter) arm is
                                                 # the one gated against
                                                 # tools/BENCH_BASELINE.json
    python tools/bench_serve.py --replicas 3 --drain-mid-run
                                                 # halfway through the request
                                                 # stream, drain one replica via
                                                 # the router admin plane (POST
                                                 # /replicas/drain → DELETE) —
                                                 # the JSON line adds drained_ok
                                                 # plus the failovers/hedges the
                                                 # churn caused, so elasticity
                                                 # shows up in the bench
                                                 # trajectory
    python tools/bench_serve.py --replicas 2 --swap-mid-run
                                                 # halfway through the request
                                                 # stream, roll a new base
                                                 # checkpoint across the fleet
                                                 # (POST /admin/weights/rollout:
                                                 # drain -> swap -> canary ->
                                                 # rejoin, one replica at a
                                                 # time) while requests keep
                                                 # flowing — the JSON line adds
                                                 # a rollout record (wall_s,
                                                 # streams_lost which must be 0,
                                                 # p99 TTFT during the swap
                                                 # window) so zero-downtime is a
                                                 # gateable number
    python tools/bench_serve.py --replicas 2 --hedge-after-ms 250
                                                 # arm request hedging: a stream
                                                 # (or batch request) with no
                                                 # first token inside the budget
                                                 # races a shadow on the next
                                                 # replica; JSON adds hedges
                                                 # (total fired/capped)
    PDNLP_TPU_FLIGHT_RECORDER=0 python tools/bench_serve.py
                                                 # flight recorder disabled:
                                                 # rerun without the env var
                                                 # and diff value/tails — the
                                                 # recorder-overhead A/B. The
                                                 # JSON line always carries
                                                 # flight_recorder (on/off) +
                                                 # flight_events, and an
                                                 # `attribution` record with
                                                 # per-phase p50/p99 (queue/
                                                 # admission_gate/prefill/
                                                 # chunk_stall/migration_wait/
                                                 # decode) so a BENCH_r*
                                                 # regression localizes to a
                                                 # phase, not just a number
    python tools/bench_serve.py --surge 1,6,8 --autoscale 1,3
                                                 # closed-loop demo: open-loop
                                                 # arrivals ramp 1 -> 6 req/s
                                                 # over 8s (flat shoulders
                                                 # before/after) while the
                                                 # in-process autoscaler
                                                 # watches /fleet/slo +
                                                 # /replicas and drives the
                                                 # admin plane inside a 1..3
                                                 # replica envelope. 1 in 4
                                                 # requests is best_effort —
                                                 # at the max envelope the
                                                 # brownout ladder sheds them
                                                 # while interactive TTFT
                                                 # holds. JSON adds surge
                                                 # (per-phase p99 TTFT, shed/
                                                 # rejected counts, SLO burn
                                                 # trajectory) + autoscale
                                                 # (scale events, final
                                                 # replica count)
    python tools/bench_serve.py --multi-turn 4   # conversation-lifetime arm:
                                                 # 16 conversations of 4 chat
                                                 # turns each through
                                                 # /v1/chat/completions, turn 1
                                                 # opening with a long (64-tok)
                                                 # user message. The engine runs
                                                 # with a deliberately small
                                                 # device KV pool + a host spill
                                                 # tier (host_kv_blocks), so
                                                 # between a conversation's
                                                 # turns the OTHER conversations
                                                 # churn its cached blocks out
                                                 # to host RAM — turn k's
                                                 # history promotes back H2D
                                                 # ahead of prefill. JSON adds a
                                                 # multi_turn record (per-turn
                                                 # cache-hit rate, TTFT turn 1
                                                 # vs turn k, spill/promote
                                                 # counts + promote bandwidth)
                                                 # that tools/bench_compare.py
                                                 # gates: hit rate > 0 on turns
                                                 # >= 2 and turn-k TTFT below
                                                 # turn-1 TTFT
    python tools/bench_serve.py --disagg 2,2 --long-prompt-mix --prefill-chunk 64
                                                 # disaggregated prefill/decode
                                                 # engine: prompt work on a
                                                 # 2-device prefill stage,
                                                 # decode on a 2-device decode
                                                 # stage, KV blocks migrating
                                                 # between stage pools. JSON
                                                 # adds a disagg record with
                                                 # per-stage TTFT / inter-token
                                                 # tails + migration counts —
                                                 # compare against
                                                 # --mesh-shape 1,4 (shared
                                                 # pool) with one flag flip
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

METRIC = "serve_smoke_requests_per_sec"
UNIT = "requests/sec (tiny-llama CPU serving smoke)"
RUN_TIMEOUT_S = float(os.environ.get("PDNLP_BENCH_SERVE_TIMEOUT", 600))


def _fail(reason: str) -> None:
    print(json.dumps({"metric": METRIC, "value": 0.0, "unit": UNIT, "error": reason[:2000]}))
    sys.exit(1)


def _parse_mesh_shape():
    """``--mesh-shape R,C`` (dp x tp) or ``--mesh-shape T`` (tp only)."""
    if "--mesh-shape" not in sys.argv:
        return None
    raw = sys.argv[sys.argv.index("--mesh-shape") + 1]
    parts = [int(x) for x in raw.split(",")]
    if len(parts) == 1:
        parts = [1, parts[0]]
    if len(parts) != 2 or any(p < 1 for p in parts):
        _fail(f"--mesh-shape must be T or R,C with positive degrees, got {raw!r}")
    return tuple(parts)


def _parse_disagg():
    """``--disagg P,D``: device counts for the prefill / decode stages."""
    if "--disagg" not in sys.argv:
        return None
    raw = sys.argv[sys.argv.index("--disagg") + 1]
    parts = [int(x) for x in raw.split(",")]
    if len(parts) != 2 or any(p < 1 for p in parts):
        _fail(f"--disagg must be P,D with positive device counts, got {raw!r}")
    return tuple(parts)


def _parse_surge():
    """``--surge R1,R2,T``: open-loop arrival rate ramping R1 -> R2 req/s
    over T seconds (flat R1 shoulders of T/2 before and after)."""
    if "--surge" not in sys.argv:
        return None
    raw = sys.argv[sys.argv.index("--surge") + 1]
    parts = [float(x) for x in raw.split(",")]
    if len(parts) != 3 or parts[0] <= 0 or parts[1] <= 0 or parts[2] <= 0:
        _fail(f"--surge must be R1,R2,T with positive values, got {raw!r}")
    return tuple(parts)


def _parse_autoscale():
    """``--autoscale MIN,MAX``: run the in-process autoscaler in the loop."""
    if "--autoscale" not in sys.argv:
        return None
    raw = sys.argv[sys.argv.index("--autoscale") + 1]
    parts = [int(x) for x in raw.split(",")]
    if len(parts) != 2 or not 1 <= parts[0] <= parts[1]:
        _fail(f"--autoscale must be MIN,MAX with 1 <= MIN <= MAX, got {raw!r}")
    return tuple(parts)


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    mesh = _parse_mesh_shape()
    disagg = _parse_disagg()
    if mesh is not None and disagg is not None:
        _fail("--mesh-shape and --disagg are mutually exclusive (a disagg "
              "stage is itself a sharded device group)")
    n_dev = None
    if mesh is not None:
        n_dev = mesh[0] * mesh[1]
    elif disagg is not None:
        n_dev = disagg[0] + disagg[1]
    if n_dev is not None:
        # the host-device count must be pinned BEFORE jax loads; the virtual
        # CPU devices back the sharded/disagg engine's meshes. Appended so
        # any user-supplied XLA flags survive (last flag wins on duplicates)
        extra = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{extra} --xla_force_host_platform_device_count={n_dev}".strip())
    else:
        os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    sys.path[:] = [p for p in sys.path if "axon" not in p]
    if os.environ.get("PYTHONPATH"):
        os.environ["PYTHONPATH"] = os.pathsep.join(
            p for p in os.environ["PYTHONPATH"].split(os.pathsep) if "axon" not in p)
    import jax

    jax.config.update("jax_platforms", "cpu")


def _arg(flag: str, default: int) -> int:
    if flag in sys.argv:
        return int(sys.argv[sys.argv.index(flag) + 1])
    return default


def _farg(flag: str, default: float) -> float:
    if flag in sys.argv:
        return float(sys.argv[sys.argv.index(flag) + 1])
    return default


def run() -> None:
    _force_cpu()
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
    import http.client
    import threading

    from paddlenlp_tpu.experimental import InferenceEngine
    from paddlenlp_tpu.serving import MetricsRegistry, SchedulerConfig, ServingServer
    from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

    n_requests = _arg("--requests", 16)
    concurrency = _arg("--concurrency", 8)
    max_tokens = _arg("--max-tokens", 16)
    n_replicas = _arg("--replicas", 1)
    drain_mid_run = "--drain-mid-run" in sys.argv
    swap_mid_run = "--swap-mid-run" in sys.argv
    hedge_after_ms = _farg("--hedge-after-ms", 0.0)
    prefix_share = _farg("--prefix-share", 0.0)
    surge = _parse_surge()
    autoscale = _parse_autoscale()
    if autoscale and not surge:
        _fail("--autoscale needs --surge (the control loop reacts to the ramp)")
    if autoscale:
        # the fleet starts at the envelope floor; the autoscaler grows it
        n_replicas = autoscale[0]
    if drain_mid_run and n_replicas < 2:
        _fail("--drain-mid-run needs --replicas >= 2 (one replica must survive)")
    if swap_mid_run and n_replicas < 2:
        _fail("--swap-mid-run needs --replicas >= 2 (the rollout swaps one "
              "replica at a time while the rest keep serving)")
    # --surge R1,R2,T: precompute the open-loop arrival schedule (the ramp
    # integrates the linear rate; flat R1 shoulders bracket it so the JSON
    # can report p99 TTFT before/during/after)
    surge_schedule = []  # (t_offset_s, phase, priority)
    if surge:
        r1, r2, ramp_s = surge
        shoulder = max(ramp_s / 2.0, 2.0)
        t = 0.0
        i = 0
        while t < shoulder:
            surge_schedule.append((t, "before"))
            t += 1.0 / r1
        ramp_t0 = t
        while t - ramp_t0 < ramp_s:
            frac = (t - ramp_t0) / ramp_s
            surge_schedule.append((t, "during"))
            t += 1.0 / (r1 + (r2 - r1) * frac)
        tail_t0 = t
        while t - tail_t0 < shoulder:
            surge_schedule.append((t, "after"))
            t += 1.0 / r1
        # 1 in 4 requests is best_effort: the shed class the brownout ladder
        # drops first when the envelope pins
        surge_schedule = [(off, phase, "best_effort" if i % 4 == 3 else "interactive")
                          for i, (off, phase) in enumerate(surge_schedule)]
        n_requests = len(surge_schedule)
    multi_turn = _arg("--multi-turn", 0)
    if multi_turn:
        if multi_turn < 2:
            _fail(f"--multi-turn must be >= 2 turns, got {multi_turn}")
        if surge or drain_mid_run or swap_mid_run or "--long-prompt-mix" in sys.argv \
                or _parse_disagg() is not None:
            _fail("--multi-turn composes with --replicas/--prefill-chunk/"
                  "--mesh-shape only (not --surge/--drain-mid-run/"
                  "--swap-mid-run/--long-prompt-mix/--disagg)")
    n_adapters = _arg("--adapters", 0)
    tenant_mix = "--tenant-mix" in sys.argv
    tenants = ("acme", "globex", "initech")
    long_mix = "--long-prompt-mix" in sys.argv
    n_long = _arg("--long-prompts", 2)
    long_tokens = _arg("--long-prompt-tokens", 2048)
    prefill_chunk = _arg("--prefill-chunk", 0)
    mesh_shape = _parse_mesh_shape()
    disagg = _parse_disagg()
    token_flatten = (bool(_arg("--token-flatten", 1))
                     if "--token-flatten" in sys.argv else None)
    if not 0.0 <= prefix_share <= 1.0:
        _fail(f"--prefix-share must be in [0, 1], got {prefix_share}")
    # 24 tokens = 6 full blocks at block_size=4: a warm hit skips all of them
    shared_prefix = [9, 8, 7, 6, 5, 4, 3, 2] * 3

    # mesh/disagg runs use a head count the tp axes can divide (8 heads x
    # head_dim 8 instead of 4 x 16) so the KV pool and attention actually shard
    n_heads, n_kv = (8, 8) if (mesh_shape or disagg) else (4, 2)
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=n_heads, num_key_value_heads=n_kv,
                      max_position_embeddings=4096 if long_mix else 256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    model = LlamaForCausalLM.from_config(cfg, seed=0)

    if long_mix:
        # bigger blocks so a multi-thousand-token prompt fits a sane table
        eng_kw = dict(max_batch_size=4, block_size=32, num_blocks=352,
                      max_blocks_per_seq=96, decode_steps=4)
        # over-capacity long prompts would finish 'capacity' with zero tokens
        # over a normal 200 stream — the mix would silently measure nothing
        cap = eng_kw["max_blocks_per_seq"] * eng_kw["block_size"]
        if long_tokens + max_tokens > cap:
            _fail(f"--long-prompt-tokens {long_tokens} + --max-tokens {max_tokens} "
                  f"exceeds the long-mix engine's per-seq KV capacity ({cap} tokens)")
    else:
        eng_kw = dict(max_batch_size=4, block_size=4, num_blocks=256,
                      max_blocks_per_seq=32, decode_steps=4)
    # --multi-turn K: conversations of K chat turns. The device pool is
    # deliberately SMALL relative to the conversations' total cached KV, so
    # finished turns' blocks spill to the host tier under LRU pressure and
    # turn k's history must promote back — the hierarchy is what's measured.
    n_convs = 0
    mt_open_tokens, mt_user_tokens = 64, 4
    if multi_turn:
        n_convs = n_requests
        eng_kw = dict(max_batch_size=4, block_size=4, num_blocks=160,
                      max_blocks_per_seq=48, decode_steps=4,
                      enable_prefix_cache=True, host_kv_blocks=2048)
        # final-turn render: [u]+64+[sep] opener, then per prior turn an
        # assistant ([a]+completion+[sep]) + user ([u]+4+[sep]) pair, + the
        # trailing assistant marker — must fit per-seq KV with the completion
        final_prompt = (2 + mt_open_tokens) \
            + (multi_turn - 1) * (2 + max_tokens + 2 + mt_user_tokens) + 1
        cap = eng_kw["max_blocks_per_seq"] * eng_kw["block_size"]
        if final_prompt + max_tokens > cap:
            _fail(f"--multi-turn {multi_turn} x --max-tokens {max_tokens}: "
                  f"final-turn prompt (~{final_prompt}) + completion exceeds "
                  f"the per-seq KV capacity ({cap} tokens)")
        n_requests = n_convs * multi_turn  # throughput counts every turn
    if prefill_chunk:
        eng_kw["prefill_chunk_tokens"] = prefill_chunk
    if mesh_shape:
        eng_kw["mesh_shape"] = mesh_shape
    if disagg:
        eng_kw["disagg_stages"] = disagg
    if token_flatten is not None:
        eng_kw["token_flatten"] = token_flatten
    # which stream positions carry a long prompt (spread through the run so
    # chatty decodes are always in flight when one lands)
    long_every = max(n_requests // max(n_long, 1), 1)
    # request 0 is the warmup; long prompts land at i = 1, 1+long_every, ...
    # (the i-1 anchor keeps long_every == 1 meaningful: requests 1..n_long)
    is_long = (lambda i: long_mix and i >= 1 and (i - 1) % long_every == 0
               and (i - 1) // long_every < n_long)
    # what the schedule actually issues (i ranges over 0..n_requests-1, so
    # --long-prompts close to --requests can't all land); report THIS count
    n_long_issued = sum(1 for i in range(n_requests) if is_long(i))

    # --adapters N: N deterministic rank-4 LoRA adapters served from the
    # engine's slot pool. pool_slots caps at 4 so N > 4 overcommits the pool
    # and the run exercises LRU hot-load/evict churn, not just warm gathers.
    adapter_registries: list = []
    adapter_pool_slots = min(n_adapters, 4) if n_adapters else 0

    def adapter_source(idx: int) -> dict:
        import numpy as _np

        from paddlenlp_tpu.serving.tenancy.adapters import adapter_dims_from_config

        rng = _np.random.default_rng(1000 + idx)
        src = {}
        for proj, (d_in, d_out) in adapter_dims_from_config(cfg).items():
            src[proj] = {
                "A": rng.standard_normal(
                    (cfg.num_hidden_layers, d_in, 4)).astype(_np.float32) * 0.02,
                "B": rng.standard_normal(
                    (cfg.num_hidden_layers, 4, d_out)).astype(_np.float32) * 0.02,
            }
        return src

    def make_engine():
        # one shared model (read-only params), one engine per replica — except
        # under --swap-mid-run: the hot-swap rebinds model.params, so a shared
        # model object would leak the new weights into replicas that have not
        # swapped yet; each replica gets its own identically-seeded model
        mdl = LlamaForCausalLM.from_config(cfg, seed=0) if swap_mid_run else model
        kw = dict(eng_kw)
        if n_adapters:
            from paddlenlp_tpu.serving.tenancy import AdapterRegistry

            reg = AdapterRegistry(config=cfg, max_rank=4,
                                  pool_slots=adapter_pool_slots)
            for a in range(n_adapters):
                reg.add(f"bench-ad-{a}", adapter_source(a))
            adapter_registries.append(reg)
            kw["adapter_registry"] = reg
        return InferenceEngine(mdl, **kw)

    # --swap-mid-run: commit the two checkpoints the rollout needs BEFORE the
    # timed window (v1 is the new weights, v0 the rollback target) so the
    # measured wall clock holds only the drain/swap/canary/rejoin walk itself
    swap_ckpts: dict = {}
    if swap_mid_run:
        import tempfile

        from paddlenlp_tpu.trainer.unified_checkpoint import save_unified_checkpoint

        ck_root = tempfile.mkdtemp(prefix="bench_swap_ck_")
        for ver, seed in (("v0", 0), ("v1", 1)):
            path = os.path.join(ck_root, ver)
            save_unified_checkpoint(
                path, LlamaForCausalLM.from_config(cfg, seed=seed), None)
            swap_ckpts[ver] = path

    registry = MetricsRegistry()
    fleet = server = None
    if n_replicas > 1 or autoscale:
        # multi-replica mode: the timed window goes through the router front
        # tier, so the measured path includes routing + SSE passthrough
        from paddlenlp_tpu.serving.router import launch_fleet

        fleet = launch_fleet(
            n_replicas, make_engine, policy="least_loaded", router_registry=registry,
            poll_interval_s=0.2,
            hedge_after_s=hedge_after_ms / 1e3 if hedge_after_ms > 0 else None,
            scheduler_config=SchedulerConfig(max_inflight=2 * n_requests))
        port = fleet.router_port
    else:
        server = ServingServer(make_engine(), registry=registry,
                               scheduler_config=SchedulerConfig(max_inflight=2 * n_requests))
        port = server.start_in_thread()

    # warmup: one request pays the jit compiles so the timed window measures
    # steady-state serving, not tracing
    def one_request(i: int, stats: dict):
        t0 = time.time()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=RUN_TIMEOUT_S)
        # --long-prompt-mix: a few multi-thousand-token prompts ride a stream
        # of short chatty requests (the worst decode-stall workload). Unique
        # deterministic token streams keep the prefix cache out of the picture.
        # --prefix-share P: fraction P of requests open with one long common
        # prefix (a system prompt stand-in), so the prefix cache has something
        # to hit; the unique tail keeps every request distinct. The golden-
        # ratio stride spreads the P fraction evenly even for small N
        if i == -1:
            # dedicated long-prompt warmup: same length as the measured long
            # prompts but a distinct token stream (no prefix-cache overlap)
            prompt = [(5 + 3 * j) % 90 + 1 for j in range(long_tokens)]
        elif i < -1:
            # chatty warmup riders: distinct short prompts, never the shared
            # prefix (they must not pre-warm the measured prefix cache)
            prompt = [78 - i, 6, 7]
        elif is_long(i):
            prompt = [(7 * i + 3 * j) % 90 + 1 for j in range(long_tokens)]
        elif (i * 0.6180339887) % 1.0 < prefix_share:
            prompt = shared_prefix + [5 + i % 8, 6, 7]
        else:
            prompt = [5 + i % 8, 6, 7]
        payload = {"prompt": prompt, "max_tokens": max_tokens, "stream": True}
        # 3 of 4 requests decode with an adapter (round-robin over the pool),
        # the 4th stays on the base model — mixed batches are the point; the
        # warmup (i == 0) carries an adapter so the gathered-delta program
        # compiles outside the measured window
        if n_adapters and i >= 0 and i % 4 != 3:
            payload["adapter_id"] = f"bench-ad-{i % n_adapters}"
        if tenant_mix and i >= 0:
            payload["tenant"] = tenants[i % len(tenants)]
        body = json.dumps(payload)
        conn.request("POST", "/v1/completions", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f"request {i}: HTTP {resp.status}")
        n_toks, ttft, last_t = 0, None, None
        gaps = []
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: ") or line == b"data: [DONE]":
                if line == b"data: [DONE]":
                    break
                continue
            ev = json.loads(line[len(b"data: "):])
            if "token" in ev["choices"][0]:
                now = time.time()
                if ttft is None:
                    ttft = now - t0
                else:
                    gaps.append(now - last_t)
                last_t = now
                n_toks += 1
        conn.close()
        stats["ttft"].append(ttft if ttft is not None else float("nan"))
        stats["tokens"] += n_toks
        if not is_long(i):
            # the chatty requests are the decode-stall victims: their token
            # gaps are the p99 the long-prompt mix is trying to protect
            stats["gaps_short"].extend(gaps)

    warm = {"ttft": [], "tokens": 0, "gaps_short": []}
    one_request(0, warm)
    if long_mix:
        # compile the long-prefill path (mixed-step jit / long prefill bucket)
        # outside the measured window: the tail comparison is about steady-state
        # scheduling, not one-time XLA compiles. Short chatty streams ride along
        # so mixed-step shapes with 1..3 concurrent decode rows (every
        # token-flattened segment bucket the measured window will see) compile
        # here too, not inside a measured decode gap
        riders = [threading.Thread(
            target=one_request, args=(-2 - r, {"ttft": [], "tokens": 0, "gaps_short": []}))
            for r in range(3)]
        for t in riders:
            t.start()
        one_request(-1, warm)
        for t in riders:
            t.join()

    # --autoscale: the in-process provisioner + control loop, started after
    # warmup so compile stalls don't read as overload
    scaler = provisioner = None
    if autoscale:
        from paddlenlp_tpu.serving.router.autoscaler import (
            Autoscaler,
            AutoscalerPolicy,
            InProcessProvisioner,
        )

        provisioner = InProcessProvisioner(
            make_engine, replica_kw=dict(
                scheduler_config=SchedulerConfig(max_inflight=2 * n_requests)))
        scaler = Autoscaler(
            ("127.0.0.1", port), provisioner,
            policy=AutoscalerPolicy(
                min_replicas=autoscale[0], max_replicas=autoscale[1],
                scale_up_queue_depth=2.0, scale_up_kv_utilization=0.7,
                scale_down_queue_depth=0.5, scale_down_kv_utilization=0.3,
                hysteresis_up=2, hysteresis_down=4,
                cooldown_up_s=2.0, cooldown_down_s=4.0,
                max_step_up=1, drain_deadline_s=15.0),
            interval_s=0.5)
        scaler.start()

    stats = {"ttft": [], "tokens": 0, "gaps_short": []}
    surge_stats = {"shed": 0, "shed_best_effort": 0, "rejected": 0,
                   "phase_ttft": {"before": [], "during": [], "after": []},
                   "interactive_ttft": []}
    slo_samples: list = []
    lock = threading.Lock()
    errors: list = []
    sem = threading.Semaphore(concurrency)

    # --drain-mid-run: halfway through the request stream, drain the last
    # replica through the router's admin plane (the same POST /replicas/drain
    # → poll → DELETE sequence an autoscaler would issue) while the remaining
    # requests keep flowing — elasticity becomes part of the measured window.
    drain_result: dict = {}

    def drain_worker():
        victim = f"127.0.0.1:{fleet.ports[-1]}"
        t_drain = time.time()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request("POST", "/replicas/drain",
                         body=json.dumps({"id": victim, "deadline_s": 30.0}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            conn.close()
            if resp.status != 200:
                drain_result["drained_ok"] = False
                drain_result["error"] = f"drain POST: HTTP {resp.status}"
                return
            # the poller drives drain progress; wait for "drained" then DELETE
            drained = False
            deadline = time.time() + 60
            while time.time() < deadline and not drained:
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
                conn.request("GET", "/replicas")
                doc = json.loads(conn.getresponse().read())
                conn.close()
                drained = any(r["id"] == victim and (r.get("drain") or {}).get("drained")
                              for r in doc.get("replicas", []))
                if not drained:
                    time.sleep(0.1)
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request("DELETE", f"/replicas/{victim}" + ("" if drained else "?force=1"))
            resp = conn.getresponse()
            resp.read()
            conn.close()
            drain_result["drained_ok"] = bool(drained and resp.status == 200)
            drain_result["drain_wall_s"] = round(time.time() - t_drain, 3)
            drain_result["drained_replica"] = victim
        except Exception as e:
            drain_result["drained_ok"] = False
            drain_result["error"] = repr(e)

    # --swap-mid-run: halfway through the request stream, roll the v1
    # checkpoint across every replica via the router's rollout orchestrator
    # (drain -> swap -> canary -> health-gated rejoin, one replica at a time)
    # while the remaining requests keep flowing. ttft_timed pairs each TTFT
    # with its absolute first-token timestamp so the record can isolate the
    # tail measured INSIDE the swap window.
    rollout_result: dict = {}
    ttft_timed: list = []  # (abs first-token time, ttft_s)

    def swap_worker():
        rollout_result["t0"] = time.time()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=RUN_TIMEOUT_S)
            conn.request("POST", "/admin/weights/rollout",
                         body=json.dumps({"ckpt_dir": swap_ckpts["v1"],
                                          "rollback_ckpt_dir": swap_ckpts["v0"],
                                          "drain_deadline_s": 60.0,
                                          "rejoin_timeout_s": 60.0,
                                          "wait": True}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            doc = json.loads(resp.read())
            conn.close()
            ro = doc.get("rollout") or {}
            rollout_result["status"] = ro.get("status")
            rollout_result["wall_s"] = ro.get("wall_s")
            rollout_result["replicas_swapped"] = len(ro.get("completed") or [])
            rollout_result["abort_reason"] = ro.get("abort_reason")
            rollout_result["ok"] = bool(
                resp.status == 200 and ro.get("status") == "done")
        except Exception as e:
            rollout_result["ok"] = False
            rollout_result["error"] = repr(e)
        rollout_result["t1"] = time.time()

    def worker(i: int):
        local = {"ttft": [], "tokens": 0, "gaps_short": []}
        t_req = time.time()
        try:
            one_request(i, local)
        except Exception as e:
            with lock:
                errors.append(f"req {i}: {e!r}")
            return
        finally:
            sem.release()
        with lock:
            stats["ttft"].extend(local["ttft"])
            stats["tokens"] += local["tokens"]
            stats["gaps_short"].extend(local["gaps_short"])
            if swap_mid_run:
                ttft_timed.extend((t_req + v, v) for v in local["ttft"])

    # --multi-turn: per-conversation history (token-id assistant content, the
    # exact sampled ids — re-encoding text could diverge from the cache) and
    # per-turn readouts. conv_hist is only touched by that conversation's
    # worker thread within a turn wave, and waves are join()-separated.
    conv_hist: list = [[] for _ in range(n_convs)]
    turn_rows: list = [[] for _ in range(multi_turn)]  # (ttft, cached, prompt)

    def chat_turn(conv: int, turn: int):
        t0_turn = time.time()
        if turn == 0:
            # a long opener (system-prompt stand-in): the span turns 2..K
            # re-use from cache instead of re-prefilling
            content = [(11 * conv + 5 + j) % 88 + 5 for j in range(mt_open_tokens)]
        else:
            content = [(11 * conv + 7 * turn + j) % 88 + 5
                       for j in range(mt_user_tokens)]
        messages = conv_hist[conv] + [{"role": "user", "content": content}]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=RUN_TIMEOUT_S)
        conn.request("POST", "/v1/chat/completions",
                     body=json.dumps({"messages": messages,
                                      "max_tokens": max_tokens, "stream": True,
                                      "conversation": f"bench-conv-{conv}"}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f"conv {conv} turn {turn}: HTTP {resp.status}")
        ttft, toks, usage = None, [], {}
        while True:
            line = resp.readline()
            if not line or line.strip() == b"data: [DONE]":
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            ev = json.loads(line[len(b"data: "):])
            delta = (ev.get("choices") or [{}])[0].get("delta") or {}
            if "token" in delta:
                if ttft is None:
                    ttft = time.time() - t0_turn
                toks.append(delta["token"])
            if ev.get("usage"):
                usage = ev["usage"]
        conn.close()
        conv_hist[conv] = messages + [{"role": "assistant", "content": toks}]
        with lock:
            stats["ttft"].append(ttft if ttft is not None else float("nan"))
            stats["tokens"] += len(toks)
            turn_rows[turn].append((ttft if ttft is not None else 0.0,
                                    int(usage.get("cached_tokens", 0)),
                                    int(usage.get("prompt_tokens", 0))))

    def conv_worker(conv: int, turn: int):
        try:
            chat_turn(conv, turn)
        except Exception as e:
            with lock:
                errors.append(f"conv {conv} turn {turn}: {e!r}")
        finally:
            sem.release()

    def surge_request(i: int, phase: str, priority: str):
        """One open-loop surge request: sheds (503 overloaded_shed) and
        backpressure rejections are COUNTED, not errors — graceful
        degradation is the behavior under measurement."""
        t_start = time.time()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=RUN_TIMEOUT_S)
            conn.request("POST", "/v1/completions",
                         body=json.dumps({"prompt": [5 + i % 8, 6, 7],
                                          "max_tokens": max_tokens,
                                          "stream": True, "priority": priority}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                raw = resp.read()
                conn.close()
                try:
                    etype = json.loads(raw).get("error", {}).get("type", "")
                except ValueError:
                    etype = ""
                with lock:
                    # a replica-level shed reaches the client directly
                    # (overloaded_shed) or wrapped by the router after every
                    # candidate shed it (no_replica_available); the replicas'
                    # shed counter in the JSON is the authoritative total
                    if etype == "overloaded_shed" or (
                            etype == "no_replica_available"
                            and priority == "best_effort"):
                        surge_stats["shed"] += 1
                        if priority == "best_effort":
                            surge_stats["shed_best_effort"] += 1
                    else:
                        surge_stats["rejected"] += 1
                return
            ttft, n_toks = None, 0
            while True:
                line = resp.readline()
                if not line or line.strip() == b"data: [DONE]":
                    break
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                ev = json.loads(line[len(b"data: "):])
                if "token" in ev["choices"][0]:
                    if ttft is None:
                        ttft = time.time() - t_start
                    n_toks += 1
            conn.close()
            with lock:
                stats["tokens"] += n_toks
                if ttft is not None:
                    stats["ttft"].append(ttft)
                    surge_stats["phase_ttft"][phase].append(ttft)
                    if priority == "interactive":
                        surge_stats["interactive_ttft"].append(ttft)
        except Exception as e:
            with lock:
                errors.append(f"surge req {i}: {e!r}")

    t0 = time.time()
    threads = []
    drain_thread = None
    swap_thread = None
    if surge:
        # SLO burn trajectory: sampled like an on-call dashboard would, once
        # a second over the whole run (router mode only)
        stop_sampler = threading.Event()

        def slo_sampler():
            while not stop_sampler.is_set():
                try:
                    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
                    conn.request("GET", "/fleet/slo")
                    doc = json.loads(conn.getresponse().read())
                    conn.close()
                    windows = doc.get("windows") or {}
                    if windows:
                        w = windows[min(windows, key=lambda k: int(k.rstrip("s")))]
                        slo_samples.append({
                            "t_s": round(time.time() - t0, 2),
                            "availability_burn": round(
                                w["availability_burn_rate"], 3),
                            "ttft_burn": round(w["ttft_burn_rate"], 3)})
                except Exception:
                    pass
                stop_sampler.wait(1.0)

        sampler = None
        if fleet is not None:
            sampler = threading.Thread(target=slo_sampler, daemon=True)
            sampler.start()
        # open loop: each request fires at its scheduled offset regardless of
        # how many are still in flight — arrival pressure is the experiment
        for i, (off, phase, priority) in enumerate(surge_schedule):
            delay = t0 + off - time.time()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=surge_request, args=(i, phase, priority))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        if scaler is not None:
            # post-surge settle window: give the loop a chance to observe the
            # calm, scale back down, AND finalize the drain (removal happens
            # on a later tick than the down decision) before the verdict
            settle_deadline = time.time() + 15.0
            while time.time() < settle_deadline:
                if any(a == "drained" for _t, a, _d in scaler.events):
                    break
                time.sleep(0.25)
            scaler.stop()
        if sampler is not None:
            stop_sampler.set()
            sampler.join(timeout=5)
    elif multi_turn:
        # turn waves: every conversation's turn t runs (concurrency-bounded)
        # before any turn t+1 starts, so between a conversation's consecutive
        # turns the other conversations' prefills churn the device cache —
        # the forced-pressure schedule that makes the host tier earn the hit
        for turn in range(multi_turn):
            wave = []
            for c in range(n_convs):
                sem.acquire()
                th = threading.Thread(target=conv_worker, args=(c, turn))
                th.start()
                wave.append(th)
            for th in wave:
                th.join()
    else:
        for i in range(n_requests):
            sem.acquire()
            if drain_mid_run and drain_thread is None and i >= n_requests // 2:
                drain_thread = threading.Thread(target=drain_worker, daemon=True)
                drain_thread.start()
            if swap_mid_run and swap_thread is None and i >= n_requests // 2:
                swap_thread = threading.Thread(target=swap_worker, daemon=True)
                swap_thread.start()
            t = threading.Thread(target=worker, args=(i,))
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
    if drain_thread is not None:
        drain_thread.join(timeout=90)
    if swap_thread is not None:
        swap_thread.join(timeout=RUN_TIMEOUT_S)
    dt = time.time() - t0

    # scrape /metrics over HTTP (the same path a real Prometheus takes) BEFORE
    # shutdown, while the end-of-run engine state is still live. In router
    # mode the HTTP plane serves the paddlenlp_router_* series; the per-replica
    # serving planes are read straight from the in-process registries.
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    scraped = resp.read().decode()
    conn.close()
    if resp.status != 200:
        _fail(f"/metrics scrape failed: HTTP {resp.status}")
    replica_expositions = [r.expose() for r in fleet.registries()] if fleet is not None \
        else [scraped]
    if provisioner is not None:
        # autoscaler-provisioned replicas live outside the launch-time fleet;
        # their serving planes fold into the same readouts
        replica_expositions += [s.registry.expose()
                                for s in provisioner.servers.values()]
    fleet_slo = None
    if fleet is not None:
        # fleet SLO plane: federated availability + TTFT burn rates, scraped
        # the same way an on-call dashboard would
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/fleet/slo")
        resp = conn.getresponse()
        slo_raw = resp.read()
        conn.close()
        if resp.status == 200:
            fleet_slo = json.loads(slo_raw)
    final_replicas = None
    if scaler is not None:
        scaler.stop()  # no-op when the settle window already stopped it
        final_replicas = len(fleet.router.pool)
    if fleet is not None:
        fleet.shutdown(drain_timeout_s=10)
    else:
        server.shutdown(drain_timeout_s=10)
    if provisioner is not None:
        provisioner.close()

    if errors:
        _fail(f"{len(errors)}/{n_requests} requests failed: {errors[:3]}")
    if swap_mid_run and not rollout_result.get("ok"):
        _fail(f"--swap-mid-run rollout did not land: {rollout_result}")
    ttfts = sorted(stats["ttft"])
    p = lambda q: ttfts[min(int(q * len(ttfts)), len(ttfts) - 1)] if ttfts else 0.0

    from paddlenlp_tpu.observability import histogram_quantile, parse_prometheus_text

    replica_fams = [parse_prometheus_text(t) for t in replica_expositions]

    def scalar_sum(name):
        return sum((f[name].value() or 0.0) for f in replica_fams if name in f)

    def labeled_sum(name):
        # sum across every labelset (Family.value() is unlabeled-only)
        total = 0.0
        for f in replica_fams:
            fam = f.get(name)
            if fam is None:
                continue
            for (sample_name, _labels), v in fam.samples.items():
                if sample_name == name:
                    total += v
        return total

    def quantile_max(name, q):
        # worst replica's quantile: merging bucket vectors across registries
        # buys nothing a tail-latency readout cares about
        vals = [histogram_quantile(f[name], q) for f in replica_fams if name in f]
        return max(vals) if vals else 0.0

    record = {
        "metric": METRIC,
        "value": round(n_requests / dt, 3),
        "unit": UNIT,
        "n_requests": n_requests,
        "concurrency": concurrency,
        "max_tokens": max_tokens,
        "replicas": n_replicas,
        "wall_s": round(dt, 3),
        "tokens_per_sec": round(stats["tokens"] / dt, 1),
        "p50_ttft_ms": round(p(0.50) * 1e3, 1),
        "p99_ttft_ms": round(p(0.99) * 1e3, 1),
        "server_ttft_p50_ms": round(
            quantile_max("paddlenlp_serving_ttft_seconds", 0.5) * 1e3, 1),
        "p99_inter_token_ms": round(
            quantile_max("paddlenlp_serving_inter_token_seconds", 0.99) * 1e3, 1),
        "kv_utilization": round(
            scalar_sum("paddlenlp_serving_kv_utilization") / max(len(replica_fams), 1), 4),
        "kv_free_blocks": scalar_sum("paddlenlp_serving_kv_free_blocks"),
        "preemptions": scalar_sum("paddlenlp_serving_preemptions_total"),
        "tokens_generated": scalar_sum("paddlenlp_serving_tokens_generated_total"),
        "mesh_shape": f"{mesh_shape[0]}x{mesh_shape[1]}" if mesh_shape else "1x1",
        "tp_degree": mesh_shape[1] if mesh_shape else 1,
        "prefix_share": prefix_share,
        # hit rate over every request the engines saw (timed + warmup)
        "prefix_cache_hit_rate": round(
            scalar_sum("paddlenlp_serving_prefix_cache_hits_total") / (n_requests + 1), 4),
        "cached_tokens": int(scalar_sum("paddlenlp_serving_prefix_cache_cached_tokens_total")),
    }
    # per-phase latency attribution (worst replica's quantiles, like the other
    # tail readouts): a BENCH_r* regression now names the phase that moved
    from paddlenlp_tpu.observability import RECORDER

    attr_name = "paddlenlp_serving_latency_attribution_seconds"
    attribution = {}
    for phase in ("queue", "admission_gate", "promote_wait", "prefill",
                  "chunk_stall", "migration_wait", "decode"):
        p50 = max([histogram_quantile(f[attr_name], 0.5, phase=phase)
                   for f in replica_fams if attr_name in f] or [0.0])
        p99 = max([histogram_quantile(f[attr_name], 0.99, phase=phase)
                   for f in replica_fams if attr_name in f] or [0.0])
        attribution[phase] = {"p50_ms": round(p50 * 1e3, 1),
                              "p99_ms": round(p99 * 1e3, 1)}
    record["attribution"] = attribution
    # goodput ledger readout: what fraction of the run's device positions was
    # useful work, the waste decomposition, compile count and the host-gap
    # tail — the fields tools/bench_compare.py gates regressions on
    def labeled_by(name, label):
        out = {}
        for f in replica_fams:
            fam = f.get(name)
            if fam is None:
                continue
            for (_sample, labels), v in fam.samples.items():
                key = dict(labels).get(label)
                if key is not None:
                    out[key] = out.get(key, 0.0) + v
        return out

    gp_fed = scalar_sum("paddlenlp_serving_fed_tokens_total")
    gp_useful = scalar_sum("paddlenlp_serving_useful_tokens_total")
    record["goodput"] = {
        "ratio": round(gp_useful / gp_fed, 6) if gp_fed else 1.0,
        "fed_tokens": int(gp_fed),
        "useful_tokens": int(gp_useful),
        "wasted_tokens": {k: int(v) for k, v in sorted(
            labeled_by("paddlenlp_serving_wasted_tokens_total", "kind").items())},
        "compiles": int(sum(
            labeled_by("paddlenlp_serving_compiles_total", "program").values())),
        "compile_seconds": round(sum(
            labeled_by("paddlenlp_serving_compile_seconds_total", "program").values()), 3),
        "step_gap_p99_ms": round(
            quantile_max("paddlenlp_serving_step_gap_seconds", 0.99) * 1e3, 3),
        "shape_buckets": int(scalar_sum("paddlenlp_serving_jit_shape_buckets")),
    }
    if n_adapters:
        hits = sum(r.hits for r in adapter_registries)
        misses = sum(r.misses for r in adapter_registries)
        record["adapter_hit_rate"] = round(hits / max(hits + misses, 1), 4)
        record["adapter_evictions"] = sum(r.evictions for r in adapter_registries)
        record["multi_lora"] = {
            "adapters": n_adapters,
            "pool_slots": adapter_pool_slots,
            "hits": hits,
            "misses": misses,
            "loads": sum(r.loads for r in adapter_registries),
        }
    if tenant_mix:
        # per-tenant ledger straight off the serving counters: every admitted
        # request and every shed, keyed by the tenant label the isolation
        # layer stamps — summed across replicas
        record["tenants"] = {
            "requests": {k: int(v) for k, v in sorted(labeled_by(
                "paddlenlp_serving_requests_total", "tenant").items())},
            "shed": {k: int(v) for k, v in sorted(labeled_by(
                "paddlenlp_serving_requests_shed_total", "tenant").items())},
        }
        # billing view: fold every replica's usage-meter aggregate and
        # cross-check metered useful tokens against the goodput counters —
        # every booked request finished on one engine here, so the match is
        # exact (the chaos-only slack sources never fire in a clean bench)
        from paddlenlp_tpu.observability.usage import merge_aggregates

        usage_servers = fleet.servers if fleet is not None else [server]
        usage_fold = merge_aggregates(
            [s.loop.usage.snapshot() for s in usage_servers])
        ledger_useful = labeled_sum("paddlenlp_serving_useful_tokens_total")
        record["usage"] = {
            "records": usage_fold["records"],
            "reconciliation_ok": usage_fold["totals"]["useful_tokens"]
            == int(ledger_useful),
            "per_tenant_tokens": {
                t: int(b.get("prompt_tokens", 0) - b.get("cached_tokens", 0)
                       + b.get("completion_tokens", 0))
                for t, b in sorted(usage_fold["tenants"].items())},
        }
    # recorder-overhead A/B facts: run once with PDNLP_TPU_FLIGHT_RECORDER=0
    # and once without, diff value/tails — these two fields label the arms
    record["flight_recorder"] = RECORDER.enabled
    record["flight_events"] = len(RECORDER)
    if surge:
        pq = lambda arr, q: (sorted(arr)[min(int(q * len(arr)), len(arr) - 1)]
                             if arr else 0.0)
        pt = surge_stats["phase_ttft"]
        record["surge"] = {
            "rate_from": surge[0], "rate_to": surge[1], "ramp_s": surge[2],
            "requests": n_requests,
            "shed": surge_stats["shed"],
            "shed_best_effort": surge_stats["shed_best_effort"],
            "rejected": surge_stats["rejected"],
            # the replicas' own shed counter (brownout + deadline rejects),
            # covering direct sheds the router re-routed around
            "replica_shed_total": int(
                labeled_sum("paddlenlp_serving_requests_shed_total")),
            "p99_ttft_before_ms": round(pq(pt["before"], 0.99) * 1e3, 1),
            "p99_ttft_during_ms": round(pq(pt["during"], 0.99) * 1e3, 1),
            "p99_ttft_after_ms": round(pq(pt["after"], 0.99) * 1e3, 1),
            "interactive_p99_ttft_ms": round(
                pq(surge_stats["interactive_ttft"], 0.99) * 1e3, 1),
            "slo_trajectory": slo_samples[-20:],
        }
    if scaler is not None:
        ev = list(scaler.events)
        record["autoscale"] = {
            "min": autoscale[0], "max": autoscale[1],
            "scale_ups": sum(1 for _t, a, _d in ev if a == "up"),
            "scale_downs": sum(1 for _t, a, _d in ev if a == "down"),
            "replaces": sum(1 for _t, a, _d in ev if a == "replace"),
            "holds": sum(1 for _t, a, _d in ev if a == "hold"),
            "final_replicas": final_replicas,
            "events": [[round(t - t0, 2), a, d] for t, a, d in ev][-30:],
        }
    if long_mix:
        gaps = sorted(stats["gaps_short"])
        gp = lambda q: gaps[min(int(q * len(gaps)), len(gaps) - 1)] if gaps else 0.0
        record["long_prompt_mix"] = {
            "long_prompts": n_long_issued,
            "long_prompt_tokens": long_tokens,
            "prefill_chunk": prefill_chunk,
            # which mixed-step layout ran: flat segments (cost ~ fed tokens)
            # vs the padded B x chunk launch (--token-flatten 0)
            "token_flatten": token_flatten if token_flatten is not None
                             else bool(prefill_chunk),
            # client-observed tails: the chatty requests' inter-token gaps are
            # the decode stalls the chunked prefill bounds
            "client_p99_inter_token_ms": round(gp(0.99) * 1e3, 1),
            "client_p50_inter_token_ms": round(gp(0.50) * 1e3, 1),
            "prefill_chunks": int(scalar_sum("paddlenlp_serving_prefill_chunks_total")),
            "decode_stall_p99_ms": round(
                quantile_max("paddlenlp_serving_decode_stall_seconds", 0.99) * 1e3, 1),
        }
    if multi_turn:
        # per-turn view of the conversation-lifetime hierarchy: turn 1 is the
        # cold long opener, turns 2..K should hit the (device or host) cache
        # for the whole history — hit rate > 0 with spills > 0 is the proof
        # the HOST tier served turns the device LRU had already evicted
        per_turn = []
        for t, rows in enumerate(turn_rows):
            tt = sorted(r[0] for r in rows)
            cached = sum(r[1] for r in rows)
            prompt = sum(r[2] for r in rows)
            per_turn.append({
                "turn": t + 1,
                "ttft_p50_ms": round(
                    (tt[len(tt) // 2] if tt else 0.0) * 1e3, 1),
                "cache_hit_rate": round(cached / prompt, 4) if prompt else 0.0,
                "cached_tokens": cached,
                "prompt_tokens": prompt,
            })
        mt_promote_bytes = scalar_sum("paddlenlp_serving_kv_host_promote_bytes_total")
        record["multi_turn"] = {
            "turns": multi_turn,
            "conversations": n_convs,
            "ttft_turn1_ms": per_turn[0]["ttft_p50_ms"],
            "ttft_turnk_ms": per_turn[-1]["ttft_p50_ms"],
            "per_turn": per_turn,
            "per_turn_cache_hit_rate": [pt["cache_hit_rate"] for pt in per_turn],
            "host_spills": int(scalar_sum("paddlenlp_serving_kv_host_spills_total")),
            "host_promotes": int(
                scalar_sum("paddlenlp_serving_kv_host_promotes_total")),
            "host_blocks": int(scalar_sum("paddlenlp_serving_kv_host_blocks")),
            "promote_bytes": int(mt_promote_bytes),
            "promote_bandwidth_mb_s": round(mt_promote_bytes / dt / 1e6, 3),
        }
    if disagg:
        # per-stage view: TTFT is prefill-stage latency, the chatty client
        # inter-token tail is decode-stage latency, and the migration series
        # is the traffic between them
        def stage_gauge(name, stage):
            total = 0.0
            for f in replica_fams:
                fam = f.get(name)
                if fam is None:
                    continue
                for (_sample, labels), v in fam.samples.items():
                    if dict(labels).get("stage") == stage:
                        total += v
            return total / max(len(replica_fams), 1)

        dgaps = sorted(stats["gaps_short"])
        dgp = lambda q: dgaps[min(int(q * len(dgaps)), len(dgaps) - 1)] if dgaps else 0.0
        record["disagg"] = {
            "stages": f"{disagg[0]},{disagg[1]}",
            "prefill_stage": {
                "ttft_p50_ms": round(p(0.50) * 1e3, 1),
                "ttft_p99_ms": round(p(0.99) * 1e3, 1),
                "kv_utilization": round(
                    stage_gauge("paddlenlp_serving_stage_kv_utilization", "prefill"), 4),
            },
            "decode_stage": {
                "client_p50_inter_token_ms": round(dgp(0.50) * 1e3, 1),
                "client_p99_inter_token_ms": round(dgp(0.99) * 1e3, 1),
                "kv_utilization": round(
                    stage_gauge("paddlenlp_serving_stage_kv_utilization", "decode"), 4),
            },
            "migrations": int(scalar_sum("paddlenlp_serving_kv_migrations_total")),
            "migrated_blocks": int(
                scalar_sum("paddlenlp_serving_kv_migrated_blocks_total")),
            "migrated_bytes": int(
                scalar_sum("paddlenlp_serving_kv_migrated_bytes_total")),
        }
    if fleet is not None:
        router_fams = parse_prometheus_text(scraped)
        share = {}
        req_fam = router_fams.get("paddlenlp_router_requests_total")
        if req_fam is not None:
            for (_sample, labels), v in req_fam.samples.items():
                share[dict(labels).get("replica", "?")] = \
                    share.get(dict(labels).get("replica", "?"), 0.0) + v
        rscalar = lambda name: (router_fams[name].value() or 0.0) if name in router_fams else 0.0
        record["request_share"] = {k: int(v) for k, v in sorted(share.items())}
        record["failovers"] = int(rscalar("paddlenlp_router_failovers_total"))
        record["rerouted"] = int(rscalar("paddlenlp_router_rerouted_total"))
        # hedges_total is labeled by outcome: fold the fired ones (and capped
        # separately — a capped hedge is latency NOT bought back)
        hedge_fam = router_fams.get("paddlenlp_router_hedges_total")
        hedge_by = {}
        if hedge_fam is not None:
            for (_sample, labels), v in hedge_fam.samples.items():
                hedge_by[dict(labels).get("outcome", "?")] = int(v)
        record["hedges"] = sum(v for k, v in hedge_by.items() if k != "capped")
        if hedge_by.get("capped"):
            record["hedges_capped"] = hedge_by["capped"]
        if drain_mid_run:
            record["drained_ok"] = bool(drain_result.get("drained_ok"))
            if "drain_wall_s" in drain_result:
                record["drain_wall_s"] = drain_result["drain_wall_s"]
            if "error" in drain_result:
                record["drain_error"] = drain_result["error"]
        if swap_mid_run:
            # zero-downtime readout: the run already _fail()s on any client
            # error, so streams_lost is the gateable proof that the rollout
            # cost nothing; the in-window p99 isolates the tail the drain/
            # swap/canary walk added on top of steady-state serving
            w0 = rollout_result.get("t0", t0)
            w1 = rollout_result.get("t1", t0 + dt)
            during = sorted(v for at, v in ttft_timed if w0 <= at <= w1)
            d_p99 = (during[min(int(0.99 * len(during)), len(during) - 1)]
                     if during else 0.0)
            record["rollout"] = {
                "status": rollout_result.get("status"),
                "wall_s": rollout_result.get("wall_s"),
                "replicas_swapped": rollout_result.get("replicas_swapped", 0),
                "streams_lost": len(errors),
                "ttft_p99_during_swap_ms": round(d_p99 * 1e3, 1),
            }
        if fleet_slo is not None and fleet_slo.get("windows"):
            # the longest window covers the whole bench run (process lifetime)
            widest = fleet_slo["windows"][max(
                fleet_slo["windows"], key=lambda w: int(w.rstrip("s")))]
            objectives = fleet_slo.get("objectives", {})
            record["fleet_availability"] = round(widest["availability"], 6)
            record["fleet_availability_burn_rate"] = round(
                widest["availability_burn_rate"], 3)
            record["fleet_ttft_burn_rate"] = round(widest["ttft_burn_rate"], 3)
            record["fleet_ttft_violation_rate"] = round(
                widest["ttft_violation_rate"], 4)
            record["ttft_objective_ms"] = round(
                objectives.get("ttft_threshold_s", 0.0) * 1e3, 1)
            record["server_ttft_p99_ms"] = round(
                quantile_max("paddlenlp_serving_ttft_seconds", 0.99) * 1e3, 1)
    print(json.dumps(record))


def main() -> None:
    # subprocess isolation: a hung backend or deadlocked loop cannot eat the
    # caller — the watchdog timeout always produces the JSON failure record
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--run", *sys.argv[1:]],
            capture_output=True, text=True, timeout=RUN_TIMEOUT_S,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    except subprocess.TimeoutExpired:
        _fail(f"serving smoke run timed out after {RUN_TIMEOUT_S}s")
        return
    for line in reversed((proc.stdout or "").strip().splitlines()):
        if line.startswith("{"):
            print(line)
            sys.exit(proc.returncode)
    tail = "\n".join(((proc.stdout or "") + (proc.stderr or "")).strip().splitlines()[-8:])
    _fail(f"serving smoke produced no JSON line (rc={proc.returncode}): {tail}")


if __name__ == "__main__":
    if "--run" in sys.argv:
        try:
            run()
        except Exception as e:
            _fail(f"{type(e).__name__}: {e}")
    else:
        main()
