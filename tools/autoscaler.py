"""Standalone autoscaler daemon: the closed-loop policy thread as an operator
process.

Watches a running router's ``/fleet/slo`` + ``/replicas`` planes and drives
its elastic admin plane (``POST /replicas`` / ``POST /replicas/drain`` /
``DELETE /replicas/{id}``): sustained overload scales up, sustained calm
scales down, a DOWN replica is force-removed and replaced, and overload at
the max envelope pushes a brownout floor to the replicas (shed best-effort
first) instead of letting everyone time out. Every decision is a
flight-recorder event and one JSONL line on stdout.

Replicas are provisioned through a subprocess command template — anything
that starts a serving HTTP plane on ``{host}:{port}`` works::

    python tools/autoscaler.py --router 127.0.0.1:8010 --min 1 --max 4 \\
        --spawn "python -m my_replica_entrypoint --host {host} --port {port}"

Knobs mirror ``AutoscalerPolicy`` (see ``--help``). Ctrl-C drains nothing:
the fleet keeps serving; only the control loop stops.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def parse_args(argv):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--router", required=True, help="router HOST:PORT")
    ap.add_argument("--spawn", required=True,
                    help="replica launch command template ({host}/{port} substituted)")
    ap.add_argument("--host", default="127.0.0.1", help="bind host for spawned replicas")
    ap.add_argument("--min", type=int, default=1, dest="min_replicas")
    ap.add_argument("--max", type=int, default=4, dest="max_replicas")
    ap.add_argument("--interval", type=float, default=2.0, help="tick seconds")
    ap.add_argument("--up-kv", type=float, default=0.85)
    ap.add_argument("--up-queue", type=float, default=4.0)
    ap.add_argument("--up-burn", type=float, default=10.0)
    ap.add_argument("--down-kv", type=float, default=0.30)
    ap.add_argument("--down-queue", type=float, default=0.5)
    ap.add_argument("--hysteresis-up", type=int, default=2)
    ap.add_argument("--hysteresis-down", type=int, default=5)
    ap.add_argument("--cooldown-up", type=float, default=10.0)
    ap.add_argument("--cooldown-down", type=float, default=30.0)
    ap.add_argument("--step-up", type=int, default=2)
    ap.add_argument("--step-down", type=int, default=1)
    ap.add_argument("--drain-deadline", type=float, default=30.0)
    ap.add_argument("--brownout-level", type=int, default=1,
                    help="brownout floor pushed at the max envelope (0 disables)")
    ap.add_argument("--teardown-on-exit", action="store_true",
                    help="terminate every autoscaler-spawned replica on exit "
                         "(default: leave the fleet serving — only the "
                         "control loop stops)")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
    from paddlenlp_tpu.serving.router.autoscaler import (
        Autoscaler,
        AutoscalerPolicy,
        SubprocessProvisioner,
    )

    host, _, port = args.router.partition(":")
    if not port:
        print(json.dumps({"error": f"--router must be HOST:PORT, got {args.router!r}"}))
        return 2
    policy = AutoscalerPolicy(
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        scale_up_kv_utilization=args.up_kv, scale_up_queue_depth=args.up_queue,
        scale_up_burn_rate=args.up_burn,
        scale_down_kv_utilization=args.down_kv,
        scale_down_queue_depth=args.down_queue,
        hysteresis_up=args.hysteresis_up, hysteresis_down=args.hysteresis_down,
        cooldown_up_s=args.cooldown_up, cooldown_down_s=args.cooldown_down,
        max_step_up=args.step_up, max_step_down=args.step_down,
        drain_deadline_s=args.drain_deadline,
        brownout_push_level=args.brownout_level)
    provisioner = SubprocessProvisioner(args.spawn, host=args.host)
    scaler = Autoscaler((host, int(port)), provisioner, policy=policy,
                        interval_s=args.interval)

    stop = {"flag": False}

    def _sig(_signum, _frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        while not stop["flag"]:
            t0 = time.time()
            try:
                summary = scaler.evaluate_once()
            except Exception as e:
                summary = {"t": t0, "error": repr(e)}
            print(json.dumps(summary), flush=True)
            delay = args.interval - (time.time() - t0)
            if delay > 0:
                time.sleep(delay)
    finally:
        # the docstring contract: a daemon exit stops ONLY the control loop;
        # spawned replicas keep serving (still registered with the router)
        # unless the operator explicitly asked for teardown
        if args.teardown_on_exit:
            provisioner.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
