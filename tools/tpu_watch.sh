#!/bin/bash
# Background TPU tunnel watcher. Probes the axon backend every ~3 minutes and
# records the latest status in tools/tpu_status.json so the builder can poll
# cheaply. Appends history to tools/tpu_watch.log.
cd /root/repo
while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  out=$(timeout 75 python - <<'EOF' 2>&1
import jax
ds = jax.devices()
import jax.numpy as jnp
x = jnp.ones((256, 256), dtype=jnp.bfloat16)
y = (x @ x).sum()
print("LIVE", ds[0].platform, float(y))
EOF
)
  rc=$?
  if [ $rc -eq 0 ] && echo "$out" | grep -q LIVE; then
    status=live
  else
    status=down
  fi
  echo "{\"ts\": \"$ts\", \"status\": \"$status\", \"rc\": $rc}" > tools/tpu_status.json
  echo "$ts $status rc=$rc" >> tools/tpu_watch.log
  sleep 150
done
