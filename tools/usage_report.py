"""Offline usage-ledger aggregator: merge, dedup, price, reconcile.

Reads the durable JSONL usage ledgers N replicas wrote (one directory per
replica, or one shared directory — segment names carry the replica id either
way) and produces the billing view:

- **merge + dedup**: records are keyed by ``record_id`` (the request's trace
  id). A mid-stream failover legitimately books the same id on two replicas;
  the merge keeps the terminal-success record (``finish_reason`` stop/length)
  and counts the loser as ``failover_superseded``. Two *successful* records
  for one id with different token payloads is a billing conflict — reported
  and exit code 1 (nobody gets double-billed silently);
- **pricing**: ``--price-per-1k`` (default 0: token report only) or a
  ``--prices FILE`` JSON table ``{tenant: $/1k}`` (``"*"`` = default). The
  billed quantity per record is ``prompt - cached + completion`` — prefix-
  cache hits are a credit, exactly the tokens the device never re-fed;
- **reconciliation**: ``--useful-total N`` (repeatable; pass each replica's
  goodput-ledger ``useful`` total) cross-checks the metered
  ``useful_tokens`` sum against the device-side truth. Divergence beyond
  ``--slack`` (absolute tokens, default 0) exits 1. The documented slack
  sources: requests retried across an engine rebuild undershoot by the dead
  engine's completed work, and counter totals include requests still
  in flight / never booked (aborted pre-admission).

Reading is tolerant, mirroring ``observability/usage.py``: sealed segments
(``usage-*-NNNNNN.jsonl``) are authoritative; an open segment
(``.open.jsonl``) with a sealed twin is skipped; torn or corrupt lines are
dropped and counted, never fatal.

Stdlib-only on purpose (no jax, no repo imports): runnable on a laptop
against ledger directories scp'd off the fleet.

Usage::

    python tools/usage_report.py /var/ledger/replica-a /var/ledger/replica-b
    python tools/usage_report.py LEDGER_DIR --prices prices.json
    python tools/usage_report.py LEDGER_DIR --useful-total 48211 --slack 64
    python tools/usage_report.py LEDGER_DIR --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["load_dirs", "dedup_records", "aggregate", "price", "reconcile",
           "main"]

OPEN_SUFFIX = ".open.jsonl"
SEALED_SUFFIX = ".jsonl"

#: mirrors observability.usage.SUM_FIELDS — the shared aggregate shape is
#: the contract that lets this report be diffed against GET /fleet/usage
SUM_FIELDS = (
    "prompt_tokens",
    "cached_tokens",
    "completion_tokens",
    "useful_tokens",
    "spec_drafted",
    "spec_accepted",
    "kv_block_seconds",
    "adapter_slot_seconds",
)

#: terminal finish reasons that mean "the client got a complete answer" —
#: the survivor pick for failover-duplicated record ids
SUCCESS_REASONS = {"stop", "length"}


# --------------------------------------------------------------------- read
def _parse_lines(path: str) -> Tuple[List[Dict], int]:
    records: List[Dict] = []
    dropped = 0
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            raw = f.read().split("\n")
    except OSError:
        return records, dropped
    for line in raw:
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("record is not an object")
            records.append(rec)
        except ValueError:
            dropped += 1
    return records, dropped


def load_dirs(directories: List[str]) -> Tuple[List[Dict], Dict]:
    """Read every segment under every directory; returns (records, report).
    Same tolerance contract as the in-repo loader: sealed beats its open
    twin, bad lines drop + count."""
    report = {"dirs": list(directories), "sealed_segments": 0,
              "open_segments": 0, "torn_lines_dropped": 0,
              "twins_skipped": 0, "records_read": 0}
    records: List[Dict] = []
    for directory in directories:
        try:
            names = sorted(os.listdir(directory))
        except OSError as e:
            print(f"usage_report: cannot read {directory}: {e}", file=sys.stderr)
            continue
        sealed_stems = {n[: -len(SEALED_SUFFIX)] for n in names
                        if n.endswith(SEALED_SUFFIX)
                        and not n.endswith(OPEN_SUFFIX)}
        for name in names:
            path = os.path.join(directory, name)
            if name.endswith(OPEN_SUFFIX):
                if name[: -len(OPEN_SUFFIX)] in sealed_stems:
                    report["twins_skipped"] += 1
                    continue
                report["open_segments"] += 1
            elif name.endswith(SEALED_SUFFIX):
                report["sealed_segments"] += 1
            else:
                continue
            recs, dropped = _parse_lines(path)
            records.extend(recs)
            report["torn_lines_dropped"] += dropped
    report["records_read"] = len(records)
    return records, report


# -------------------------------------------------------------------- dedup
def _tokens_key(rec: Dict) -> Tuple:
    return tuple(rec.get(k) or 0 for k in
                 ("prompt_tokens", "cached_tokens", "completion_tokens"))


def _is_success(rec: Dict) -> bool:
    return rec.get("finish_reason") in SUCCESS_REASONS


def dedup_records(records: List[Dict]) -> Tuple[List[Dict], Dict, List[Dict]]:
    """Collapse records sharing a record_id to one bill each.

    Returns ``(kept, counts, conflicts)``. Identical duplicates collapse
    silently (a re-sealed segment copied twice). A success + failure pair for
    one id is the mid-stream-failover signature: the success wins, the loser
    counts as ``failover_superseded``. Two successes with *different* token
    payloads is a double bill — both land in ``conflicts`` (caller exits 1)
    and the first is kept so totals stay deterministic."""
    by_id: "Dict[str, Dict]" = {}
    order: List[str] = []
    counts = {"unique": 0, "identical_duplicates": 0,
              "failover_superseded": 0, "conflicts": 0}
    conflicts: List[Dict] = []
    for rec in records:
        rid = rec.get("record_id")
        if not isinstance(rid, str) or not rid:
            rid = f"_anon-{len(order)}"  # never merge id-less records
        cur = by_id.get(rid)
        if cur is None:
            by_id[rid] = rec
            order.append(rid)
            counts["unique"] += 1
            continue
        if _tokens_key(cur) == _tokens_key(rec) \
                and cur.get("finish_reason") == rec.get("finish_reason"):
            counts["identical_duplicates"] += 1
            continue
        cur_ok, new_ok = _is_success(cur), _is_success(rec)
        if cur_ok and new_ok:
            counts["conflicts"] += 1
            conflicts.append({"record_id": rid, "kept": cur, "dropped": rec})
        elif new_ok and not cur_ok:
            by_id[rid] = rec  # failover: the completed attempt is the bill
            counts["failover_superseded"] += 1
        else:
            # failure duplicate of a success (or of another failure): the
            # kept record already covers the client-visible outcome
            counts["failover_superseded"] += 1
    return [by_id[r] for r in order], counts, conflicts


# ---------------------------------------------------------------- aggregate
def _fold(bucket: Dict, rec: Dict):
    bucket["records"] = bucket.get("records", 0) + 1
    for k in SUM_FIELDS:
        v = rec.get(k) or 0
        bucket[k] = round(bucket.get(k, 0) + v, 6) if isinstance(v, float) \
            else bucket.get(k, 0) + v


def aggregate(records: List[Dict]) -> Dict:
    """The /fleet/usage fold shape: fleet totals + per-tenant + per-adapter
    buckets (None adapter bills to "base")."""
    agg = {"records": 0, "totals": {k: 0 for k in SUM_FIELDS},
           "tenants": {}, "adapters": {}}
    for rec in records:
        agg["records"] += 1
        for k in SUM_FIELDS:
            v = rec.get(k) or 0
            t = agg["totals"]
            t[k] = round(t[k] + v, 6) if isinstance(v, float) else t[k] + v
        _fold(agg["tenants"].setdefault(rec.get("tenant") or "default", {}), rec)
        _fold(agg["adapters"].setdefault(rec.get("adapter_id") or "base", {}), rec)
    return agg


def billed_tokens(bucket: Dict) -> int:
    """The billable quantity: prompt minus prefix-cache credit plus
    completion."""
    return (bucket.get("prompt_tokens", 0) - bucket.get("cached_tokens", 0)
            + bucket.get("completion_tokens", 0))


def price(agg: Dict, default_per_1k: float,
          table: Optional[Dict[str, float]] = None) -> Dict:
    """Per-tenant dollars from the $/1k-token table (``"*"`` = fallback)."""
    table = table or {}
    out = {}
    for tenant, bucket in sorted(agg["tenants"].items()):
        rate = table.get(tenant, table.get("*", default_per_1k))
        toks = billed_tokens(bucket)
        out[tenant] = {"billed_tokens": toks, "rate_per_1k": rate,
                       "amount": round(toks / 1000.0 * rate, 6)}
    return out


def reconcile(agg: Dict, useful_totals: List[float], slack: float) -> Dict:
    """Metered useful tokens vs the goodput ledgers' device-side truth."""
    metered = agg["totals"]["useful_tokens"]
    counter = sum(useful_totals)
    gap = counter - metered
    return {"metered_useful_tokens": metered,
            "ledger_useful_tokens": counter,
            "gap": gap, "slack": slack, "ok": abs(gap) <= slack}


# --------------------------------------------------------------------- main
def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Merge usage ledgers: dedup, price, reconcile.")
    ap.add_argument("dirs", nargs="+", help="ledger directories (one or more)")
    ap.add_argument("--price-per-1k", type=float, default=0.0,
                    help="default $ per 1k billed tokens")
    ap.add_argument("--prices", help="JSON file {tenant: $/1k}, '*' = default")
    ap.add_argument("--useful-total", type=float, action="append", default=[],
                    help="a goodput ledger's useful-token total (repeatable; "
                         "summed across replicas)")
    ap.add_argument("--slack", type=float, default=0.0,
                    help="absolute token slack tolerated by the reconciliation")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)

    table = None
    if args.prices:
        with open(args.prices, encoding="utf-8") as f:
            table = json.load(f)

    records, read_report = load_dirs(args.dirs)
    kept, dedup_counts, conflicts = dedup_records(records)
    agg = aggregate(kept)
    invoice = price(agg, args.price_per_1k, table)
    recon = reconcile(agg, args.useful_total, args.slack) \
        if args.useful_total else None

    rc = 0
    if conflicts:
        rc = 1
    if recon is not None and not recon["ok"]:
        rc = 1

    doc = {"read": read_report, "dedup": dedup_counts, "usage": agg,
           "invoice": invoice, "reconciliation": recon,
           "conflicts": [{"record_id": c["record_id"]} for c in conflicts],
           "ok": rc == 0}
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return rc

    print(f"segments: {read_report['sealed_segments']} sealed, "
          f"{read_report['open_segments']} open, "
          f"{read_report['torn_lines_dropped']} torn lines dropped, "
          f"{read_report['twins_skipped']} twins skipped")
    print(f"records: {read_report['records_read']} read -> "
          f"{agg['records']} billed "
          f"({dedup_counts['identical_duplicates']} identical dups, "
          f"{dedup_counts['failover_superseded']} failover-superseded)")
    t = agg["totals"]
    print(f"totals: prompt={t['prompt_tokens']} cached={t['cached_tokens']} "
          f"completion={t['completion_tokens']} useful={t['useful_tokens']} "
          f"kv_block_s={t['kv_block_seconds']}")
    print("per tenant:")
    for tenant in sorted(agg["tenants"]):
        b = agg["tenants"][tenant]
        line = (f"  {tenant}: requests={b.get('records', 0)} "
                f"billed_tokens={billed_tokens(b)}")
        if tenant in invoice and invoice[tenant]["rate_per_1k"]:
            line += f" amount=${invoice[tenant]['amount']}"
        print(line)
    print("per adapter:")
    for adapter in sorted(agg["adapters"]):
        b = agg["adapters"][adapter]
        print(f"  {adapter}: requests={b.get('records', 0)} "
              f"billed_tokens={billed_tokens(b)} "
              f"slot_s={b.get('adapter_slot_seconds', 0)}")
    for c in conflicts:
        print(f"CONFLICT: record_id {c['record_id']!r} has two successful "
              f"records with different token payloads (double bill)")
    if recon is not None:
        verdict = "ok" if recon["ok"] else "DIVERGED"
        print(f"reconciliation: metered useful={recon['metered_useful_tokens']} "
              f"vs ledger useful={recon['ledger_useful_tokens']} "
              f"gap={recon['gap']} slack={recon['slack']} -> {verdict}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
