"""Metric-catalog lint — thin shim over the static-analysis suite's runtime
half.

The catalog builders moved to ``tools/analyze/runtime_metrics.py``; this
entry point (and its ONE-JSON-line contract, enforced by
``tests/observability/test_check_metrics.py``) stays put. Two layers now
cover metrics:

- **static** (``python -m tools.analyze``, ``metrics-catalog`` checker, no
  jax): registered metric *names* are valid Prometheus names, counters end in
  ``_total``, every name is documented in a README metrics table;
- **runtime** (this tool, needs jax to instantiate the catalog): the full
  serving + router + SLO + training catalog renders a clean exposition —
  missing HELP, missing TYPE, illegal names/labels, non-cumulative histogram
  buckets, negative counters all fail — and the federated path
  (``federate_expositions`` + ``lint_federation``) merges two synthetic
  replicas cleanly.

Usage::

    python tools/check_metrics.py              # lint the built-in catalogs
    python tools/check_metrics.py --file dump  # lint a scraped /metrics dump
"""

from __future__ import annotations

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.analyze.runtime_metrics import (  # noqa: E402,F401 — re-exported API
    _stub_engine,
    catalog_exposition,
    federation_problems,
)


def main() -> int:
    from paddlenlp_tpu.observability import lint_exposition, parse_prometheus_text

    if "--file" in sys.argv:
        with open(sys.argv[sys.argv.index("--file") + 1]) as f:
            text = f.read()
        problems = lint_exposition(text)
    else:
        text = catalog_exposition()
        problems = lint_exposition(text) + federation_problems()
    families = parse_prometheus_text(text)
    print(json.dumps({
        "ok": not problems,
        "families": len(families),
        "samples": sum(len(f.samples) for f in families.values()),
        "problems": problems,
    }))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
