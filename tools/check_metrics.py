"""Metric-catalog lint: every registered metric must expose valid Prometheus
text format with HELP/TYPE lines.

Instantiates the full catalog — the serving runtime's ``ServingMetrics`` (on a
stub engine, no jax compute), the router front tier's ``RouterMetrics``, and
the trainer's ``register_training_metrics`` —
into one fresh registry, renders the exposition, and runs
``observability.lint_exposition`` over it: missing HELP, missing TYPE, illegal
names/labels, non-cumulative histogram buckets, negative counters all fail.

Prints ONE JSON line (``{"ok": ..., "families": N, "problems": [...]}``) and
exits non-zero on problems — `tests/observability/test_check_metrics.py` runs
it so tier-1 enforces catalog hygiene on every PR.

Usage::

    python tools/check_metrics.py              # lint the built-in catalogs
    python tools/check_metrics.py --file dump  # lint a scraped /metrics dump
"""

from __future__ import annotations

import json
import os
import sys


def _stub_engine():
    """Just enough engine surface for ServingMetrics' pull-mode gauges."""

    class _Mgr:
        num_free = 42
        total_usable_blocks = 64
        max_blocks_per_seq = 8
        num_cached_blocks = 3
        cache_hits = 0
        cached_tokens_total = 0
        evictions = 0

    class _Engine:
        mgr = _Mgr()
        waiting = []
        slots = [None] * 4
        max_batch_size = 4
        spec_stats = {"drafted": 0, "accepted": 0}

    return _Engine()


def catalog_exposition() -> str:
    """Render the full serving + router + training metric catalog from a
    fresh registry."""
    from paddlenlp_tpu.serving.engine_loop import ServingMetrics
    from paddlenlp_tpu.serving.metrics import MetricsRegistry
    from paddlenlp_tpu.serving.router.metrics import RouterMetrics
    from paddlenlp_tpu.trainer.integrations import register_training_metrics

    registry = MetricsRegistry()
    ServingMetrics(_stub_engine(), registry=registry)
    router = RouterMetrics(registry)
    # labeled series expose no samples until touched — exercise one labelset
    # of each so the lint sees real sample lines, not just HELP/TYPE headers
    router.replica_healthy.set(1.0, replica="replica-0")
    router.requests.inc(replica="replica-0", outcome="ok")
    router.health_polls.inc(replica="replica-0", outcome="ok")
    register_training_metrics(registry)
    return registry.expose()


def main() -> int:
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
    from paddlenlp_tpu.observability import lint_exposition, parse_prometheus_text

    if "--file" in sys.argv:
        with open(sys.argv[sys.argv.index("--file") + 1]) as f:
            text = f.read()
    else:
        text = catalog_exposition()
    problems = lint_exposition(text)
    families = parse_prometheus_text(text)
    print(json.dumps({
        "ok": not problems,
        "families": len(families),
        "samples": sum(len(f.samples) for f in families.values()),
        "problems": problems,
    }))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
