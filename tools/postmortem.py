"""Offline postmortem-bundle analyzer: per-request cross-tier timelines.

Reads one or more postmortem bundles (auto-dumped by a supervisor degrade /
slot quarantine / drain eviction / SLO fast burn, or forced via
``POST /debug/postmortem``) and reconstructs what happened:

- ``--list`` enumerates every request (trace id) seen in the bundles' flight
  events, with event counts per tier;
- ``--req rtr-3`` (or ``req-0``, or a bare engine req_id) prints that
  request's **decision trail** — router-tier and replica-tier flight events
  joined on the shared trace id, merged with the request's spans into one
  monotonic timeline — plus its **latency-attribution breakdown** from the
  bundle's finished-request tail;
- with no selector, a bundle summary (trigger, tier, health headlines,
  event/span counts) is printed.

Bundles from one process (an in-process fleet) already carry both tiers;
separate router/replica processes each dump their own bundle — pass all of
them and the analyzer merges on the trace id. Timestamps inside one process
are epoch-anchored monotonic; merging across processes assumes loosely
synced clocks (the trails are for humans, not for skew-corrected profiling —
that is ``/debug/trace``'s job).

Stdlib-only on purpose (no jax, no repo imports): runnable on a laptop
against bundles scp'd off an incident.

Usage::

    python tools/postmortem.py bundle.json                 # summary
    python tools/postmortem.py bundle.json --list          # requests seen
    python tools/postmortem.py bundle.json --req rtr-3     # one trail
    python tools/postmortem.py router.json replica.json --req rtr-3
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional

__all__ = ["load_bundles", "merged_events", "request_ids", "timeline_for",
           "attribution_for", "render_timeline", "main"]


def load_bundles(paths: List[str]) -> List[Dict]:
    bundles = []
    for path in paths:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if "events" not in doc or "trigger" not in doc:
            raise ValueError(f"{path}: not a postmortem bundle (no events/trigger)")
        doc["_path"] = path
        bundles.append(doc)
    return bundles


def _tier_of(bundle: Dict, name: str) -> str:
    """Which tier produced one event: router.* events are router-tier even
    inside a replica-tagged in-process bundle (the recorder is shared)."""
    if name.startswith("router."):
        return "router"
    if name.startswith(("sched.", "supervisor.")):
        return "serving"
    if name.startswith(("admit.", "chunk.", "migrate.")) or name == "preempt":
        return "engine"
    return bundle.get("tier", "?")


def merged_events(bundles: List[Dict]) -> List[Dict]:
    """Every bundle's flight events, tier-tagged and sorted by timestamp.
    Duplicate (same-seq, same-pid) events across two dumps of one process
    collapse, so overlapping bundles don't double every line."""
    seen = set()
    out = []
    for b in bundles:
        for ev in b.get("events", ()):
            # the timestamp disambiguates two processes whose pids collide
            # (recycled pid, bundles from different hosts): same-process dumps
            # of one event repeat t exactly, distinct processes never do
            key = (b.get("pid"), ev.get("seq"), ev.get("name"), ev.get("t"))
            if key in seen:
                continue
            seen.add(key)
            ev = dict(ev)
            ev["_tier"] = _tier_of(b, ev.get("name", ""))
            out.append(ev)
    out.sort(key=lambda e: e.get("t", 0.0))
    return out


def _matches(ev: Dict, key: str) -> bool:
    if ev.get("trace") == key:
        return True
    rid = ev.get("req_id")
    # "req_id:N" is the key --list prints for trace-less events — every
    # listed selector must round-trip through --req
    return rid is not None and key in (str(rid), f"req-{rid}", f"req_id:{rid}")


def request_ids(bundles: List[Dict]) -> Dict[str, Dict[str, int]]:
    """{trace-or-req key: {tier: event count}} over every bundle."""
    out: Dict[str, Dict[str, int]] = {}
    for ev in merged_events(bundles):
        key = ev.get("trace")
        if key is None and ev.get("req_id") is not None:
            key = f"req_id:{ev['req_id']}"
        if key is None:
            continue
        per = out.setdefault(key, {})
        per[ev["_tier"]] = per.get(ev["_tier"], 0) + 1
    return out


def timeline_for(bundles: List[Dict], key: str) -> List[Dict]:
    """One request's cross-tier timeline: its flight events (router +
    replica, joined on the trace id) merged with its spans, sorted by
    timestamp. Each entry: {"t", "kind": "event"|"span", "tier", "name",
    ...original fields}."""
    entries: List[Dict] = []
    for ev in merged_events(bundles):
        if _matches(ev, key):
            e = dict(ev)
            e["kind"] = "event"
            e["tier"] = e.pop("_tier")
            entries.append(e)
    seen_spans = set()
    for b in bundles:
        for sp in b.get("spans", ()):
            if sp.get("trace") != key:
                continue
            skey = (sp.get("name"), sp.get("ts"), sp.get("tid"))
            if skey in seen_spans:
                continue
            seen_spans.add(skey)
            entries.append({"kind": "span", "tier": b.get("tier", "?"),
                            "name": sp.get("name"), "t": sp.get("ts", 0.0),
                            "dur": sp.get("dur"), "args": sp.get("args")})
    entries.sort(key=lambda e: e.get("t", 0.0))
    return entries


def attribution_for(bundles: List[Dict], key: str) -> Optional[Dict]:
    """The request's latency-attribution record from any bundle's
    finished-request tail (replica bundles carry it in
    health.recent_finished)."""
    for b in bundles:
        for row in (b.get("health") or {}).get("recent_finished", ()) or ():
            if row.get("trace") == key or str(row.get("req_id")) == key:
                return row
    return None


def render_timeline(entries: List[Dict]) -> List[str]:
    """Human-readable trail lines, one per entry, t-relative to the first."""
    if not entries:
        return ["  (no events or spans for this request)"]
    t0 = entries[0].get("t", 0.0)
    lines = []
    for e in entries:
        dt = (e.get("t", 0.0) - t0) * 1e3
        extra = {k: v for k, v in e.items()
                 if k not in ("t", "kind", "tier", "name", "seq", "trace", "args", "dur")}
        if e.get("dur") is not None:
            extra["dur_ms"] = round(e["dur"] * 1e3, 3)
        if e.get("args"):
            extra.update(e["args"])
        detail = " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
        lines.append(f"  +{dt:10.3f}ms  [{e['tier']:>7}] {e['kind']:<5} "
                     f"{e['name']:<24} {detail}".rstrip())
    return lines


def _top_tenant_line(bundle: Dict) -> Optional[str]:
    """One headline from the bundle's rolling usage aggregate: who was
    burning the most billed tokens when the incident fired. None when the
    bundle predates usage metering (or is router-tier)."""
    usage = (bundle.get("health") or {}).get("usage") or {}
    tenants = usage.get("tenants") or {}
    if not tenants:
        return None
    def billed(b):
        return (b.get("prompt_tokens", 0) - b.get("cached_tokens", 0)
                + b.get("completion_tokens", 0))
    top, bucket = max(tenants.items(), key=lambda kv: billed(kv[1]))
    return (f"usage: {usage.get('records', 0)} records, top tenant "
            f"{top} ({billed(bucket)} billed tokens, "
            f"{bucket.get('records', 0)} requests)")


def _summary(bundles: List[Dict]) -> List[str]:
    lines = []
    for b in bundles:
        health = b.get("health") or {}
        lines.append(f"{b['_path']}:")
        lines.append(f"  tier={b.get('tier')} trigger={b.get('trigger')} "
                     f"wall_time={b.get('wall_time')}")
        if b.get("detail"):
            lines.append(f"  detail: {json.dumps(b['detail'])[:200]}")
        lines.append(f"  events={len(b.get('events', []))} "
                     f"(dropped {b.get('events_dropped', 0)}), "
                     f"spans={len(b.get('spans', []))} "
                     f"(dropped {b.get('spans_dropped', 0)})")
        for k in ("loop_state", "pending", "slot_quarantines", "policy"):
            if k in health:
                lines.append(f"  {k}={health[k]}")
        top = _top_tenant_line(b)
        if top is not None:
            lines.append(f"  {top}")
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    req = None
    if "--req" in argv:
        i = argv.index("--req")
        if i + 1 >= len(argv):
            print(__doc__)
            return 2
        req = argv[i + 1]
        del argv[i:i + 2]
    list_mode = "--list" in argv
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print(__doc__)
        return 2
    bundles = load_bundles(paths)
    if req is not None:
        entries = timeline_for(bundles, req)
        print(f"decision trail for {req} "
              f"({sum(1 for e in entries if e['kind'] == 'event')} events, "
              f"{sum(1 for e in entries if e['kind'] == 'span')} spans):")
        for line in render_timeline(entries):
            print(line)
        row = attribution_for(bundles, req)
        if row is not None and row.get("attribution"):
            e2e = (row.get("finish_t") or 0) - (row.get("arrival_t") or 0)
            print(f"latency attribution (e2e {e2e * 1e3:.1f}ms, "
                  f"finish_reason={row.get('finish_reason')}):")
            for phase, v in row["attribution"].items():
                print(f"  {phase:<16} {v * 1e3:10.3f}ms")
        else:
            print("latency attribution: not in these bundles "
                  "(request unfinished at dump time, or router-only bundle)")
        return 0
    if list_mode:
        for b in bundles:
            top = _top_tenant_line(b)
            if top is not None:
                print(f"{b['_path']}: {top}")
        for key, per in sorted(request_ids(bundles).items()):
            counts = " ".join(f"{t}={n}" for t, n in sorted(per.items()))
            print(f"{key:<16} {counts}")
        return 0
    for line in _summary(bundles):
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
