"""Fault-point catalog lint: every fault point used in source must be
registered and documented, and every catalog entry must have a call site.

Same contract as ``tools/check_metrics.py`` for the metric catalog: the fault
names are stable API (chaos tests and the ``PDNLP_TPU_FAULTS`` env spec refer
to them by string), so drift between call sites and
``paddlenlp_tpu.utils.faults.CATALOG`` means a chaos test that silently never
fires. Checks:

- every ``FaultPoint("name")`` / ``FAULTS.arm("name")`` / ``fire("name")``
  in ``paddlenlp_tpu/`` names a CATALOG entry;
- every CATALOG entry has a real doc (>= 20 chars — "TODO" doesn't count);
- every CATALOG entry has at least one ``FaultPoint`` call site in source
  (a registered-but-unwired fault point is dead chaos coverage).

Prints ONE JSON line (``{"ok": ..., "catalog": N, "call_sites": M,
"problems": [...]}``) and exits non-zero on problems —
``tests/robustness/test_check_faults.py`` runs it so tier-1 enforces the
catalog on every PR.

Usage::

    python tools/check_faults.py
"""

from __future__ import annotations

import importlib.util
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC_DIR = os.path.join(ROOT, "paddlenlp_tpu")


def _load_catalog():
    """Load faults.py directly by path — importing it through the
    ``paddlenlp_tpu`` package would execute the package __init__ (jax and
    all); the module itself is stdlib-only so the lint stays dependency-free."""
    path = os.path.join(SRC_DIR, "utils", "faults.py")
    spec = importlib.util.spec_from_file_location("_pdnlp_faults_lint", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclass field-type resolution looks the module up in sys.modules
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod.CATALOG

# FaultPoint("x.y") declarations and registry-level uses of a literal name
_RE_POINT = re.compile(r'FaultPoint\(\s*[\'"]([\w.]+)[\'"]')
_RE_REGISTRY = re.compile(r'FAULTS\.(?:arm|fire)\(\s*[\'"]([\w.]+)[\'"]')


def scan_call_sites(src_dir: str = SRC_DIR):
    """name → [relpath, ...] for every fault-point reference in source."""
    sites = {}
    for root, _dirs, names in os.walk(src_dir):
        for name in names:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, ROOT)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for rx in (_RE_POINT, _RE_REGISTRY):
                for m in rx.finditer(text):
                    sites.setdefault(m.group(1), []).append(rel)
    return sites


def main() -> int:
    CATALOG = _load_catalog()
    sites = scan_call_sites()
    problems = []
    for used, where in sorted(sites.items()):
        if used not in CATALOG:
            problems.append(f"fault point {used!r} used in {sorted(set(where))} "
                            "but not registered in faults.CATALOG")
    for name, doc in sorted(CATALOG.items()):
        if not doc or len(doc.strip()) < 20:
            problems.append(f"catalog entry {name!r} has no meaningful doc")
        if name not in sites:
            problems.append(f"catalog entry {name!r} has no call site under paddlenlp_tpu/ "
                            "(dead chaos coverage — wire it or drop it)")
    print(json.dumps({
        "ok": not problems,
        "catalog": len(CATALOG),
        "call_sites": sum(len(v) for v in sites.values()),
        "problems": problems,
    }))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
