"""Fault-point catalog lint — thin shim over the static-analysis suite.

The implementation moved to ``tools/analyze/checkers/catalogs.py`` (the
``faults-catalog`` checker), which also runs under ``python -m tools.analyze``
with the baseline ratchet. This entry point is kept because the fault names
are stable API and so is this tool's contract: chaos docs and
``tests/robustness/test_check_faults.py`` invoke it directly and parse its
ONE JSON line (``{"ok": ..., "catalog": N, "call_sites": M,
"problems": [...]}``), exiting non-zero on problems.

Checks (see the checker module for details):

- every ``FaultPoint("name")`` / ``FAULTS.arm("name")`` / ``fire("name")``
  in ``paddlenlp_tpu/`` names a CATALOG entry;
- every CATALOG entry has a real doc (>= 20 chars — "TODO" doesn't count);
- every CATALOG entry has at least one call site in source.

Usage::

    python tools/check_faults.py
"""

from __future__ import annotations

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC_DIR = os.path.join(ROOT, "paddlenlp_tpu")

if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.analyze.checkers.catalogs import (  # noqa: E402
    faults_problems,
    faults_scan_call_sites,
    load_module_by_path,
)


def _load_catalog():
    """Load faults.py directly by path — importing it through the
    ``paddlenlp_tpu`` package would execute the package __init__ (jax and
    all); the module itself is stdlib-only so the lint stays dependency-free."""
    return load_module_by_path(os.path.join(SRC_DIR, "utils", "faults.py"),
                               "_pdnlp_faults_lint").CATALOG


def scan_call_sites(src_dir: str = SRC_DIR):
    """name → [relpath, ...] for every fault-point reference in source."""
    return faults_scan_call_sites(None, src_dir, ROOT)


def main() -> int:
    CATALOG = _load_catalog()
    sites = scan_call_sites()
    problems = faults_problems(CATALOG, sites)
    print(json.dumps({
        "ok": not problems,
        "catalog": len(CATALOG),
        "call_sites": sum(len(v) for v in sites.values()),
        "problems": problems,
    }))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
