#!/bin/bash
# Hourly TPU-tunnel probe. Appends one JSON line per attempt to
# BENCH_PROBELOG.jsonl (round evidence: VERDICT r2 asked for a recorded probe
# log proving whether the tunnel ever opened). Exits 0 the moment a probe
# succeeds so the orchestrator is notified and can run the full bench.
cd /root/repo
LOG=BENCH_PROBELOG.jsonl
for i in $(seq 1 70); do
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  OUT=$(timeout 180 python - <<'EOF' 2>&1
import json
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), dtype=jnp.bfloat16)
y = jax.jit(lambda a: a @ a)(x)
jax.block_until_ready(y)
print(json.dumps({"ok": True, "device": str(jax.devices()[0])}))
EOF
)
  RC=$?
  if [ $RC -eq 0 ] && echo "$OUT" | grep -q '"ok": true'; then
    echo "{\"ts\": \"$TS\", \"attempt\": $i, \"ok\": true, \"detail\": $(echo "$OUT" | tail -1)}" >> "$LOG"
    echo "TUNNEL OPEN at $TS (attempt $i)"
    exit 0
  fi
  DETAIL=$(echo "$OUT" | tail -1 | head -c 200 | python -c 'import json,sys; print(json.dumps(sys.stdin.read()))')
  echo "{\"ts\": \"$TS\", \"attempt\": $i, \"ok\": false, \"rc\": $RC, \"detail\": $DETAIL}" >> "$LOG"
  sleep 600
done
echo "tunnel never opened after 70 probes at 10-min intervals"
exit 1
