"""SentencePiece converter: pure-python ModelProto parse + fast-tokenizer build
(counterpart of reference convert_slow_tokenizer.py SpmConverter; the test
hand-encodes spm protos with a minimal proto2 writer so no sentencepiece wheel
is needed)."""

import os
import struct

import pytest


def varint(n):
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def field(no, wt, payload):
    if wt == 0:
        return varint(no << 3 | 0) + varint(payload)
    return varint(no << 3 | 2) + varint(len(payload)) + payload


def piece(p, score, t=1):
    body = field(1, 2, p.encode()) + varint(2 << 3 | 5) + struct.pack("<f", score) + field(3, 0, t)
    return field(1, 2, body)


UNIGRAM_PIECES = [("<unk>", 0.0, 2), ("<s>", 0.0, 3), ("</s>", 0.0, 3),
                  ("▁", -3.0, 1), ("▁hello", -1.0, 1), ("▁world", -1.5, 1),
                  ("h", -4.0, 1), ("e", -4.0, 1), ("l", -4.0, 1), ("o", -4.0, 1),
                  ("w", -4.0, 1), ("r", -4.0, 1), ("d", -4.0, 1)]


def write_unigram_spm(path):
    proto = b"".join(piece(p, s, t) for p, s, t in UNIGRAM_PIECES)
    proto += field(2, 2, field(3, 0, 1) + field(40, 0, 0) + field(41, 0, 1) + field(42, 0, 2)
                   + field(43, 0, 2**64 - 1))  # pad_id = -1
    proto += field(3, 2, field(3, 0, 1))  # add_dummy_prefix=true
    with open(path, "wb") as f:
        f.write(proto)


class TestProtoParse:
    def test_parse_fields(self, tmp_path):
        from paddlenlp_tpu.transformers.convert_slow_tokenizer import parse_spm_model

        p = tmp_path / "spiece.model"
        write_unigram_spm(str(p))
        m = parse_spm_model(p.read_bytes())
        assert [x[0] for x in m.pieces[:4]] == ["<unk>", "<s>", "</s>", "▁"]
        assert m.pieces[4] == ("▁hello", pytest.approx(-1.0), 1)
        assert m.model_type == 1 and m.unk_id == 0 and m.bos_id == 1 and m.eos_id == 2
        assert m.pad_id == -1  # sign-extended negative varint decoded
        assert m.add_dummy_prefix


class TestUnigramConvert:
    def test_tokenize_and_bos(self, tmp_path):
        from paddlenlp_tpu.transformers.convert_slow_tokenizer import convert_spm_to_fast

        p = tmp_path / "spiece.model"
        write_unigram_spm(str(p))
        tok = convert_spm_to_fast(str(p))
        enc = tok.encode("hello world")
        assert enc.tokens[0] == "<s>"  # llama-style bos template
        assert "▁hello" in enc.tokens and "▁world" in enc.tokens

    def test_tokenizer_from_pretrained_spm_only(self, tmp_path):
        """A checkpoint dir with ONLY tokenizer.model (llama lineage) loads
        through the normal path with the bos-prepending template."""
        from paddlenlp_tpu.transformers import PretrainedTokenizer

        write_unigram_spm(str(tmp_path / "tokenizer.model"))
        tok = PretrainedTokenizer.from_pretrained(str(tmp_path))
        ids = tok("hello world")["input_ids"]
        assert ids[0] == 1  # bos
        assert tok._tokenizer.decode(ids, skip_special_tokens=True).strip() == "hello world"

    def test_spiece_gets_t5_style_eos(self, tmp_path):
        """spiece.model (t5 lineage) defaults to appending </s>, no bos."""
        from paddlenlp_tpu.transformers import PretrainedTokenizer

        write_unigram_spm(str(tmp_path / "spiece.model"))
        tok = PretrainedTokenizer.from_pretrained(str(tmp_path))
        ids = tok("hello world")["input_ids"]
        assert ids[-1] == 2 and ids[0] != 1  # </s> appended, no <s>

    def test_tokenizer_config_overrides_template(self, tmp_path):
        """Explicit add_bos_token/add_eos_token in tokenizer_config.json win."""
        import json

        from paddlenlp_tpu.transformers import PretrainedTokenizer

        write_unigram_spm(str(tmp_path / "spiece.model"))
        (tmp_path / "tokenizer_config.json").write_text(
            json.dumps({"add_bos_token": True, "add_eos_token": False}))
        tok = PretrainedTokenizer.from_pretrained(str(tmp_path))
        ids = tok("hello world")["input_ids"]
        assert ids[0] == 1 and ids[-1] != 2

    def test_save_roundtrip_to_fast(self, tmp_path):
        """Converted tokenizer saves as tokenizer.json and reloads identically."""
        from paddlenlp_tpu.transformers import PretrainedTokenizer

        write_unigram_spm(str(tmp_path / "spiece.model"))
        tok = PretrainedTokenizer.from_pretrained(str(tmp_path))
        out = tmp_path / "saved"
        tok.save_pretrained(str(out))
        assert (out / "tokenizer.json").exists()
        tok2 = PretrainedTokenizer.from_pretrained(str(out))
        assert tok2("hello world")["input_ids"] == tok("hello world")["input_ids"]


class TestMBartLineage:
    def test_bpe_model_appends_eos_and_lang_codes(self, tmp_path):
        """sentencepiece.bpe.model defaults to eos-appending; lang codes from
        additional_special_tokens are grafted onto the converted vocab."""
        import json

        from paddlenlp_tpu.transformers import PretrainedTokenizer

        write_unigram_spm(str(tmp_path / "sentencepiece.bpe.model"))
        (tmp_path / "tokenizer_config.json").write_text(
            json.dumps({"additional_special_tokens": ["en_XX", "ro_RO"]}))
        tok = PretrainedTokenizer.from_pretrained(str(tmp_path))
        ids = tok("hello world")["input_ids"]
        assert ids[-1] == 2 and ids[0] != 1  # </s> appended, no <s>
        en = tok._tokenizer.token_to_id("en_XX")
        assert en is not None and en >= len(UNIGRAM_PIECES)


class TestBPEConvert:
    def test_bpe_merges_extracted(self, tmp_path):
        from paddlenlp_tpu.transformers.convert_slow_tokenizer import convert_spm_to_fast

        pieces = [("<unk>", 0.0, 2), ("<s>", 0.0, 3), ("</s>", 0.0, 3),
                  ("▁", -1.0, 1), ("h", -2.0, 1), ("e", -2.0, 1), ("l", -2.0, 1), ("o", -2.0, 1),
                  ("he", -0.5, 1), ("ll", -0.6, 1), ("hell", -0.3, 1), ("hello", -0.1, 1),
                  ("▁hello", -0.05, 1)]
        proto = b"".join(piece(p, s, t) for p, s, t in pieces)
        proto += field(2, 2, field(3, 0, 2) + field(40, 0, 0))  # model_type=BPE
        proto += field(3, 2, field(3, 0, 1))
        p = tmp_path / "tokenizer.model"
        p.write_bytes(proto)
        tok = convert_spm_to_fast(str(p))
        enc = tok.encode("hello")
        assert enc.tokens[-1] == "▁hello"  # merges reach the full word
