"""Static export (StableHLO via jax.export) + distillation utilities
(reference transformers/export.py + distill_utils.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

from paddlenlp_tpu.transformers import BertConfig, BertForSequenceClassification, LlamaConfig, LlamaForCausalLM


class TestExport:
    def test_export_import_roundtrip(self, tmp_path):
        from paddlenlp_tpu.transformers.export import export_model, import_model

        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        export_model(model, str(tmp_path), batch_size=1, seq_length=8)
        assert (tmp_path / "model.stablehlo").exists()
        fn, config = import_model(str(tmp_path))
        ids = jnp.asarray(np.arange(8)[None] % 60 + 2, jnp.int32)
        got = np.asarray(fn(ids))
        want = np.asarray(model(input_ids=ids).logits)
        np.testing.assert_allclose(got, want, atol=1e-5)
        assert config["input_names"] == ["input_ids"]


class TestDistill:
    def _pair(self):
        mk = lambda h, L, seed: BertForSequenceClassification.from_config(
            BertConfig(vocab_size=64, hidden_size=h, num_hidden_layers=L, num_attention_heads=2,
                       intermediate_size=2 * h, max_position_embeddings=32, num_labels=2,
                       hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0), seed=seed)
        return mk(32, 1, 0), mk(32, 2, 1)  # student, teacher

    def test_losses_zero_when_identical(self):
        from paddlenlp_tpu.transformers.distill_utils import (
            hidden_mse_loss, kl_div_loss, soft_cross_entropy)

        logits = jnp.asarray(np.random.default_rng(0).standard_normal((4, 8)), jnp.float32)
        assert float(kl_div_loss(logits, logits)) < 1e-6
        assert float(hidden_mse_loss(logits, logits)) < 1e-9
        # soft CE at identical logits equals the teacher's entropy (not 0)
        assert float(soft_cross_entropy(logits, logits)) > 0

    def test_minilm_relation_loss_shapes(self):
        from paddlenlp_tpu.transformers.distill_utils import minilm_relation_loss

        rng = np.random.default_rng(0)
        s = jnp.asarray(rng.standard_normal((2, 6, 32)), jnp.float32)
        t = jnp.asarray(rng.standard_normal((2, 6, 48)), jnp.float32)
        loss = minilm_relation_loss(s, t, num_relation_heads=4)
        assert np.isfinite(float(loss))
        assert float(minilm_relation_loss(s, s, num_relation_heads=4)) < 1e-6

    def test_distill_trainer_loss_decreases(self, tmp_path):
        from paddlenlp_tpu.transformers.distill_utils import DistillTrainer
        from paddlenlp_tpu.trainer import TrainingArguments

        student, teacher = self._pair()
        data = [{"input_ids": np.asarray([2, 5, 6, 7], np.int32),
                 "labels": np.asarray(1, np.int32)} for _ in range(16)]
        args = TrainingArguments(output_dir=str(tmp_path), per_device_train_batch_size=1,
                                 learning_rate=1e-3, num_train_epochs=2, logging_steps=100)
        trainer = DistillTrainer(model=student, args=args, train_dataset=data,
                                 teacher=teacher, alpha=0.5, temperature=2.0)
        result = trainer.train()
        assert np.isfinite(result.training_loss)
