"""LLaMA unit tests — the ModelTesterMixin pattern from the reference
(tests/transformers/test_modeling_common.py): tiny random configs, forward shape
checks, save/load round-trip, decode-cache parity, sharded-vs-replicated parity."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.parallel import MeshConfig, create_mesh, use_mesh
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM, LlamaModel, init_cache


def tiny_config(**kwargs):
    defaults = dict(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=112,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
    )
    defaults.update(kwargs)
    return LlamaConfig(**defaults)


class TestLlamaForward:
    def test_forward_shapes(self):
        model = LlamaForCausalLM.from_config(tiny_config(), seed=0)
        ids = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], dtype=jnp.int32)
        out = model(input_ids=ids)
        assert out.logits.shape == (1, 8, 128)
        assert out.logits.dtype == jnp.float32

    def test_base_model(self):
        model = LlamaModel.from_config(tiny_config(), seed=0)
        ids = jnp.array([[1, 2, 3, 4]], dtype=jnp.int32)
        out = model(input_ids=ids)
        assert out.last_hidden_state.shape == (1, 4, 64)

    def test_deterministic(self):
        model = LlamaForCausalLM.from_config(tiny_config(), seed=0)
        ids = jnp.array([[5, 6, 7]], dtype=jnp.int32)
        a = model(input_ids=ids).logits
        b = model(input_ids=ids).logits
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_attention_mask_padding(self):
        """Left-context invariance: padding tokens must not change later logits."""
        model = LlamaForCausalLM.from_config(tiny_config(), seed=0)
        ids = jnp.array([[9, 10, 11, 12]], dtype=jnp.int32)
        full = model(input_ids=ids).logits
        padded_ids = jnp.array([[9, 10, 11, 12, 0, 0]], dtype=jnp.int32)
        mask = jnp.array([[1, 1, 1, 1, 0, 0]], dtype=jnp.int32)
        padded = model(input_ids=padded_ids, attention_mask=mask).logits
        np.testing.assert_allclose(np.asarray(full[0, :4]), np.asarray(padded[0, :4]), atol=2e-5)

    def test_gqa_heads(self):
        cfg = tiny_config(num_attention_heads=8, num_key_value_heads=2)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        out = model(input_ids=jnp.ones((2, 6), dtype=jnp.int32))
        assert out.logits.shape == (2, 6, 128)

    def test_kv_cache_decode_parity(self):
        """Prefill+decode through the static cache == one full forward."""
        cfg = tiny_config()
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        ids = jnp.array([[3, 1, 4, 1, 5, 9]], dtype=jnp.int32)
        full = model(input_ids=ids).logits

        cache = init_cache(cfg, batch_size=1, max_length=16, dtype=jnp.float32)
        out = model(input_ids=ids[:, :4], cache=cache)
        cache = out.past_key_values
        logits_4 = out.logits[:, -1]
        np.testing.assert_allclose(np.asarray(logits_4), np.asarray(full[:, 3]), atol=2e-5)
        for t in range(4, 6):
            out = model(input_ids=ids[:, t : t + 1], cache=cache)
            cache = out.past_key_values
            np.testing.assert_allclose(np.asarray(out.logits[:, -1]), np.asarray(full[:, t]), atol=2e-5)

    def test_packed_segments(self):
        """Packed batch (ZeroPadding/flashmask equivalent): two segments in one row
        give the same logits as two separate rows."""
        model = LlamaForCausalLM.from_config(tiny_config(), seed=0)
        a = jnp.array([[7, 8, 9]], dtype=jnp.int32)
        b = jnp.array([[20, 21, 22]], dtype=jnp.int32)
        la = model(input_ids=a).logits
        lb = model(input_ids=b).logits
        packed = jnp.concatenate([a, b], axis=1)
        seg = jnp.array([[0, 0, 0, 1, 1, 1]], dtype=jnp.int32)
        lp = model(input_ids=packed, segment_ids=seg, position_ids=jnp.array([[0, 1, 2, 0, 1, 2]])).logits
        np.testing.assert_allclose(np.asarray(lp[0, :3]), np.asarray(la[0]), atol=2e-5)
        np.testing.assert_allclose(np.asarray(lp[0, 3:]), np.asarray(lb[0]), atol=2e-5)


class TestLlamaSaveLoad:
    def test_save_load_roundtrip(self, tmp_path):
        model = LlamaForCausalLM.from_config(tiny_config(), seed=0)
        ids = jnp.array([[1, 2, 3]], dtype=jnp.int32)
        before = model(input_ids=ids).logits
        model.save_pretrained(str(tmp_path))
        assert os.path.isfile(tmp_path / "model.safetensors")
        assert os.path.isfile(tmp_path / "config.json")
        loaded = LlamaForCausalLM.from_pretrained(str(tmp_path))
        after = loaded(input_ids=ids).logits
        np.testing.assert_allclose(np.asarray(before), np.asarray(after), atol=1e-6)

    def test_hf_key_format(self, tmp_path):
        """Saved checkpoints must use HF llama key names (checkpoint interop)."""
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        model = LlamaForCausalLM.from_config(tiny_config(num_hidden_layers=1), seed=0)
        model.save_pretrained(str(tmp_path))
        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "model.embed_tokens.weight" in keys
        assert "model.layers.0.self_attn.q_proj.weight" in keys
        assert "model.layers.0.mlp.gate_proj.weight" in keys
        assert "model.norm.weight" in keys
        assert "lm_head.weight" in keys

    def test_load_from_hf_torch_layout(self, tmp_path):
        """A checkpoint written with torch [out,in] Linear layout loads correctly.

        Weights are perturbed (x1.5) before the torch round-trip so a silent
        fallback to same-seed fresh init CANNOT pass the parity check.
        """
        import torch
        from safetensors.torch import save_file as torch_save

        cfg = tiny_config(num_hidden_layers=1, use_scan_layers=False)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        model.params = jax.tree.map(lambda x: x * 1.5, model.params)
        # round-trip through a torch-style file: transpose kernels like HF does
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params
        flat = flatten_params(model.params)
        tensors = {}
        for path, arr in flat.items():
            from paddlenlp_tpu.transformers.conversion_utils import target_to_hf_key
            key = target_to_hf_key(path)
            a = np.asarray(jax.device_get(arr))
            if path.endswith("/kernel"):
                a = a.T
            tensors[key] = torch.from_numpy(np.ascontiguousarray(a))
        torch_save(tensors, str(tmp_path / "model.safetensors"))
        cfg.save_pretrained(str(tmp_path))
        loaded = LlamaForCausalLM.from_pretrained(str(tmp_path))
        ids = jnp.array([[1, 2, 3]], dtype=jnp.int32)
        np.testing.assert_allclose(
            np.asarray(model(input_ids=ids).logits), np.asarray(loaded(input_ids=ids).logits), atol=1e-6
        )


class TestLlamaSharded:
    def test_tp_parity(self, eight_devices):
        """tp=4 sharded forward == replicated forward (GSPMD correctness)."""
        cfg = tiny_config()
        mesh = create_mesh(MeshConfig(dp=2, tp=4))
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        ref = model(input_ids=jnp.ones((2, 8), dtype=jnp.int32)).logits

        sharded = LlamaForCausalLM.from_config(cfg, seed=0, mesh=mesh)
        with use_mesh(mesh):
            out = sharded(input_ids=jnp.ones((2, 8), dtype=jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=1e-4)

    def test_param_shardings_applied(self, eight_devices):
        cfg = tiny_config(use_scan_layers=False)
        mesh = create_mesh(MeshConfig(dp=1, fsdp=2, tp=4))
        model = LlamaForCausalLM.from_config(cfg, seed=0, mesh=mesh)
        qk = model.params["model"]["layers_0"]["self_attn"]["q_proj"]["kernel"]
        spec = qk.sharding.spec
        assert spec == jax.sharding.PartitionSpec("fsdp", "tp")
        emb = model.params["model"]["embed_tokens"]["embedding"]
        assert emb.sharding.spec == jax.sharding.PartitionSpec("tp", "fsdp")


class TestLlamaRecompute:
    @pytest.mark.parametrize("granularity", ["full", "full_attn", "core_attn"])
    def test_recompute_grad_parity(self, granularity):
        """Remat must not change gradients (reference recompute_granularity knob)."""
        cfg = tiny_config()
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        ids = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], dtype=jnp.int32)

        def loss_fn(params, config):
            m = LlamaForCausalLM(config, params=params)
            logits = m.apply(params, input_ids=ids[:, :-1]).logits
            from paddlenlp_tpu.ops import causal_lm_loss
            return causal_lm_loss(logits, ids[:, 1:])

        g_plain = jax.grad(loss_fn)(model.params, cfg)
        cfg_r = tiny_config(recompute=True, recompute_granularity=granularity)
        g_remat = jax.grad(loss_fn)(model.params, cfg_r)
        for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


class TestLlamaScanLayers:
    def test_scan_matches_unrolled(self):
        """Scanned-layer stack == unrolled layers, loading the SAME checkpoint."""
        import tempfile

        cfg = tiny_config(use_scan_layers=False)  # baseline: genuinely unrolled
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        ids = jnp.array([[3, 1, 4, 1, 5, 9, 2, 6]], dtype=jnp.int32)
        ref = model(input_ids=ids).logits
        with tempfile.TemporaryDirectory() as d:
            model.save_pretrained(d)
            scan_cfg = tiny_config(use_scan_layers=True)
            scan_model = LlamaForCausalLM.from_pretrained(d, config=scan_cfg)
            got = scan_model(input_ids=ids).logits
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got), atol=1e-5)

    def test_scan_checkpoint_identical_keys(self, tmp_path):
        """A scan model's checkpoint keeps HF per-layer keys (interop both ways)."""
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        cfg = tiny_config(use_scan_layers=True)
        m = LlamaForCausalLM.from_config(cfg, seed=0)
        m.save_pretrained(str(tmp_path))
        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "model.layers.0.self_attn.q_proj.weight" in keys
        assert "model.layers.1.mlp.down_proj.weight" in keys
        # and it loads back as unrolled
        unrolled = LlamaForCausalLM.from_pretrained(str(tmp_path), config=tiny_config(use_scan_layers=False))
        ids = jnp.array([[1, 2, 3]], dtype=jnp.int32)
        np.testing.assert_allclose(
            np.asarray(m(input_ids=ids).logits), np.asarray(unrolled(input_ids=ids).logits), atol=1e-5
        )

    def test_scan_generate_cache(self):
        cfg = tiny_config(use_scan_layers=True)
        ref_cfg = tiny_config(use_scan_layers=False)
        import tempfile

        model = LlamaForCausalLM.from_config(ref_cfg, seed=0)
        with tempfile.TemporaryDirectory() as d:
            model.save_pretrained(d)
            scan_model = LlamaForCausalLM.from_pretrained(d, config=cfg)
        prompt = jnp.array([[5, 6, 7, 8]], dtype=jnp.int32)
        a, _ = model.generate(prompt, max_new_tokens=6, do_sample=False)
        b, _ = scan_model.generate(prompt, max_new_tokens=6, do_sample=False)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_scan_sharded_params(self, eight_devices):
        cfg = tiny_config(use_scan_layers=True)
        mesh = create_mesh(MeshConfig(dp=2, tp=4))
        m = LlamaForCausalLM.from_config(cfg, seed=0, mesh=mesh)
        qk = m.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert qk.ndim == 3  # [L, in, out]
        assert qk.sharding.spec == jax.sharding.PartitionSpec(None, None, "tp")
