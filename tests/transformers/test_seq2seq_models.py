"""T5/BART encoder-decoder tests: forward/roundtrip, HF-torch numerical parity
(golden check of relative-position buckets, tied-head rescale, post-LN, position
offsets), cached-decode == teacher-forced parity, HF checkpoint key layout.

Mirrors the reference's tests/transformers/{t5,bart}/test_modeling.py at tiny
scale, plus the torch cross-check its CI does via converted community models."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlenlp_tpu.transformers import (
    BartConfig,
    BartForConditionalGeneration,
    MBartConfig,
    MBartForConditionalGeneration,
    MT5Config,
    MT5ForConditionalGeneration,
    PegasusConfig,
    PegasusForConditionalGeneration,
    T5Config,
    T5EncoderModel,
    T5ForConditionalGeneration,
)
from paddlenlp_tpu.transformers.t5.modeling import shift_tokens_right


def tiny_t5_cfg(**kw):
    return T5Config(vocab_size=96, d_model=64, d_kv=16, d_ff=128, num_layers=2,
                    num_heads=4, dropout_rate=0.0, **kw)


def tiny_bart_cfg(**kw):
    return BartConfig(vocab_size=96, d_model=64, encoder_layers=2, decoder_layers=2,
                      encoder_attention_heads=4, decoder_attention_heads=4,
                      encoder_ffn_dim=128, decoder_ffn_dim=128, max_position_embeddings=64,
                      dropout=0.0, attention_dropout=0.0, activation_dropout=0.0, **kw)


def tiny_mbart_cfg(**kw):
    return MBartConfig(vocab_size=96, d_model=64, encoder_layers=2, decoder_layers=2,
                       encoder_attention_heads=4, decoder_attention_heads=4,
                       encoder_ffn_dim=128, decoder_ffn_dim=128, max_position_embeddings=64,
                       dropout=0.0, attention_dropout=0.0, activation_dropout=0.0, **kw)


def tiny_pegasus_cfg(**kw):
    return PegasusConfig(vocab_size=96, d_model=64, encoder_layers=2, decoder_layers=2,
                         encoder_attention_heads=4, decoder_attention_heads=4,
                         encoder_ffn_dim=128, decoder_ffn_dim=128, max_position_embeddings=64,
                         dropout=0.0, attention_dropout=0.0, activation_dropout=0.0, **kw)


CASES = {
    "t5": (T5ForConditionalGeneration, tiny_t5_cfg),
    "t5_gated": (T5ForConditionalGeneration, lambda: tiny_t5_cfg(feed_forward_proj="gated-gelu",
                                                                tie_word_embeddings=False)),
    "bart": (BartForConditionalGeneration, tiny_bart_cfg),
    "mt5": (MT5ForConditionalGeneration, lambda: MT5Config(vocab_size=96, d_model=64, d_kv=16,
                                                           d_ff=128, num_layers=2, num_heads=4,
                                                           dropout_rate=0.0)),
    "mbart": (MBartForConditionalGeneration, tiny_mbart_cfg),
    "pegasus": (PegasusForConditionalGeneration, tiny_pegasus_cfg),
}


@pytest.mark.parametrize("name", list(CASES))
class TestSeq2SeqCommon:
    def test_forward_and_roundtrip(self, name, tmp_path):
        cls, cfg_fn = CASES[name]
        model = cls.from_config(cfg_fn(), seed=0)
        ids = jnp.asarray(np.arange(10)[None, :] % 90 + 3, dtype=jnp.int32)
        dec = jnp.asarray([[model.config.decoder_start_token_id, 5, 6, 7]], dtype=jnp.int32)
        out = model(input_ids=ids, decoder_input_ids=dec)
        assert out.logits.shape == (1, 4, 96)
        assert np.isfinite(np.asarray(out.logits)).all()
        model.save_pretrained(str(tmp_path))
        reloaded = cls.from_pretrained(str(tmp_path))
        np.testing.assert_allclose(
            np.asarray(out.logits),
            np.asarray(reloaded(input_ids=ids, decoder_input_ids=dec).logits), atol=1e-5
        )

    def test_greedy_generate_cache_parity(self, name):
        """Cached while-loop decode == argmax over repeated teacher-forced forwards."""
        cls, cfg_fn = CASES[name]
        model = cls.from_config(cfg_fn(), seed=3)
        ids = jnp.asarray([[5, 6, 7, 8, 2]], dtype=jnp.int32)
        gen, _ = model.generate(ids, max_new_tokens=4, do_sample=False, eos_token_id=94,
                                forced_bos_token_id=None, forced_eos_token_id=None)
        dec = np.asarray([[model.config.decoder_start_token_id]], dtype=np.int32)
        for _ in range(4):
            logits = model(input_ids=ids, decoder_input_ids=jnp.asarray(dec)).logits
            dec = np.concatenate([dec, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
        np.testing.assert_array_equal(np.asarray(gen)[0], dec[0, 1:])

    def test_padding_invariance(self, name):
        """Encoder pad tokens masked out must not change decoder logits."""
        cls, cfg_fn = CASES[name]
        model = cls.from_config(cfg_fn(), seed=0)
        pad = model.config.pad_token_id
        ids = jnp.asarray([[5, 6, 7, 8]], dtype=jnp.int32)
        dec = jnp.asarray([[model.config.decoder_start_token_id, 5]], dtype=jnp.int32)
        full = model(input_ids=ids, attention_mask=jnp.ones_like(ids), decoder_input_ids=dec).logits
        padded = jnp.asarray([[5, 6, 7, 8, pad, pad]], dtype=jnp.int32)
        mask = jnp.asarray([[1, 1, 1, 1, 0, 0]], dtype=jnp.int32)
        out = model(input_ids=padded, attention_mask=mask, decoder_input_ids=dec).logits
        np.testing.assert_allclose(np.asarray(full), np.asarray(out), atol=2e-5)


class TestT5Specifics:
    def test_hf_key_format(self, tmp_path):
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        model = T5ForConditionalGeneration.from_config(tiny_t5_cfg(), seed=0)
        model.save_pretrained(str(tmp_path))
        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        for want in [
            "shared.weight",
            "encoder.block.0.layer.0.SelfAttention.q.weight",
            "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight",
            "decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight",
            "decoder.block.1.layer.1.EncDecAttention.o.weight",
            "decoder.block.0.layer.2.DenseReluDense.wi.weight",
            "encoder.final_layer_norm.weight",
        ]:
            assert want in keys, f"missing {want}"
        # block-0-only bias (the stack-level table maps to HF's block-0 slot)
        assert "encoder.block.1.layer.0.SelfAttention.relative_attention_bias.weight" not in keys

    def test_torch_parity(self, tmp_path):
        """Golden numerical check vs transformers' torch T5 on identical weights."""
        torch = pytest.importorskip("torch")
        from transformers import T5Config as HFC, T5ForConditionalGeneration as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=96, d_model=64, d_kv=16, d_ff=128, num_layers=2,
                     num_decoder_layers=2, num_heads=4, dropout_rate=0.0,
                     feed_forward_proj="relu", tie_word_embeddings=True)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        ids_t = torch.tensor([[5, 6, 7, 8, 1]])
        dec_t = torch.tensor([[0, 9, 10]])
        with torch.no_grad():
            golden = hm(input_ids=ids_t, decoder_input_ids=dec_t).logits.numpy()
        model = T5ForConditionalGeneration.from_pretrained(str(tmp_path))
        mine = model(input_ids=jnp.asarray([[5, 6, 7, 8, 1]], dtype=jnp.int32),
                     decoder_input_ids=jnp.asarray([[0, 9, 10]], dtype=jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=2e-4)

    def test_encoder_model(self):
        model = T5EncoderModel.from_config(tiny_t5_cfg(), seed=0)
        out = model(input_ids=jnp.asarray([[5, 6, 7]], dtype=jnp.int32))
        assert out.last_hidden_state.shape == (1, 3, 64)

    def test_shift_tokens_right(self):
        labels = jnp.asarray([[5, 6, -100, -100]], dtype=jnp.int32)
        shifted = shift_tokens_right(labels, pad_token_id=0, decoder_start_token_id=7)
        np.testing.assert_array_equal(np.asarray(shifted), [[7, 5, 6, 0]])


class TestBartSpecifics:
    def test_hf_key_format(self, tmp_path):
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        model = BartForConditionalGeneration.from_config(tiny_bart_cfg(), seed=0)
        model.save_pretrained(str(tmp_path))
        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        for want in [
            "model.shared.weight",
            "model.encoder.embed_positions.weight",
            "model.encoder.layernorm_embedding.weight",
            "model.encoder.layers.0.self_attn.q_proj.weight",
            "model.encoder.layers.0.self_attn.q_proj.bias",
            "model.decoder.layers.1.encoder_attn.out_proj.weight",
            "model.decoder.layers.0.fc1.weight",
            "final_logits_bias",
        ]:
            assert want in keys, f"missing {want}"

    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import BartConfig as HFC, BartForConditionalGeneration as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=96, d_model=64, encoder_layers=2, decoder_layers=2,
                     encoder_attention_heads=4, decoder_attention_heads=4,
                     encoder_ffn_dim=128, decoder_ffn_dim=128, max_position_embeddings=64,
                     dropout=0.0, attention_dropout=0.0, activation_dropout=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor([[5, 6, 7, 8, 2]]),
                        decoder_input_ids=torch.tensor([[2, 0, 9, 10]])).logits.numpy()
        model = BartForConditionalGeneration.from_pretrained(str(tmp_path))
        mine = model(input_ids=jnp.asarray([[5, 6, 7, 8, 2]], dtype=jnp.int32),
                     decoder_input_ids=jnp.asarray([[2, 0, 9, 10]], dtype=jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=2e-4)


class TestMBartSpecifics:
    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import MBartConfig as HFC, MBartForConditionalGeneration as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=96, d_model=64, encoder_layers=2, decoder_layers=2,
                     encoder_attention_heads=4, decoder_attention_heads=4,
                     encoder_ffn_dim=128, decoder_ffn_dim=128, max_position_embeddings=64,
                     dropout=0.0, attention_dropout=0.0, activation_dropout=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor([[5, 6, 7, 8, 2]]),
                        decoder_input_ids=torch.tensor([[2, 0, 9, 10]])).logits.numpy()
        model = MBartForConditionalGeneration.from_pretrained(str(tmp_path))
        mine = model(input_ids=jnp.asarray([[5, 6, 7, 8, 2]], dtype=jnp.int32),
                     decoder_input_ids=jnp.asarray([[2, 0, 9, 10]], dtype=jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=2e-4)

    def test_mbart_shift(self):
        from paddlenlp_tpu.transformers import mbart as _  # noqa: F401
        from paddlenlp_tpu.transformers.mbart.modeling import shift_tokens_right_mbart

        ids = jnp.asarray([[5, 6, 2, 42, 1, 1]], dtype=jnp.int32)  # ... eos lang pad pad
        shifted = shift_tokens_right_mbart(ids, pad_token_id=1)
        np.testing.assert_array_equal(np.asarray(shifted), [[42, 5, 6, 2, 42, 1]])


class TestPegasusSpecifics:
    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import PegasusConfig as HFC, PegasusForConditionalGeneration as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=96, d_model=64, encoder_layers=2, decoder_layers=2,
                     encoder_attention_heads=4, decoder_attention_heads=4,
                     encoder_ffn_dim=128, decoder_ffn_dim=128, max_position_embeddings=64,
                     dropout=0.0, attention_dropout=0.0, activation_dropout=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor([[5, 6, 7, 8, 1]]),
                        decoder_input_ids=torch.tensor([[0, 9, 10]])).logits.numpy()
        model = PegasusForConditionalGeneration.from_pretrained(str(tmp_path))
        mine = model(input_ids=jnp.asarray([[5, 6, 7, 8, 1]], dtype=jnp.int32),
                     decoder_input_ids=jnp.asarray([[0, 9, 10]], dtype=jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=2e-4)

    def test_sinusoid_table_matches_hf_layout(self):
        torch = pytest.importorskip("torch")
        from transformers.models.pegasus.modeling_pegasus import PegasusSinusoidalPositionalEmbedding

        from paddlenlp_tpu.transformers.bart.modeling import sinusoidal_position_table

        emb = PegasusSinusoidalPositionalEmbedding(16, 32)
        # HF defers the sinusoid fill to model post_init; apply it directly
        emb._init_weight()
        np.testing.assert_allclose(np.asarray(sinusoidal_position_table(16, 32)),
                                   emb.weight.detach().numpy(), atol=1e-5)


class TestForcedTokens:
    def test_bart_forced_eos_at_length_cap(self):
        """BartConfig defaults forced_eos_token_id=2: the last slot must be eos."""
        model = BartForConditionalGeneration.from_config(tiny_bart_cfg(), seed=0)
        ids = jnp.asarray([[5, 6, 7, 8]], dtype=jnp.int32)
        gen, _ = model.generate(ids, max_new_tokens=4, do_sample=False, eos_token_id=94)
        assert int(np.asarray(gen)[0, -1]) == 2

    def test_forced_bos_first_token(self):
        model = BartForConditionalGeneration.from_config(tiny_bart_cfg(), seed=0)
        ids = jnp.asarray([[5, 6, 7, 8]], dtype=jnp.int32)
        gen, _ = model.generate(ids, max_new_tokens=3, do_sample=False, eos_token_id=94,
                                forced_bos_token_id=11, forced_eos_token_id=None)
        assert int(np.asarray(gen)[0, 0]) == 11


class TestSeq2SeqAuto:
    def test_auto_seq2seq_roundtrip(self, tmp_path):
        from paddlenlp_tpu.transformers.auto import AutoModelForSeq2SeqLM

        model = T5ForConditionalGeneration.from_config(tiny_t5_cfg(), seed=0)
        model.save_pretrained(str(tmp_path))
        auto = AutoModelForSeq2SeqLM.from_pretrained(str(tmp_path))
        assert type(auto).__name__ == "T5ForConditionalGeneration"

    def test_tp_sharded_forward(self, eight_devices):
        from paddlenlp_tpu.parallel import MeshConfig, create_mesh

        mesh = create_mesh(MeshConfig(dp=2, tp=4))
        model = T5ForConditionalGeneration.from_config(tiny_t5_cfg(), seed=0, mesh=mesh)
        q = model.params["encoder"]["block_0"]["layer_0_SelfAttention"]["q"]["kernel"]
        assert "tp" in str(q.sharding.spec)
        ids = jnp.asarray([[5, 6, 7, 8]] * 2, dtype=jnp.int32)
        dec = jnp.asarray([[0, 5]] * 2, dtype=jnp.int32)
        out = model(input_ids=ids, decoder_input_ids=dec)
        assert np.isfinite(np.asarray(out.logits)).all()
