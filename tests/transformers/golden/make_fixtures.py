"""Generate golden conversion-fidelity fixtures (run offline, outputs committed).

For each family: a tiny REAL torch/HF checkpoint (safetensors + config.json)
plus the torch fp32 logits on fixed input ids. The paired test
(``test_golden_parity.py``) loads the checkpoint through OUR ``from_pretrained``
(torch-layout transposes, fused/stacked conversions) and asserts logits parity —
the end-to-end conversion-fidelity check the reference does with
``LogitComparer`` (paddlenlp/transformers/conversion_utils.py:927).

Usage: python tests/transformers/golden/make_fixtures.py
"""

import json
import os

import numpy as np
import torch

HERE = os.path.dirname(os.path.abspath(__file__))
INPUT_IDS = np.arange(1, 17, dtype=np.int64)[None, :] % 250  # [1, 16]


def _save(name, model, extra_cfg=None):
    out = os.path.join(HERE, name)
    os.makedirs(out, exist_ok=True)
    model = model.eval()
    with torch.no_grad():
        logits = model(torch.from_numpy(INPUT_IDS)).logits.float().numpy()
    model.save_pretrained(out, safe_serialization=True)
    np.savez(os.path.join(out, "golden_logits.npz"), input_ids=INPUT_IDS, logits=logits)
    # keep the fixture minimal: drop the generation config (not under test)
    gen_cfg = os.path.join(out, "generation_config.json")
    if os.path.exists(gen_cfg):
        os.remove(gen_cfg)
    size = sum(os.path.getsize(os.path.join(out, f)) for f in os.listdir(out))
    print(f"{name}: {size/1e3:.0f} KB, logits {logits.shape}")


def main():
    torch.manual_seed(0)
    from transformers import LlamaConfig, LlamaForCausalLM

    _save("llama_tiny", LlamaForCausalLM(LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=128,
        tie_word_embeddings=False)))

    torch.manual_seed(1)
    _save("llama_gqa_tiny", LlamaForCausalLM(LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=128,
        tie_word_embeddings=False)))

    torch.manual_seed(2)
    from transformers import MixtralConfig, MixtralForCausalLM

    _save("mixtral_tiny", MixtralForCausalLM(MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=128,
        num_local_experts=4, num_experts_per_tok=2, tie_word_embeddings=False,
        output_router_logits=False)))


def encoders():
    torch.manual_seed(3)
    from transformers import RobertaConfig, RobertaForMaskedLM

    _save("roberta_tiny", RobertaForMaskedLM(RobertaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=96, pad_token_id=1,
        type_vocab_size=1, tie_word_embeddings=True)))

    torch.manual_seed(4)
    from transformers import ElectraConfig, ElectraForSequenceClassification

    _save("electra_tiny", ElectraForSequenceClassification(ElectraConfig(
        vocab_size=256, embedding_size=32, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, max_position_embeddings=96,
        num_labels=3)))

    torch.manual_seed(5)
    from transformers import AlbertConfig, AlbertForMaskedLM

    _save("albert_tiny", AlbertForMaskedLM(AlbertConfig(
        vocab_size=256, embedding_size=32, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, max_position_embeddings=96)))


if __name__ == "__main__":
    main()
    encoders()
