"""Tokenizer tests: encode/pad/truncate, left padding, save/load, chat templates
(reference: tests/transformers/test_tokenizer_common.py pattern)."""

import json

import numpy as np
import pytest

from paddlenlp_tpu.transformers import PretrainedTokenizer


@pytest.fixture(scope="module")
def tok(tmp_path_factory):
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {"<pad>": 0, "<s>": 1, "</s>": 2, "<unk>": 3}
    for i, w in enumerate("the quick brown fox jumps over lazy dog hello world how are you".split()):
        vocab[w] = i + 4
    t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    t.pre_tokenizer = Whitespace()
    return PretrainedTokenizer(
        tokenizer_object=t,
        pad_token="<pad>",
        bos_token="<s>",
        eos_token="</s>",
        unk_token="<unk>",
    )


class TestEncodeDecode:
    def test_basic(self, tok):
        enc = tok("the quick brown fox")
        assert enc["input_ids"] == [4, 5, 6, 7]
        assert enc["attention_mask"] == [1, 1, 1, 1]
        assert tok.decode(enc["input_ids"]) == "the quick brown fox"

    def test_unk(self, tok):
        enc = tok("the zebra")
        assert enc["input_ids"] == [4, 3]

    def test_batch_right_pad(self, tok):
        enc = tok(["the quick", "hello world how are"], padding=True)
        assert enc["input_ids"][0] == [4, 5, 0, 0]
        assert enc["attention_mask"][0] == [1, 1, 0, 0]

    def test_batch_left_pad(self, tok):
        enc = tok(["the quick", "hello world how are"], padding=True, padding_side="left")
        assert enc["input_ids"][0] == [0, 0, 4, 5]
        assert enc["attention_mask"][0] == [0, 0, 1, 1]

    def test_max_length_pad_and_truncate(self, tok):
        enc = tok(["the quick"], padding="max_length", max_length=6)
        assert len(enc["input_ids"][0]) == 6
        enc = tok(["hello world how are you"], truncation=True, max_length=3)
        assert len(enc["input_ids"][0]) == 3

    def test_return_np(self, tok):
        enc = tok(["the quick", "hello world"], padding=True, return_tensors="np")
        assert isinstance(enc["input_ids"], np.ndarray)
        assert enc["input_ids"].shape == (2, 2)

    def test_special_ids(self, tok):
        assert tok.pad_token_id == 0
        assert tok.bos_token_id == 1
        assert tok.eos_token_id == 2

    def test_vocab(self, tok):
        assert tok.vocab_size >= 17
        assert tok.convert_tokens_to_ids("fox") == 7
        assert tok.convert_ids_to_tokens(7) == "fox"


class TestSaveLoad:
    def test_roundtrip(self, tok, tmp_path):
        tok.save_pretrained(str(tmp_path))
        assert (tmp_path / "tokenizer.json").exists()
        assert (tmp_path / "tokenizer_config.json").exists()
        reloaded = PretrainedTokenizer.from_pretrained(str(tmp_path))
        assert reloaded("the quick")["input_ids"] == [4, 5]
        assert reloaded.pad_token_id == 0

    def test_auto_tokenizer(self, tok, tmp_path):
        from paddlenlp_tpu.transformers import AutoTokenizer

        tok.save_pretrained(str(tmp_path))
        reloaded = AutoTokenizer.from_pretrained(str(tmp_path))
        assert reloaded("hello world")["input_ids"] == [12, 13]


class TestChatTemplate:
    def test_render(self, tok):
        tok.chat_template = (
            "{% for m in messages %}<|{{ m['role'] }}|>{{ m['content'] }}</s>{% endfor %}"
            "{% if add_generation_prompt %}<|assistant|>{% endif %}"
        )
        out = tok.apply_chat_template(
            [{"role": "user", "content": "hello"}, {"role": "assistant", "content": "world"},
             {"role": "user", "content": "how are you"}],
        )
        assert out == "<|user|>hello</s><|assistant|>world</s><|user|>how are you</s><|assistant|>"

    def test_template_persisted(self, tok, tmp_path):
        tok.chat_template = "{% for m in messages %}{{ m['content'] }} {% endfor %}"
        tok.save_pretrained(str(tmp_path))
        reloaded = PretrainedTokenizer.from_pretrained(str(tmp_path))
        assert reloaded.chat_template == tok.chat_template

    def test_no_template_raises(self, tok):
        tok2 = PretrainedTokenizer(tokenizer_object=tok._tokenizer)
        with pytest.raises(ValueError, match="chat_template"):
            tok2.apply_chat_template([{"role": "user", "content": "x"}])
