"""Golden conversion fidelity: REAL torch/HF checkpoints (committed fixtures,
see golden/make_fixtures.py) loaded through our ``from_pretrained`` must
reproduce the torch logits — the end-to-end check for torch-layout transposes,
GQA head layouts, and stacked-expert MoE conversion (reference LogitComparer,
paddlenlp/transformers/conversion_utils.py:927)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.transformers import (
    AlbertForMaskedLM,
    ElectraForSequenceClassification,
    LlamaForCausalLM,
    MixtralForCausalLM,
    RobertaForMaskedLM,
)

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")

CASES = {
    "llama_tiny": LlamaForCausalLM,
    "llama_gqa_tiny": LlamaForCausalLM,
    "mixtral_tiny": MixtralForCausalLM,
    "roberta_tiny": RobertaForMaskedLM,
    "electra_tiny": ElectraForSequenceClassification,
    "albert_tiny": AlbertForMaskedLM,
}


@pytest.mark.parametrize("name", list(CASES))
def test_logits_match_torch(name):
    fixture = os.path.join(HERE, name)
    data = np.load(os.path.join(fixture, "golden_logits.npz"))
    model = CASES[name].from_pretrained(fixture, dtype=jnp.float32, param_dtype=jnp.float32)
    ids = jnp.asarray(data["input_ids"], jnp.int32)
    got = np.asarray(model(input_ids=ids).logits, np.float32)
    ref = data["logits"]
    assert got.shape == ref.shape
    # fp32 on both sides: differences are op-ordering only
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)
    # and through the scan<->unrolled layout switch
    cfg = model.config
    cfg.use_scan_layers = not getattr(cfg, "use_scan_layers", True)
    model2 = CASES[name].from_pretrained(fixture, config=cfg, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    got2 = np.asarray(model2(input_ids=ids).logits, np.float32)
    np.testing.assert_allclose(got2, ref, atol=2e-4, rtol=2e-3)
