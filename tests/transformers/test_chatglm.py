"""ChatGLM v1 specifics: the GLM 2D (position, block_position) generation
convention — cached greedy decode must equal an uncached argmax loop that
builds the same explicit [B, 2, T] position ids (reference chatglm
prepare_inputs_for_generation semantics)."""

import jax.numpy as jnp
import numpy as np

from paddlenlp_tpu.transformers import ChatGLMConfig, ChatGLMForCausalLM


def _glm_positions(prompt_len: int, total_len: int) -> np.ndarray:
    """[1, 2, total], reference get_position_ids scheme for '...[gMASK][bos]':
    context (arange, 0) up to gMASK; bos and generated tokens freeze position
    at the gMASK index prompt_len-2; bos is block 1, generated blocks 2, 3..."""
    mask_pos = max(prompt_len - 2, 0)
    pos = np.concatenate([np.arange(prompt_len - 1),
                          np.full(total_len - prompt_len + 1, mask_pos)])
    block = np.concatenate([np.zeros(prompt_len - 1, np.int64),
                            np.arange(1, total_len - prompt_len + 2)])
    return np.stack([pos, block])[None]


class TestChatGLMGeneration:
    def test_2d_position_generate_parity(self):
        cfg = ChatGLMConfig(vocab_size=96, hidden_size=64, intermediate_size=128,
                            num_hidden_layers=2, num_attention_heads=4,
                            max_position_embeddings=64, initializer_range=0.02,
                            bos_token_id=None, eos_token_id=None)
        assert cfg.generation_2d_positions
        model = ChatGLMForCausalLM.from_config(cfg, seed=0)
        prompt = [5, 6, 7]
        gen, _ = model.generate(jnp.asarray([prompt], jnp.int32), max_new_tokens=5,
                                do_sample=False, eos_token_id=None)
        # uncached baseline with the SAME explicit GLM position ids
        ids = np.asarray([prompt])
        for _ in range(5):
            pos = jnp.asarray(_glm_positions(len(prompt), ids.shape[1]), jnp.int32)
            logits = model(input_ids=jnp.asarray(ids), position_ids=pos).logits
            ids = np.concatenate([ids, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
        np.testing.assert_array_equal(np.asarray(gen[0]), ids[0, len(prompt):])

    def test_flag_off_uses_plain_positions(self):
        """generation_2d_positions=False must reproduce the generic causal
        scheme (the harness path)."""
        cfg = ChatGLMConfig(vocab_size=96, hidden_size=64, intermediate_size=128,
                            num_hidden_layers=2, num_attention_heads=4,
                            max_position_embeddings=64, initializer_range=0.02,
                            bos_token_id=None, eos_token_id=None,
                            generation_2d_positions=False)
        model = ChatGLMForCausalLM.from_config(cfg, seed=0)
        prompt = jnp.asarray([[5, 6, 7]], jnp.int32)
        gen, _ = model.generate(prompt, max_new_tokens=4, do_sample=False, eos_token_id=None)
        ids = np.asarray(prompt)
        for _ in range(4):
            logits = model(input_ids=jnp.asarray(ids)).logits
            ids = np.concatenate([ids, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
        np.testing.assert_array_equal(np.asarray(gen[0]), ids[0, 3:])
