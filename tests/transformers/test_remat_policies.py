"""Remat granularities (reference recompute_granularity, training_args.py):
every policy must trace, train, and produce the same loss/grads — remat is a
memory/compute tradeoff, never a numerics change."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

GRANULARITIES = [
    "full", "full_attn", "core_attn",
    "save_core_attn", "save_qkv_attn", "save_attn_mlp", "save_dots", "offload_attn",
]


def _loss_and_grad(gran, use_scan):
    if gran == "offload_attn" and not hasattr(
        jax.checkpoint_policies, "save_and_offload_only_these_names"
    ):
        pytest.skip("jax build lacks save_and_offload_only_these_names")
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=128,
        recompute=True, recompute_granularity=gran, use_flash_attention=False,
        use_scan_layers=use_scan,
    )
    m = LlamaForCausalLM(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    params = m.init_weights(seed=0)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 32)), jnp.int32)

    def loss_fn(p):
        logits = m.apply(p, input_ids=ids).logits
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
    gnorm = jax.jit(lambda g: jax.tree.reduce(jnp.add, jax.tree.map(lambda x: jnp.sum(x**2), g)))(grads)
    return float(loss), float(gnorm)


@pytest.mark.parametrize("use_scan", [True, False], ids=["scan", "unrolled"])
def test_all_granularities_numerically_identical(use_scan):
    base_loss, base_gnorm = _loss_and_grad("full", use_scan)
    for gran in GRANULARITIES[1:]:
        loss, gnorm = _loss_and_grad(gran, use_scan)
        np.testing.assert_allclose(loss, base_loss, rtol=1e-6, err_msg=gran)
        np.testing.assert_allclose(gnorm, base_gnorm, rtol=1e-4, err_msg=gran)


def test_unknown_granularity_raises():
    with pytest.raises(ValueError, match="recompute_granularity"):
        _loss_and_grad("bogus", True)
