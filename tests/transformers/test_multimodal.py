"""Multimodal families beyond CLIP: chineseclip (bert text tower), blip
(fused-qkv ViT + cross-attention text decoder, captioning generate), ernie_vil
(no-projection dual tower). HF-torch parity for blip; key-layout checks for
chineseclip; self-consistency + roundtrips everywhere."""

import numpy as np
import pytest

import jax.numpy as jnp

from paddlenlp_tpu.transformers import (
    BlipConfig,
    BlipForConditionalGeneration,
    BlipForImageTextRetrieval,
    BlipModel,
    ChineseCLIPConfig,
    ChineseCLIPModel,
    ErnieViLConfig,
    ErnieViLModel,
)

TEXT_KW = dict(vocab_size=99, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
               intermediate_size=37, max_position_embeddings=64)
VISION_KW = dict(hidden_size=32, intermediate_size=37, num_hidden_layers=2,
                 num_attention_heads=4, image_size=24, patch_size=6)


def pix(b=2, s=24):
    return jnp.asarray(np.random.default_rng(0).standard_normal((b, s, s, 3)), jnp.float32)


class TestChineseCLIP:
    def cfg(self):
        return ChineseCLIPConfig(text_config=dict(TEXT_KW), vision_config=dict(VISION_KW),
                                 projection_dim=24)

    def test_forward_and_roundtrip(self, tmp_path):
        m = ChineseCLIPModel.from_config(self.cfg(), seed=0)
        ids = jnp.asarray([[2, 5, 6, 7], [2, 8, 9, 1]], jnp.int32)
        out = m(input_ids=ids, pixel_values=pix(), return_loss=True)
        assert out.logits_per_text.shape == (2, 2) and np.isfinite(float(out.loss))
        m.save_pretrained(str(tmp_path))
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "text_model.encoder.layer.0.attention.self.query.weight" in keys
        assert "vision_model.embeddings.patch_embedding.weight" in keys
        m2 = ChineseCLIPModel.from_pretrained(str(tmp_path))
        np.testing.assert_allclose(
            np.asarray(out.logits_per_text),
            np.asarray(m2(input_ids=ids, pixel_values=pix()).logits_per_text), atol=1e-5)

    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import ChineseCLIPConfig as HFC, ChineseCLIPModel as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(text_config=dict(TEXT_KW), vision_config=dict(VISION_KW, hidden_act="quick_gelu"),
                     projection_dim=24)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        ids = np.asarray([[2, 5, 6, 7], [2, 8, 9, 1]], np.int64)
        pv = np.random.default_rng(0).standard_normal((2, 3, 24, 24)).astype(np.float32)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(ids), pixel_values=torch.tensor(pv),
                        attention_mask=torch.ones_like(torch.tensor(ids)))
        m = ChineseCLIPModel.from_pretrained(str(tmp_path))
        out = m(input_ids=jnp.asarray(ids, jnp.int32),
                attention_mask=jnp.ones((2, 4), jnp.int32),
                pixel_values=jnp.asarray(pv.transpose(0, 2, 3, 1)))
        np.testing.assert_allclose(np.asarray(out.logits_per_text),
                                   golden.logits_per_text.numpy(), atol=3e-4)


class TestBlip:
    def cfg(self):
        return BlipConfig(
            text_config=dict(TEXT_KW, num_attention_heads=4, bos_token_id=97, eos_token_id=98,
                             pad_token_id=0),
            vision_config=dict(VISION_KW), projection_dim=24)

    def test_contrastive_and_caption_loss(self):
        cfg = self.cfg()
        ids = jnp.asarray([[2, 5, 6, 7], [2, 8, 9, 0]], jnp.int32)
        m = BlipModel.from_config(cfg, seed=0)
        out = m(input_ids=ids, pixel_values=pix(), return_loss=True)
        assert out.logits_per_text.shape == (2, 2)
        g = BlipForConditionalGeneration.from_config(cfg, seed=0)
        _, loss = g(pixel_values=pix(), input_ids=ids, labels=ids)
        assert np.isfinite(float(loss))

    def test_generate_shapes_and_determinism(self):
        g = BlipForConditionalGeneration.from_config(self.cfg(), seed=0)
        caps1 = np.asarray(g.generate(pix(), max_new_tokens=5))
        caps2 = np.asarray(g.generate(pix(), max_new_tokens=5))
        assert caps1.shape == (2, 5)
        np.testing.assert_array_equal(caps1, caps2)

    def test_itm_head(self):
        m = BlipForImageTextRetrieval.from_config(self.cfg(), seed=0)
        ids = jnp.asarray([[2, 5, 6, 7]], jnp.int32)
        logits = m(input_ids=ids, pixel_values=pix(1))
        assert logits.shape == (1, 2)

    def test_blipmodel_key_layout_bare_text(self, tmp_path):
        """BlipModel's text tower saves WITHOUT the bert prefix (HF layout);
        only the LM-head decoder nests bert + cls."""
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        m = BlipModel.from_config(self.cfg(), seed=0)
        m.save_pretrained(str(tmp_path))
        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "text_model.embeddings.word_embeddings.weight" in keys
        assert "text_model.encoder.layer.0.attention.self.query.weight" in keys
        assert not any(k.startswith("text_model.bert.") for k in keys)

    def test_torch_parity_contrastive(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import BlipConfig as HFC, BlipModel as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(text_config=dict(TEXT_KW, num_attention_heads=4, bos_token_id=97,
                                      eos_token_id=98, pad_token_id=0,
                                      hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0),
                     vision_config=dict(VISION_KW), projection_dim=24)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        ids = np.asarray([[2, 5, 6, 7], [2, 8, 9, 1]], np.int64)
        pv = np.random.default_rng(0).standard_normal((2, 3, 24, 24)).astype(np.float32)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(ids), pixel_values=torch.tensor(pv),
                        attention_mask=torch.ones_like(torch.tensor(ids)))
        m = BlipModel.from_pretrained(str(tmp_path))
        out = m(input_ids=jnp.asarray(ids, jnp.int32),
                attention_mask=jnp.ones((2, 4), jnp.int32),
                pixel_values=jnp.asarray(pv.transpose(0, 2, 3, 1)))
        np.testing.assert_allclose(np.asarray(out.logits_per_text),
                                   golden.logits_per_text.numpy(), atol=3e-4)

    def test_torch_parity_caption_logits(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import BlipConfig as HFC, BlipForConditionalGeneration as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(text_config=dict(TEXT_KW, num_attention_heads=4, bos_token_id=97,
                                      eos_token_id=98, pad_token_id=0,
                                      hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0),
                     vision_config=dict(VISION_KW), projection_dim=24)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        ids = np.asarray([[97, 5, 6, 7]], np.int64)
        pv = np.random.default_rng(0).standard_normal((1, 3, 24, 24)).astype(np.float32)
        with torch.no_grad():
            golden = hm(pixel_values=torch.tensor(pv), input_ids=torch.tensor(ids)).logits.numpy()
        m = BlipForConditionalGeneration.from_pretrained(str(tmp_path))
        out = m(pixel_values=jnp.asarray(pv.transpose(0, 2, 3, 1)),
                input_ids=jnp.asarray(ids, jnp.int32))
        np.testing.assert_allclose(np.asarray(out.logits), golden, atol=3e-4)


class TestErnieViL:
    def test_forward_and_roundtrip(self, tmp_path):
        cfg = ErnieViLConfig(text_config=dict(TEXT_KW), vision_config=dict(VISION_KW))
        m = ErnieViLModel.from_config(cfg, seed=0)
        ids = jnp.asarray([[2, 5, 6, 7]], jnp.int32)
        out = m(input_ids=ids, pixel_values=pix(1), return_loss=True)
        assert out.text_embeds.shape == (1, 32)  # pooled hidden, no projection
        m.save_pretrained(str(tmp_path))
        m2 = ErnieViLModel.from_pretrained(str(tmp_path))
        np.testing.assert_allclose(np.asarray(out.text_embeds),
                                   np.asarray(m2(input_ids=ids, pixel_values=pix(1)).text_embeds),
                                   atol=1e-5)


class TestMultimodalAuto:
    def test_auto_resolves_clip(self, tmp_path):
        from paddlenlp_tpu.transformers import CLIPConfig, CLIPModel
        from paddlenlp_tpu.transformers.auto import AutoModel

        m = CLIPModel.from_config(
            CLIPConfig(text_config=dict(TEXT_KW, eos_token_id=98),
                       vision_config=dict(VISION_KW, patch_size=6), projection_dim=24), seed=0)
        m.save_pretrained(str(tmp_path))
        auto = AutoModel.from_pretrained(str(tmp_path))
        assert type(auto).__name__ == "CLIPModel"


class TestMiniGPT4:
    def cfg(self):
        from paddlenlp_tpu.transformers import MiniGPT4Config

        return MiniGPT4Config(
            vision_config=dict(hidden_size=32, intermediate_size=48, num_hidden_layers=2,
                               num_attention_heads=4, image_size=24, patch_size=6),
            qformer_config=dict(vocab_size=60, hidden_size=32, num_hidden_layers=2,
                                num_attention_heads=4, intermediate_size=48, num_query_tokens=4),
            text_config=dict(vocab_size=96, hidden_size=32, intermediate_size=64,
                             num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
                             max_position_embeddings=64, bos_token_id=1, eos_token_id=2,
                             pad_token_id=0, use_scan_layers=False))

    def test_forward_loss_generate_roundtrip(self, tmp_path):
        from paddlenlp_tpu.transformers import MiniGPT4ForConditionalGeneration

        m = MiniGPT4ForConditionalGeneration.from_config(self.cfg(), seed=0)
        ids = jnp.asarray([[1, 5, 6, 7], [1, 8, 9, 0]], jnp.int32)
        out, loss = m(pixel_values=pix(), input_ids=ids, labels=ids)
        assert out.logits.shape == (2, 4, 96) and np.isfinite(float(loss))
        caps = np.asarray(m.generate(pix(), max_new_tokens=4))
        assert caps.shape == (2, 4)
        m.save_pretrained(str(tmp_path))
        m2 = MiniGPT4ForConditionalGeneration.from_pretrained(str(tmp_path))
        _, loss2 = m2(pixel_values=pix(), input_ids=ids, labels=ids)
        np.testing.assert_allclose(float(loss), float(loss2), atol=1e-5)

    def test_qformer_prefix_shape(self):
        from paddlenlp_tpu.transformers import MiniGPT4ForConditionalGeneration

        m = MiniGPT4ForConditionalGeneration.from_config(self.cfg(), seed=0)
        prefix = m.module.apply({"params": m.params}, pix(), method=m.module.encode_image)
        assert prefix.shape == (2, 4, 32)  # [B, num_query_tokens, llm_hidden]
