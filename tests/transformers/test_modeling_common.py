"""Cross-model harness — the reference's ModelTesterMixin pattern
(tests/transformers/test_modeling_common.py): tiny configs for EVERY family,
forward shape checks, save/load round trip, greedy generate smoke, tp-sharded
placement. One parametrized suite instead of per-model copies."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.parallel import MeshConfig, create_mesh, use_mesh
from paddlenlp_tpu.transformers import (
    AlbertConfig,
    AlbertForMaskedLM,
    AlbertForSequenceClassification,
    ElectraConfig,
    ElectraForSequenceClassification,
    RobertaConfig,
    RobertaForMaskedLM,
    RobertaForSequenceClassification,
    BaichuanConfig,
    DeepseekV2Config,
    DeepseekV2ForCausalLM,
    MambaConfig,
    MambaForCausalLM,
    BaichuanForCausalLM,
    BertConfig,
    BloomConfig,
    BloomForCausalLM,
    ChatGLMv2Config,
    ChatGLMv2ForCausalLM,
    OPTConfig,
    OPTForCausalLM,
    QWenConfig,
    QWenForCausalLM,
    BertForMaskedLM,
    BertForSequenceClassification,
    ErnieConfig,
    ErnieForSequenceClassification,
    GemmaConfig,
    GemmaForCausalLM,
    GPTConfig,
    GPTForCausalLM,
    LlamaConfig,
    LlamaForCausalLM,
    MistralConfig,
    MistralForCausalLM,
    MixtralConfig,
    MixtralForCausalLM,
    Qwen2Config,
    Qwen2ForCausalLM,
    Qwen2MoeConfig,
    Qwen2MoeForCausalLM,
    RWConfig,
    RWForCausalLM,
    ChatGLMConfig,
    ChatGLMForCausalLM,
    YuanConfig,
    YuanForCausalLM,
    JambaConfig,
    JambaForCausalLM,
)

TINY = dict(hidden_size=64, num_hidden_layers=2, num_attention_heads=4, max_position_embeddings=64,
            initializer_range=0.02)

CAUSAL_CASES = {
    "llama": (LlamaForCausalLM, lambda: LlamaConfig(vocab_size=96, intermediate_size=112,
                                                    num_key_value_heads=2, **TINY)),
    "qwen2": (Qwen2ForCausalLM, lambda: Qwen2Config(vocab_size=96, intermediate_size=112,
                                                    num_key_value_heads=2, **TINY)),
    "mistral": (MistralForCausalLM, lambda: MistralConfig(vocab_size=96, intermediate_size=112,
                                                          num_key_value_heads=2, sliding_window=8, **TINY)),
    "gemma": (GemmaForCausalLM, lambda: GemmaConfig(vocab_size=96, intermediate_size=112,
                                                    num_key_value_heads=2, head_dim=16, **TINY)),
    "gpt": (GPTForCausalLM, lambda: GPTConfig(vocab_size=96, **TINY)),
    "baichuan": (BaichuanForCausalLM, lambda: BaichuanConfig(vocab_size=96, intermediate_size=112, **TINY)),
    "baichuan_alibi": (BaichuanForCausalLM, lambda: BaichuanConfig(vocab_size=96, intermediate_size=112,
                                                                   use_alibi=True, **TINY)),
    "qwen": (QWenForCausalLM, lambda: QWenConfig(vocab_size=96, intermediate_size=224, **TINY)),
    "bloom": (BloomForCausalLM, lambda: BloomConfig(vocab_size=96, **TINY)),
    "opt": (OPTForCausalLM, lambda: OPTConfig(vocab_size=96, intermediate_size=128, **TINY)),
    "chatglm_v2": (ChatGLMv2ForCausalLM, lambda: ChatGLMv2Config(vocab_size=96, intermediate_size=112,
                                                                 multi_query_group_num=2, kv_channels=16,
                                                                 **TINY)),
    "mixtral": (MixtralForCausalLM, lambda: MixtralConfig(vocab_size=96, intermediate_size=80,
                                                          num_key_value_heads=2, num_local_experts=4,
                                                          num_experts_per_tok=2, **TINY)),
    "qwen2_moe": (Qwen2MoeForCausalLM, lambda: Qwen2MoeConfig(vocab_size=96, intermediate_size=112,
                                                              num_key_value_heads=2, num_experts=4,
                                                              num_experts_per_tok=2, moe_intermediate_size=48,
                                                              shared_expert_intermediate_size=64, **TINY)),
    # MLA: low-rank q/kv, rope on a 8-dim slice, dense layer 0 + grouped MoE after
    "deepseek_v2": (DeepseekV2ForCausalLM, lambda: DeepseekV2Config(
        vocab_size=96, intermediate_size=112, moe_intermediate_size=48,
        q_lora_rank=24, kv_lora_rank=16, qk_rope_head_dim=8, qk_nope_head_dim=8, v_head_dim=16,
        n_routed_experts=4, n_shared_experts=1, num_experts_per_tok=2,
        topk_method="group_limited_greedy", n_group=2, topk_group=1,
        first_k_dense_replace=1, routed_scaling_factor=1.0, norm_topk_prob=True,
        rope_scaling={"type": "yarn", "factor": 2.0, "original_max_position_embeddings": 32,
                      "mscale": 0.707, "mscale_all_dim": 0.707,
                      "beta_fast": 32, "beta_slow": 1},
        **TINY)),
    # hybrid: NoPE attention at layer 1, mamba elsewhere; MoE ffn on odd layers
    "jamba": (JambaForCausalLM, lambda: JambaConfig(
        vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        initializer_range=0.02, num_experts=4, num_experts_per_tok=2,
        attn_layer_period=4, attn_layer_offset=1, expert_layer_period=2, expert_layer_offset=1,
        mamba_d_state=8, mamba_d_conv=4, mamba_expand=2, mamba_dt_rank=8)),
    # localized-filtering gate (two causal convs) ahead of q/k; v from raw hiddens
    "yuan": (YuanForCausalLM, lambda: YuanConfig(vocab_size=96, intermediate_size=112,
                                                 num_key_value_heads=2, **TINY)),
    # GLM v1: 2D rotary halves, alpha-scaled post-LN residuals, per-head-thirds fused qkv
    "chatglm": (ChatGLMForCausalLM, lambda: ChatGLMConfig(vocab_size=96, intermediate_size=128,
                                                          bos_token_id=None, eos_token_id=None,
                                                          generation_2d_positions=False, **TINY)),
    # falcon-7b shape: MQ fused qkv + parallel_attn + rotary; rw-1b shape: MHA + alibi
    "rw_falcon": (RWForCausalLM, lambda: RWConfig(vocab_size=96, multi_query=True,
                                                  parallel_attn=True, bias=False, **TINY)),
    "rw_alibi": (RWForCausalLM, lambda: RWConfig(vocab_size=96, multi_query=False,
                                                 parallel_attn=False, bias=True, alibi=True, **TINY)),
    # falcon-40b shape: grouped-kv fused qkv ([n_kv, group+2, hd] layout)
    "rw_gqa": (RWForCausalLM, lambda: RWConfig(vocab_size=96, multi_query=False,
                                               n_head_kv=2, parallel_attn=True, bias=False, **TINY)),
    # attention-free SSM: associative-scan recurrence + conv/ssm state cache
    "mamba": (MambaForCausalLM, lambda: MambaConfig(
        vocab_size=96, hidden_size=64, num_hidden_layers=2, state_size=8,
        conv_kernel=4, expand=2, time_step_rank=8, initializer_range=0.02,
        max_position_embeddings=64)),
}

ENCODER_CASES = {
    "bert_mlm": (BertForMaskedLM, lambda: BertConfig(vocab_size=96, intermediate_size=128, **TINY)),
    "roberta_mlm": (RobertaForMaskedLM, lambda: RobertaConfig(vocab_size=96, intermediate_size=128,
                                                              pad_token_id=1, **TINY)),
    "roberta_cls": (RobertaForSequenceClassification, lambda: RobertaConfig(
        vocab_size=96, intermediate_size=128, pad_token_id=1, num_labels=3, **TINY)),
    "electra_cls": (ElectraForSequenceClassification, lambda: ElectraConfig(
        vocab_size=96, embedding_size=32, intermediate_size=128, num_labels=3, **TINY)),
    "albert_mlm": (AlbertForMaskedLM, lambda: AlbertConfig(vocab_size=96, embedding_size=32,
                                                           intermediate_size=128, **TINY)),
    "albert_cls": (AlbertForSequenceClassification, lambda: AlbertConfig(
        vocab_size=96, embedding_size=32, intermediate_size=128, num_labels=3, **TINY)),
    "bert_cls": (BertForSequenceClassification, lambda: BertConfig(vocab_size=96, intermediate_size=128,
                                                                   num_labels=3, **TINY)),
    "ernie_cls": (ErnieForSequenceClassification, lambda: ErnieConfig(vocab_size=96, intermediate_size=128,
                                                                      num_labels=3, **TINY)),
}


@pytest.mark.parametrize("name", list(CAUSAL_CASES))
class TestCausalCommon:
    def test_forward_and_roundtrip(self, name, tmp_path):
        cls, cfg_fn = CAUSAL_CASES[name]
        model = cls.from_config(cfg_fn(), seed=0)
        ids = jnp.asarray(np.arange(10)[None, :] % 90 + 3, dtype=jnp.int32)
        out = model(input_ids=ids)
        assert out.logits.shape == (1, 10, 96)
        assert np.isfinite(np.asarray(out.logits)).all()
        model.save_pretrained(str(tmp_path))
        reloaded = cls.from_pretrained(str(tmp_path))
        np.testing.assert_allclose(
            np.asarray(out.logits), np.asarray(reloaded(input_ids=ids).logits), atol=1e-5
        )

    def test_greedy_generate_cache_parity(self, name, tmp_path):
        """Cached greedy decode == argmax over repeated full forwards."""
        cls, cfg_fn = CAUSAL_CASES[name]
        model = cls.from_config(cfg_fn(), seed=0)
        prompt = jnp.asarray([[5, 6, 7]], dtype=jnp.int32)
        gen, _ = model.generate(prompt, max_new_tokens=4, do_sample=False, eos_token_id=None)
        ids = np.asarray(prompt)
        for _ in range(4):
            logits = model(input_ids=jnp.asarray(ids)).logits
            ids = np.concatenate([ids, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
        np.testing.assert_array_equal(np.asarray(gen[0]), ids[0, 3:])


@pytest.mark.parametrize("name", list(ENCODER_CASES))
class TestEncoderCommon:
    def test_forward_and_roundtrip(self, name, tmp_path):
        cls, cfg_fn = ENCODER_CASES[name]
        model = cls.from_config(cfg_fn(), seed=0)
        ids = jnp.asarray(np.arange(8)[None, :] % 90 + 3, dtype=jnp.int32)
        mask = jnp.ones_like(ids)
        out = model(input_ids=ids, attention_mask=mask)
        logits = np.asarray(out.logits)
        assert np.isfinite(logits).all()
        model.save_pretrained(str(tmp_path))
        reloaded = cls.from_pretrained(str(tmp_path))
        np.testing.assert_allclose(
            logits, np.asarray(reloaded(input_ids=ids, attention_mask=mask).logits), atol=1e-5
        )


class TestMoESpecifics:
    def test_aux_loss_flows(self):
        cls, cfg_fn = CAUSAL_CASES["mixtral"]
        model = cls.from_config(cfg_fn(), seed=0)
        ids = jnp.asarray([[4, 5, 6, 7]], dtype=jnp.int32)
        out = model(input_ids=ids)
        aux = np.asarray(out.aux_loss)
        assert np.isfinite(aux) and aux > 0  # coef 0.02 * balanced ~ E*sum(f*P) ~ 1

    def test_expert_checkpoint_keys(self, tmp_path):
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        cls, cfg_fn = CAUSAL_CASES["mixtral"]
        model = cls.from_config(cfg_fn(), seed=0)
        model.save_pretrained(str(tmp_path))
        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "model.layers.0.block_sparse_moe.experts.0.w1.weight" in keys
        assert "model.layers.1.block_sparse_moe.experts.3.w2.weight" in keys
        assert "model.layers.0.block_sparse_moe.gate.weight" in keys

    def test_qwen2moe_shared_expert_keys(self, tmp_path):
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        cls, cfg_fn = CAUSAL_CASES["qwen2_moe"]
        model = cls.from_config(cfg_fn(), seed=0)
        model.save_pretrained(str(tmp_path))
        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "model.layers.0.mlp.experts.0.gate_proj.weight" in keys
        assert "model.layers.0.mlp.shared_expert.gate_proj.weight" in keys
        assert "model.layers.0.mlp.shared_expert_gate.weight" in keys

    def test_moe_expert_sharding(self, eight_devices):
        cls, cfg_fn = CAUSAL_CASES["mixtral"]
        mesh = create_mesh(MeshConfig(dp=4, tp=2))
        model = cls.from_config(cfg_fn(), seed=0, mesh=mesh)
        w1 = model.params["model"]["layers"]["block_sparse_moe"]["w1"]
        spec = str(w1.sharding.spec)
        assert "dp" in spec  # experts sharded over the data axes (EP)


class TestGPTSpecifics:
    def test_hf_gpt2_key_format(self, tmp_path):
        from paddlenlp_tpu.utils.safetensors_io import SafeFile, safe_keys

        model = GPTForCausalLM.from_config(GPTConfig(vocab_size=96, use_scan_layers=False, **TINY), seed=0)
        model.save_pretrained(str(tmp_path))
        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "transformer.wte.weight" in keys
        assert "transformer.wpe.weight" in keys
        assert "transformer.h.0.attn.c_attn.weight" in keys
        assert "transformer.h.0.mlp.c_fc.weight" in keys
        assert "transformer.ln_f.weight" in keys
        # Conv1D layout: c_attn stored [in, 3*out] (not transposed)
        with SafeFile(str(tmp_path / "model.safetensors")) as sf:
            assert sf.get_slice("transformer.h.0.attn.c_attn.weight").shape == (64, 192)


class TestBertSpecifics:
    def test_hf_bert_key_format(self, tmp_path):
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        model = BertForSequenceClassification.from_config(
            BertConfig(vocab_size=96, intermediate_size=128, num_labels=3, **TINY), seed=0
        )
        model.save_pretrained(str(tmp_path))
        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "bert.embeddings.word_embeddings.weight" in keys
        assert "bert.encoder.layer.0.attention.self.query.weight" in keys
        assert "bert.encoder.layer.0.attention.output.LayerNorm.weight" in keys
        assert "bert.encoder.layer.1.intermediate.dense.weight" in keys
        assert "bert.pooler.dense.weight" in keys
        assert "classifier.weight" in keys

    def test_padding_invariance(self):
        model = BertForSequenceClassification.from_config(
            BertConfig(vocab_size=96, intermediate_size=128, num_labels=3, **TINY), seed=0
        )
        ids = jnp.asarray([[5, 6, 7, 8]], dtype=jnp.int32)
        full = model(input_ids=ids, attention_mask=jnp.ones_like(ids)).logits
        padded = jnp.asarray([[5, 6, 7, 8, 0, 0]], dtype=jnp.int32)
        mask = jnp.asarray([[1, 1, 1, 1, 0, 0]], dtype=jnp.int32)
        out = model(input_ids=padded, attention_mask=mask).logits
        np.testing.assert_allclose(np.asarray(full), np.asarray(out), atol=2e-5)


class TestAutoClasses:
    def test_auto_roundtrip(self, tmp_path):
        from paddlenlp_tpu.transformers import AutoConfig, AutoModelForCausalLM

        model = LlamaForCausalLM.from_config(
            LlamaConfig(vocab_size=96, intermediate_size=112, num_key_value_heads=2, **TINY), seed=0
        )
        model.save_pretrained(str(tmp_path))
        cfg = AutoConfig.from_pretrained(str(tmp_path))
        assert cfg.model_type == "llama"
        auto = AutoModelForCausalLM.from_pretrained(str(tmp_path))
        assert type(auto).__name__ == "LlamaForCausalLM"

    def test_auto_unknown_type(self, tmp_path):
        import json

        (tmp_path / "config.json").write_text(json.dumps({"model_type": "not_a_model"}))
        from paddlenlp_tpu.transformers import AutoConfig

        with pytest.raises(ValueError, match="unrecognized model_type"):
            AutoConfig.from_pretrained(str(tmp_path))
