"""CLIP dual-tower tests: forward shapes, contrastive logits symmetry,
HF-torch numerical parity on identical weights (incl. the patch-conv layout
transpose), image processor pipeline, save/load roundtrip."""

import numpy as np
import pytest

import jax.numpy as jnp

from paddlenlp_tpu.transformers import (
    CLIPConfig,
    CLIPImageProcessor,
    CLIPModel,
    CLIPTextConfig,
    CLIPTextModelWithProjection,
    CLIPVisionConfig,
    CLIPVisionModel,
)

TEXT_KW = dict(vocab_size=99, hidden_size=32, intermediate_size=37, num_hidden_layers=2,
               num_attention_heads=4, max_position_embeddings=32,
               eos_token_id=98, bos_token_id=97, pad_token_id=1)
VISION_KW = dict(hidden_size=32, intermediate_size=37, num_hidden_layers=2,
                 num_attention_heads=4, image_size=30, patch_size=6)


def tiny_cfg():
    return CLIPConfig(text_config=dict(TEXT_KW), vision_config=dict(VISION_KW), projection_dim=24)


class TestCLIP:
    def test_forward_shapes_and_loss(self):
        model = CLIPModel.from_config(tiny_cfg(), seed=0)
        eos = model.config.text_config.eos_token_id
        ids = jnp.asarray([[5, 6, 7, eos], [8, 9, eos, 0]], jnp.int32)
        pix = jnp.asarray(np.random.default_rng(0).standard_normal((2, 30, 30, 3)), jnp.float32)
        out = model(input_ids=ids, pixel_values=pix, return_loss=True)
        assert out.logits_per_image.shape == (2, 2)
        assert out.text_embeds.shape == (2, 24) and out.image_embeds.shape == (2, 24)
        np.testing.assert_allclose(np.asarray(out.logits_per_image),
                                   np.asarray(out.logits_per_text).T, atol=1e-5)
        assert np.isfinite(float(out.loss))
        # embeds are L2-normalized
        np.testing.assert_allclose(np.linalg.norm(np.asarray(out.text_embeds), axis=-1),
                                   1.0, atol=1e-5)

    def test_save_load_roundtrip(self, tmp_path):
        model = CLIPModel.from_config(tiny_cfg(), seed=0)
        eos = model.config.text_config.eos_token_id
        ids = jnp.asarray([[5, 6, eos]], jnp.int32)
        pix = jnp.asarray(np.random.default_rng(1).standard_normal((1, 30, 30, 3)), jnp.float32)
        ref = model(input_ids=ids, pixel_values=pix)
        model.save_pretrained(str(tmp_path))
        reloaded = CLIPModel.from_pretrained(str(tmp_path))
        out = reloaded(input_ids=ids, pixel_values=pix)
        np.testing.assert_allclose(np.asarray(ref.logits_per_text),
                                   np.asarray(out.logits_per_text), atol=1e-5)

    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import CLIPConfig as HFC, CLIPModel as HFM

        torch.manual_seed(0)
        hf_cfg = HFC(text_config=dict(TEXT_KW, hidden_act="quick_gelu"),
                     vision_config=dict(VISION_KW, hidden_act="quick_gelu"),
                     projection_dim=24)
        hm = HFM(hf_cfg).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        ids_t = torch.tensor([[5, 6, 7, 98], [8, 9, 98, 1]])
        pix_np = np.random.default_rng(0).standard_normal((2, 3, 30, 30)).astype(np.float32)
        with torch.no_grad():
            golden = hm(input_ids=ids_t, pixel_values=torch.tensor(pix_np))
        model = CLIPModel.from_pretrained(str(tmp_path))
        out = model(input_ids=jnp.asarray(ids_t.numpy(), jnp.int32),
                    pixel_values=jnp.asarray(pix_np.transpose(0, 2, 3, 1)))
        np.testing.assert_allclose(np.asarray(out.logits_per_text),
                                   golden.logits_per_text.numpy(), atol=3e-4)
        np.testing.assert_allclose(np.asarray(out.image_embeds),
                                   golden.image_embeds.numpy(), atol=3e-4)

    def test_text_with_projection(self):
        cfg = CLIPTextConfig(**TEXT_KW, projection_dim=24)
        model = CLIPTextModelWithProjection.from_config(cfg, seed=0)
        out = model(input_ids=jnp.asarray([[5, 6, cfg.eos_token_id]], jnp.int32))
        assert out.pooler_output.shape == (1, 24)

    def test_vision_model(self):
        cfg = CLIPVisionConfig(**VISION_KW)
        model = CLIPVisionModel.from_config(cfg, seed=0)
        pix = jnp.asarray(np.random.default_rng(0).standard_normal((1, 30, 30, 3)), jnp.float32)
        out = model(pixel_values=pix)
        assert out.last_hidden_state.shape == (1, 26, 32)  # 25 patches + cls
        assert out.pooler_output.shape == (1, 32)


class TestImageProcessor:
    def test_clip_pipeline_shapes(self):
        proc = CLIPImageProcessor(size=18, crop_size=16)
        img = (np.random.default_rng(0).random((40, 60, 3)) * 255).astype(np.uint8)
        out = proc([img, img])
        assert out["pixel_values"].shape == (2, 16, 16, 3)
        assert out["pixel_values"].dtype == np.float32

    def test_shortest_edge_aspect(self):
        from paddlenlp_tpu.transformers.image_processing_utils import resize

        img = np.zeros((40, 80, 3), np.float32)
        proc = CLIPImageProcessor(size=20, do_center_crop=False, do_normalize=False)
        out = proc(img)["pixel_values"]
        assert out.shape == (1, 20, 40, 3)  # aspect preserved

    def test_normalization_values(self):
        proc = CLIPImageProcessor(do_resize=False, do_center_crop=False)
        img = np.full((4, 4, 3), 255, np.uint8)
        out = proc(img)["pixel_values"][0]
        expected = (1.0 - np.asarray(proc.image_mean)) / np.asarray(proc.image_std)
        np.testing.assert_allclose(out[0, 0], expected, atol=1e-6)

    def test_chw_input_accepted(self):
        proc = CLIPImageProcessor(size=8, crop_size=8)
        img = np.zeros((3, 20, 20), np.float32)
        assert proc(img)["pixel_values"].shape == (1, 8, 8, 3)

    def test_save_load(self, tmp_path):
        proc = CLIPImageProcessor(size=33)
        proc.save_pretrained(str(tmp_path))
        proc2 = CLIPImageProcessor.from_pretrained(str(tmp_path))
        assert proc2.size == 33
