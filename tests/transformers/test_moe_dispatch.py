"""Sparse MoE dispatch: capacity-buffer scatter/einsum/gather must reproduce the
dense path when nothing drops, and gradients must flow to routed experts."""

import jax
import jax.numpy as jnp
import numpy as np

from paddlenlp_tpu.transformers import MixtralConfig, MixtralForCausalLM


class TestSparseDispatch:
    def _model(self, dispatch, cf=None, seed=0):
        kw = dict(moe_dispatch=dispatch)
        if cf is not None:
            kw["moe_capacity_factor"] = cf
        cfg = MixtralConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                            moe_intermediate_size=48, num_hidden_layers=2, num_attention_heads=4,
                            num_key_value_heads=2, num_local_experts=4, num_experts_per_tok=2,
                            max_position_embeddings=64, **kw)
        return MixtralForCausalLM.from_config(cfg, seed=seed)

    def test_sparse_matches_dense_at_full_capacity(self):
        """capacity_factor >= E/K => no drops => bitwise-identical to dense."""
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)), jnp.int32)
        dense = self._model("dense")
        sparse = self._model("sparse", cf=2.0)  # C = N*K/E * 2 = N => no drop possible
        sparse.params = jax.tree.map(jnp.copy, dense.params)
        out_d = dense(input_ids=ids).logits
        out_s = sparse.module.apply({"params": sparse.params}, input_ids=ids,
                                    deterministic=True).logits
        np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_s), atol=2e-5, rtol=2e-5)

    def test_sparse_grads_flow(self):
        model = self._model("sparse", cf=1.5)
        ids = jnp.asarray(np.random.default_rng(1).integers(0, 64, (2, 8)), jnp.int32)

        def loss(p):
            return model.module.apply({"params": p}, input_ids=ids,
                                      deterministic=True).logits.astype(jnp.float32).sum()

        g = jax.grad(loss)(model.params)
        leaves = jax.tree.leaves(g)
        assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
        # expert weights receive gradient (routing selected them)
        gl = g["model"]["layers"]["block_sparse_moe"]["w1"]
        assert float(jnp.abs(gl).sum()) > 0
