"""Encoder zoo wave 2: distilbert / nezha / mpnet — forward shapes, HF-torch
numerical parity on identical weights (the real checkpoint-compat check), MLM
head tying, save/load roundtrip."""

import numpy as np
import pytest

import jax.numpy as jnp

from paddlenlp_tpu.transformers import (
    DistilBertConfig,
    DistilBertForMaskedLM,
    DistilBertModel,
    MPNetConfig,
    MPNetForMaskedLM,
    MPNetModel,
    NezhaConfig,
    NezhaForMaskedLM,
    NezhaModel,
)

IDS = np.asarray([[2, 5, 6, 7, 8, 3], [2, 9, 10, 3, 1, 1]], np.int64)
MASK = np.asarray([[1, 1, 1, 1, 1, 1], [1, 1, 1, 1, 0, 0]], np.int64)


class TestDistilBert:
    def cfg(self):
        return DistilBertConfig(vocab_size=60, dim=32, n_layers=2, n_heads=4, hidden_dim=37,
                                max_position_embeddings=64, dropout=0.0, attention_dropout=0.0)

    def test_forward_roundtrip(self, tmp_path):
        m = DistilBertModel.from_config(self.cfg(), seed=0)
        out = m(input_ids=jnp.asarray(IDS, jnp.int32), attention_mask=jnp.asarray(MASK, jnp.int32))
        assert out.last_hidden_state.shape == (2, 6, 32)
        m.save_pretrained(str(tmp_path))
        m2 = DistilBertModel.from_pretrained(str(tmp_path))
        np.testing.assert_allclose(
            np.asarray(out.last_hidden_state),
            np.asarray(m2(input_ids=jnp.asarray(IDS, jnp.int32),
                          attention_mask=jnp.asarray(MASK, jnp.int32)).last_hidden_state),
            atol=1e-5)

    def test_torch_parity_mlm(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import DistilBertConfig as HFC, DistilBertForMaskedLM as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=60, dim=32, n_layers=2, n_heads=4, hidden_dim=37,
                     max_position_embeddings=64, dropout=0.0, attention_dropout=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS), attention_mask=torch.tensor(MASK)).logits.numpy()
        m = DistilBertForMaskedLM.from_pretrained(str(tmp_path))
        mine = m(input_ids=jnp.asarray(IDS, jnp.int32),
                 attention_mask=jnp.asarray(MASK, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)


class TestNezha:
    def cfg(self):
        return NezhaConfig(vocab_size=60, hidden_size=32, num_hidden_layers=2,
                           num_attention_heads=4, intermediate_size=37,
                           max_position_embeddings=64, max_relative_position=8,
                           hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)

    def test_forward_no_position_embeddings(self, tmp_path):
        m = NezhaModel.from_config(self.cfg(), seed=0)
        out = m(input_ids=jnp.asarray(IDS, jnp.int32), attention_mask=jnp.asarray(MASK, jnp.int32))
        assert out.last_hidden_state.shape == (2, 6, 32)
        m.save_pretrained(str(tmp_path))
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "embeddings.word_embeddings.weight" in keys
        assert not any("position_embeddings" in k for k in keys)

    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import NezhaConfig as HFC, NezhaForMaskedLM as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=60, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=37, max_position_embeddings=64, max_relative_position=8,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                     classifier_dropout=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS), attention_mask=torch.tensor(MASK)).logits.numpy()
        m = NezhaForMaskedLM.from_pretrained(str(tmp_path))
        mine = m(input_ids=jnp.asarray(IDS, jnp.int32),
                 attention_mask=jnp.asarray(MASK, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)


class TestMPNet:
    def cfg(self):
        return MPNetConfig(vocab_size=60, hidden_size=32, num_hidden_layers=2,
                           num_attention_heads=4, intermediate_size=37,
                           max_position_embeddings=64, hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)

    def test_forward_shared_bias(self, tmp_path):
        m = MPNetModel.from_config(self.cfg(), seed=0)
        out = m(input_ids=jnp.asarray(IDS, jnp.int32), attention_mask=jnp.asarray(MASK, jnp.int32))
        assert out.last_hidden_state.shape == (2, 6, 32)
        m.save_pretrained(str(tmp_path))
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "encoder.relative_attention_bias.weight" in keys

    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import MPNetConfig as HFC, MPNetForMaskedLM as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=60, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=37, max_position_embeddings=64,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS), attention_mask=torch.tensor(MASK)).logits.numpy()
        m = MPNetForMaskedLM.from_pretrained(str(tmp_path))
        mine = m(input_ids=jnp.asarray(IDS, jnp.int32),
                 attention_mask=jnp.asarray(MASK, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)


class TestWave2Auto:
    def test_auto_resolution(self, tmp_path):
        from paddlenlp_tpu.transformers.auto import AutoModel

        m = DistilBertModel.from_config(
            DistilBertConfig(vocab_size=60, dim=32, n_layers=1, n_heads=4, hidden_dim=37), seed=0)
        m.save_pretrained(str(tmp_path))
        assert type(AutoModel.from_pretrained(str(tmp_path))).__name__ == "DistilBertModel"


class TestDebertaV2:
    KW = dict(vocab_size=60, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
              intermediate_size=37, max_position_embeddings=64,
              hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, pooler_dropout=0.0)
    V3 = dict(relative_attention=True, pos_att_type=["p2c", "c2p"], position_buckets=8,
              share_att_key=True, norm_rel_ebd="layer_norm")

    def test_forward_plain_and_v3(self):
        from paddlenlp_tpu.transformers import DebertaV2Config, DebertaV2Model

        for extra in ({}, self.V3):
            m = DebertaV2Model.from_config(DebertaV2Config(**self.KW, **extra), seed=0)
            out = m(input_ids=jnp.asarray(IDS, jnp.int32),
                    attention_mask=jnp.asarray(MASK, jnp.int32))
            assert out.last_hidden_state.shape == (2, 6, 32)
            assert np.isfinite(np.asarray(out.last_hidden_state)).all()

    @pytest.mark.parametrize("variant", ["plain", "v3", "v3_unshared"])
    def test_torch_parity(self, tmp_path, variant):
        torch = pytest.importorskip("torch")
        from transformers import DebertaV2Config as HFC, DebertaV2ForMaskedLM as HFM

        from paddlenlp_tpu.transformers import DebertaV2ForMaskedLM

        extra = {}
        if variant == "v3":
            extra = self.V3
        elif variant == "v3_unshared":
            extra = dict(self.V3, share_att_key=False)
        torch.manual_seed(0)
        hm = HFM(HFC(**self.KW, **extra)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS),
                        attention_mask=torch.tensor(MASK)).logits.numpy()
        m = DebertaV2ForMaskedLM.from_pretrained(str(tmp_path))
        mine = m(input_ids=jnp.asarray(IDS, jnp.int32),
                 attention_mask=jnp.asarray(MASK, jnp.int32)).logits
        # padded positions are not meaningful outputs (HF zeroes fully-masked
        # query rows, we don't) — compare the real tokens
        valid = MASK.astype(bool)
        np.testing.assert_allclose(np.asarray(mine)[valid], golden[valid], atol=3e-4)

    def test_sequence_classification_head(self):
        from paddlenlp_tpu.transformers import DebertaV2Config, DebertaV2ForSequenceClassification

        m = DebertaV2ForSequenceClassification.from_config(
            DebertaV2Config(**self.KW, num_labels=3), seed=0)
        out = m(input_ids=jnp.asarray(IDS, jnp.int32))
        assert out.logits.shape == (2, 3)


class TestFNet:
    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import FNetConfig as HFC, FNetForMaskedLM as HFM

        from paddlenlp_tpu.transformers import FNetForMaskedLM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=60, hidden_size=32, num_hidden_layers=2, intermediate_size=37,
                     max_position_embeddings=64, type_vocab_size=2,
                     hidden_dropout_prob=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS)).logits.numpy()
        m = FNetForMaskedLM.from_pretrained(str(tmp_path))
        mine = m(input_ids=jnp.asarray(IDS, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)

    def test_no_attention_params(self, tmp_path):
        from paddlenlp_tpu.transformers import FNetConfig, FNetModel
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params

        m = FNetModel.from_config(FNetConfig(vocab_size=60, hidden_size=32, num_hidden_layers=2,
                                             intermediate_size=37, type_vocab_size=2), seed=0)
        paths = list(flatten_params(m.params))
        assert not any("query" in p or "attn" in p for p in paths)  # attention-free
        out = m(input_ids=jnp.asarray(IDS, jnp.int32))
        assert out.last_hidden_state.shape == (2, 6, 32)


class TestErnieM:
    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import ErnieMConfig as HFC, ErnieMModel as HFM

        from paddlenlp_tpu.transformers import ErnieMModel

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=60, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=48, max_position_embeddings=64,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS)).last_hidden_state.numpy()
        m = ErnieMModel.from_pretrained(str(tmp_path))
        mine = m(input_ids=jnp.asarray(IDS, jnp.int32)).last_hidden_state
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)

    def test_cls_heads(self):
        from paddlenlp_tpu.transformers import ErnieMConfig, ErnieMForSequenceClassification

        m = ErnieMForSequenceClassification.from_config(
            ErnieMConfig(vocab_size=60, hidden_size=32, num_hidden_layers=1,
                         num_attention_heads=4, intermediate_size=48, num_labels=3), seed=0)
        out = m(input_ids=jnp.asarray(IDS, jnp.int32))
        assert out.logits.shape == (2, 3)


class TestMegatronBert:
    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import MegatronBertConfig as HFC, MegatronBertForMaskedLM as HFM

        from paddlenlp_tpu.transformers import MegatronBertForMaskedLM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=60, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=48, max_position_embeddings=64,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS), attention_mask=torch.tensor(MASK)).logits.numpy()
        m = MegatronBertForMaskedLM.from_pretrained(str(tmp_path))
        mine = m(input_ids=jnp.asarray(IDS, jnp.int32),
                 attention_mask=jnp.asarray(MASK, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)

    def test_pre_ln_no_embed_norm(self, tmp_path):
        from paddlenlp_tpu.transformers import MegatronBertConfig, MegatronBertModel
        from paddlenlp_tpu.utils.safetensors_io import safe_keys

        m = MegatronBertModel.from_config(
            MegatronBertConfig(vocab_size=60, hidden_size=32, num_hidden_layers=1,
                               num_attention_heads=4, intermediate_size=48), seed=0)
        m.save_pretrained(str(tmp_path))
        keys = set(safe_keys(str(tmp_path / "model.safetensors")))
        assert "encoder.layer.0.attention.ln.weight" in keys
        assert "encoder.ln.weight" in keys
        assert "embeddings.LayerNorm.weight" not in keys


class TestLayoutLM:
    def test_torch_parity_with_bbox(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import LayoutLMConfig as HFC, LayoutLMForMaskedLM as HFM

        from paddlenlp_tpu.transformers import LayoutLMForMaskedLM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=60, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=48, max_position_embeddings=64,
                     max_2d_position_embeddings=128,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        rng = np.random.default_rng(0)
        x0 = rng.integers(0, 50, (2, 6)); y0 = rng.integers(0, 50, (2, 6))
        bbox = np.stack([x0, y0, x0 + rng.integers(1, 40, (2, 6)),
                         y0 + rng.integers(1, 40, (2, 6))], axis=-1).astype(np.int64)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS), bbox=torch.tensor(bbox),
                        attention_mask=torch.tensor(MASK)).logits.numpy()
        m = LayoutLMForMaskedLM.from_pretrained(str(tmp_path))
        mine = m(input_ids=jnp.asarray(IDS, jnp.int32), bbox=jnp.asarray(bbox, jnp.int32),
                 attention_mask=jnp.asarray(MASK, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)

    def test_token_classification_head(self):
        from paddlenlp_tpu.transformers import LayoutLMConfig, LayoutLMForTokenClassification

        m = LayoutLMForTokenClassification.from_config(
            LayoutLMConfig(vocab_size=60, hidden_size=32, num_hidden_layers=1,
                           num_attention_heads=4, intermediate_size=48, num_labels=5), seed=0)
        out = m(input_ids=jnp.asarray(IDS, jnp.int32))
        assert out.logits.shape == (2, 6, 5)


class TestRemBert:
    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import RemBertConfig as HFC, RemBertForMaskedLM as HFM

        from paddlenlp_tpu.transformers import RemBertForMaskedLM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=60, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=48, max_position_embeddings=64,
                     input_embedding_size=16, output_embedding_size=24,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                     classifier_dropout_prob=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS), attention_mask=torch.tensor(MASK)).logits.numpy()
        m = RemBertForMaskedLM.from_pretrained(str(tmp_path))
        mine = m(input_ids=jnp.asarray(IDS, jnp.int32),
                 attention_mask=jnp.asarray(MASK, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)

    def test_decoupled_embedding_shapes(self):
        from paddlenlp_tpu.transformers import RemBertConfig, RemBertModel
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params

        m = RemBertModel.from_config(
            RemBertConfig(vocab_size=60, hidden_size=32, num_hidden_layers=1,
                          num_attention_heads=4, intermediate_size=48,
                          input_embedding_size=16), seed=0)
        flat = flatten_params(m.params)
        assert flat["embeddings_word_embeddings/embedding"].shape == (60, 16)
        assert flat["encoder_embedding_hidden_mapping_in/kernel"].shape == (16, 32)


class TestSqueezeBert:
    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import SqueezeBertConfig as HFC, SqueezeBertForMaskedLM as HFM

        from paddlenlp_tpu.transformers import SqueezeBertForMaskedLM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=60, hidden_size=32, embedding_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=48, max_position_embeddings=64,
                     q_groups=2, k_groups=2, v_groups=2, post_attention_groups=2,
                     intermediate_groups=2, output_groups=2,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS), attention_mask=torch.tensor(MASK)).logits.numpy()
        m = SqueezeBertForMaskedLM.from_pretrained(str(tmp_path))
        mine = m(input_ids=jnp.asarray(IDS, jnp.int32),
                 attention_mask=jnp.asarray(MASK, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)

    def test_grouped_conv_kernels(self, tmp_path):
        from paddlenlp_tpu.transformers import SqueezeBertConfig, SqueezeBertModel
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params

        m = SqueezeBertModel.from_config(
            SqueezeBertConfig(vocab_size=60, hidden_size=32, num_hidden_layers=1,
                              num_attention_heads=4, intermediate_size=48,
                              q_groups=2, k_groups=2, v_groups=2, post_attention_groups=2,
                              intermediate_groups=2, output_groups=2), seed=0)
        flat = flatten_params(m.params)
        # grouped pointwise conv: [1, in/groups, out]
        assert flat["encoder_layers_0/attention_query/kernel"].shape == (1, 16, 32)
        m.save_pretrained(str(tmp_path))
        m2 = SqueezeBertModel.from_pretrained(str(tmp_path))
        ids = jnp.asarray(IDS, jnp.int32)
        np.testing.assert_allclose(np.asarray(m(input_ids=ids).last_hidden_state),
                                   np.asarray(m2(input_ids=ids).last_hidden_state), atol=1e-5)
