"""HF checkpoint-layout loading for fused/renamed architectures: a checkpoint in
the TRUE HF key layout (fused W_pack / c_attn, transformer.h.* renames) must load
and reproduce the logits of the originating model, and our own saved checkpoints
must round-trip through the mechanical fallback keys."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
from safetensors.numpy import save_file

from paddlenlp_tpu.transformers import (
    BaichuanConfig,
    BaichuanForCausalLM,
    QWenConfig,
    QWenForCausalLM,
)
from paddlenlp_tpu.transformers.conversion_utils import flatten_params

TINY = dict(vocab_size=96, hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64)


def _write_ckpt(tmp_path, config, tensors):
    d = tmp_path / "hf"
    d.mkdir()
    config.save_pretrained(str(d))
    save_file({k: np.ascontiguousarray(v) for k, v in tensors.items()},
              os.path.join(str(d), "model.safetensors"), metadata={"format": "np"})
    return str(d)


class TestBaichuanWPack:
    def test_fused_wpack_loads(self, tmp_path):
        model = BaichuanForCausalLM.from_config(BaichuanConfig(intermediate_size=112, **TINY), seed=0)
        ids = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
        ref = model(input_ids=ids).logits
        flat = {k: np.asarray(v) for k, v in flatten_params(model.params).items()}
        D = 64
        tensors = {}
        for i in range(2):
            qkv = [flat[f"model/layers/self_attn/{p}_proj/kernel"][i].T for p in "qkv"]
            tensors[f"model.layers.{i}.self_attn.W_pack.weight"] = np.concatenate(qkv, axis=0)
        for path, arr in flat.items():
            if "/self_attn/q_proj" in path or "/self_attn/k_proj" in path or "/self_attn/v_proj" in path:
                continue
            if "/layers/" in path:
                for i in range(2):
                    key = ("model.layers.%d." % i) + path.split("/layers/")[1].replace("/", ".")
                    key = key.replace(".kernel", ".weight").replace(".scale", ".weight")
                    a = arr[i]
                    tensors[key] = a.T if path.endswith("kernel") else a
            else:
                key = path.replace("/", ".").replace(".kernel", ".weight") \
                          .replace(".scale", ".weight").replace(".embedding", ".weight")
                tensors[key] = arr.T if path.endswith("kernel") else arr
        d = _write_ckpt(tmp_path, model.config, tensors)
        loaded = BaichuanForCausalLM.from_pretrained(d)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(loaded(input_ids=ids).logits), atol=1e-5)

    def test_own_checkpoint_roundtrip(self, tmp_path):
        model = BaichuanForCausalLM.from_config(BaichuanConfig(intermediate_size=112, **TINY), seed=1)
        ids = jnp.asarray([[5, 6, 7]], jnp.int32)
        model.save_pretrained(str(tmp_path / "own"))
        loaded = BaichuanForCausalLM.from_pretrained(str(tmp_path / "own"))
        np.testing.assert_allclose(np.asarray(model(input_ids=ids).logits),
                                   np.asarray(loaded(input_ids=ids).logits), atol=1e-5)


class TestQWenCAttn:
    def test_fused_c_attn_loads(self, tmp_path):
        model = QWenForCausalLM.from_config(QWenConfig(intermediate_size=224, **TINY), seed=0)
        ids = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
        ref = model(input_ids=ids).logits
        flat = {k: np.asarray(v) for k, v in flatten_params(model.params).items()}
        rename = {
            "input_layernorm": "ln_1", "post_attention_layernorm": "ln_2",
            "self_attn.o_proj": "attn.c_proj", "mlp.gate_proj": "mlp.w2",
            "mlp.up_proj": "mlp.w1", "mlp.down_proj": "mlp.c_proj",
        }
        tensors = {}
        for i in range(2):
            qkv_w = [flat[f"model/layers/self_attn/{p}_proj/kernel"][i].T for p in "qkv"]
            qkv_b = [flat[f"model/layers/self_attn/{p}_proj/bias"][i] for p in "qkv"]
            tensors[f"transformer.h.{i}.attn.c_attn.weight"] = np.concatenate(qkv_w, axis=0)
            tensors[f"transformer.h.{i}.attn.c_attn.bias"] = np.concatenate(qkv_b, axis=0)
        for path, arr in flat.items():
            if "/self_attn/q_proj" in path or "/self_attn/k_proj" in path or "/self_attn/v_proj" in path:
                continue
            if "/layers/" in path:
                for i in range(2):
                    sub = path.split("/layers/")[1].replace("/", ".")
                    for a, b in rename.items():
                        sub = sub.replace(a, b)
                    key = f"transformer.h.{i}." + sub
                    key = key.replace(".kernel", ".weight").replace(".scale", ".weight")
                    tensors[key] = arr[i].T if path.endswith("kernel") else arr[i]
            elif path == "model/embed_tokens/embedding":
                tensors["transformer.wte.weight"] = arr
            elif path == "model/norm/scale":
                tensors["transformer.ln_f.weight"] = arr
            elif path == "lm_head/kernel":
                tensors["lm_head.weight"] = arr.T
            else:
                raise AssertionError(f"unmapped {path}")
        d = _write_ckpt(tmp_path, model.config, tensors)
        loaded = QWenForCausalLM.from_pretrained(d)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(loaded(input_ids=ids).logits), atol=1e-5)


class TestDeepseekV2HFLayout:
    def test_hf_expert_and_mla_keys_load(self, tmp_path):
        """A TRUE HF-layout deepseek_v2 checkpoint (per-expert mlp.experts.{e}.*
        keys, MLA q_a/q_b/kv_a/kv_b projections, torch [out,in] kernels) must
        load and reproduce the originating logits."""
        from paddlenlp_tpu.transformers import DeepseekV2Config, DeepseekV2ForCausalLM

        cfg = DeepseekV2Config(
            intermediate_size=112, moe_intermediate_size=48,
            q_lora_rank=24, kv_lora_rank=16, qk_rope_head_dim=8, qk_nope_head_dim=8,
            v_head_dim=16, n_routed_experts=4, n_shared_experts=1, num_experts_per_tok=2,
            first_k_dense_replace=1, **TINY)
        model = DeepseekV2ForCausalLM.from_config(cfg, seed=0)
        # perturb so same-seed re-init cannot silently pass
        model.params = jax.tree.map(lambda x: x * 1.25, model.params)
        ids = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
        ref = model(input_ids=ids).logits
        flat = {k: np.asarray(v) for k, v in flatten_params(model.params).items()}
        tensors = {}
        for path, arr in flat.items():
            tail = path.rsplit("/", 1)[-1]
            if "/mlp/" in path and "/shared_experts/" not in path and tail in (
                "gate_proj", "up_proj", "down_proj") and arr.ndim == 3:
                i = path.split("/layers_")[1].split("/")[0]
                for e in range(arr.shape[0]):
                    tensors[f"model.layers.{i}.mlp.experts.{e}.{tail}.weight"] = arr[e].T
                continue
            key = path.replace("/layers_", "/layers.").replace("/", ".")
            key = key.replace(".kernel", ".weight").replace(".scale", ".weight") \
                     .replace(".embedding", ".weight")
            tensors[key] = arr.T if path.endswith("/kernel") else arr
        d = _write_ckpt(tmp_path, cfg, tensors)
        loaded = DeepseekV2ForCausalLM.from_pretrained(d)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(loaded(input_ids=ids).logits),
                                   atol=1e-5)


class TestMambaHFLayout:
    def test_hf_mamba_keys_load(self, tmp_path):
        """TRUE HF mamba layout (backbone.layers.{i}.mixer.*, conv1d.weight
        [Di,1,K], A_log/D verbatim, tied lm_head absent) must load and
        reproduce logits; our save must round-trip."""
        from paddlenlp_tpu.transformers import MambaConfig, MambaForCausalLM

        cfg = MambaConfig(vocab_size=96, hidden_size=64, num_hidden_layers=2,
                          state_size=8, conv_kernel=4, time_step_rank=8,
                          initializer_range=0.02)
        model = MambaForCausalLM.from_config(cfg, seed=0)
        model.params = jax.tree.map(lambda x: x * 1.25, model.params)
        ids = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
        ref = model(input_ids=ids).logits
        flat = {k: np.asarray(v) for k, v in flatten_params(model.params).items()}
        import re
        tensors = {}
        for path, arr in flat.items():
            hf = re.sub(r"layers_(\d+)_(norm|mixer)", r"layers.\1.\2", path).replace("/", ".")
            if hf.endswith(".conv1d_weight"):
                tensors[hf.replace(".conv1d_weight", ".conv1d.weight")] = \
                    np.ascontiguousarray(arr.T[:, None, :])
            elif hf.endswith(".conv1d_bias"):
                tensors[hf.replace(".conv1d_bias", ".conv1d.bias")] = arr
            elif hf.endswith(".kernel"):
                tensors[hf.replace(".kernel", ".weight")] = arr.T
            elif hf.endswith(".scale"):
                tensors[hf.replace(".scale", ".weight")] = arr
            elif hf == "backbone.embeddings":
                tensors["backbone.embeddings.weight"] = arr
            else:
                tensors[hf] = arr
        d = _write_ckpt(tmp_path, cfg, tensors)
        loaded = MambaForCausalLM.from_pretrained(d)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(loaded(input_ids=ids).logits),
                                   atol=1e-5)
        loaded.save_pretrained(str(tmp_path / "own"))
        again = MambaForCausalLM.from_pretrained(str(tmp_path / "own"))
        np.testing.assert_allclose(np.asarray(ref), np.asarray(again(input_ids=ids).logits),
                                   atol=1e-5)

    def test_batched_generate_padding_invariance(self):
        """A short prompt generated in a left-padded batch must match the same
        prompt generated alone (pad tokens invisible to the SSM recurrence)."""
        from paddlenlp_tpu.transformers import MambaConfig, MambaForCausalLM

        cfg = MambaConfig(vocab_size=96, hidden_size=64, num_hidden_layers=2,
                          state_size=8, conv_kernel=4, time_step_rank=8,
                          initializer_range=0.02, pad_token_id=0)
        model = MambaForCausalLM.from_config(cfg, seed=0)
        short = [5, 6, 7]
        long = [40, 41, 42, 43, 44, 45]
        alone, _ = model.generate(jnp.asarray([short], jnp.int32), max_new_tokens=5,
                                  do_sample=False, eos_token_id=None)
        pad = len(long) - len(short)
        batch_ids = jnp.asarray([[0] * pad + short, long], jnp.int32)
        mask = jnp.asarray([[0] * pad + [1] * len(short), [1] * len(long)], jnp.int32)
        both, _ = model.generate(batch_ids, attention_mask=mask, max_new_tokens=5,
                                 do_sample=False, eos_token_id=None)
        np.testing.assert_array_equal(np.asarray(alone[0]), np.asarray(both[0]))
