"""Decoder/encoder wave 3: gptj (parallel residual + partial interleaved
rotary), codegen (fused mp_num=4 qkv mapping), roformer (rotary encoder),
tinybert/ppminilm re-exports — HF-torch parity where HF ships the family."""

import numpy as np
import pytest

import jax.numpy as jnp

from paddlenlp_tpu.transformers import (
    CodeGenConfig,
    CodeGenForCausalLM,
    GPTJConfig,
    GPTJForCausalLM,
    PPMiniLMConfig,
    PPMiniLMModel,
    RoFormerConfig,
    RoFormerForMaskedLM,
    TinyBertConfig,
    TinyBertForSequenceClassification,
)

IDS = np.asarray([[2, 5, 6, 7, 8, 3]], np.int64)


class TestGPTJ:
    def cfg(self, **kw):
        return GPTJConfig(vocab_size=64, n_embd=32, n_layer=2, n_head=4, rotary_dim=4,
                          n_positions=64, resid_pdrop=0.0, attn_pdrop=0.0, **kw)

    @pytest.mark.parametrize("scan", [False, True])
    def test_forward_and_cache_parity(self, scan):
        model = GPTJForCausalLM.from_config(self.cfg(use_scan_layers=scan), seed=0)
        ids = jnp.asarray(IDS, jnp.int32)
        out = model(input_ids=ids)
        assert out.logits.shape == (1, 6, 64)
        gen, _ = model.generate(ids, max_new_tokens=4, do_sample=False, eos_token_id=63)
        # cached decode == teacher-forced argmax
        dec = np.asarray(IDS, np.int64)
        for _ in range(4):
            logits = model(input_ids=jnp.asarray(dec, jnp.int32)).logits
            dec = np.concatenate([dec, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
        np.testing.assert_array_equal(np.asarray(gen)[0], dec[0, 6:])

    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import GPTJConfig as HFC, GPTJForCausalLM as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=64, n_embd=32, n_layer=2, n_head=4, rotary_dim=4,
                     n_positions=64, resid_pdrop=0.0, attn_pdrop=0.0, embd_pdrop=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS)).logits.numpy()
        model = GPTJForCausalLM.from_pretrained(str(tmp_path))
        mine = model(input_ids=jnp.asarray(IDS, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)


class TestCodeGen:
    def test_torch_parity_fused_qkv(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import CodeGenConfig as HFC, CodeGenForCausalLM as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=64, n_embd=32, n_layer=2, n_head=4, rotary_dim=4,
                     n_positions=64, resid_pdrop=0.0, attn_pdrop=0.0, embd_pdrop=0.0)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS)).logits.numpy()
        model = CodeGenForCausalLM.from_pretrained(str(tmp_path))
        mine = model(input_ids=jnp.asarray(IDS, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)

    def test_own_checkpoint_roundtrip(self, tmp_path):
        model = CodeGenForCausalLM.from_config(
            CodeGenConfig(vocab_size=64, n_embd=32, n_layer=1, n_head=4, rotary_dim=4,
                          n_positions=64), seed=0)
        ids = jnp.asarray(IDS, jnp.int32)
        ref = model(input_ids=ids).logits
        model.save_pretrained(str(tmp_path))
        reloaded = CodeGenForCausalLM.from_pretrained(str(tmp_path))
        np.testing.assert_allclose(np.asarray(ref), np.asarray(reloaded(input_ids=ids).logits),
                                   atol=1e-5)


class TestRoFormer:
    def test_torch_parity(self, tmp_path):
        torch = pytest.importorskip("torch")
        from transformers import RoFormerConfig as HFC, RoFormerForMaskedLM as HFM

        torch.manual_seed(0)
        hm = HFM(HFC(vocab_size=64, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=37, max_position_embeddings=64, embedding_size=32,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                     rotary_value=False)).eval()
        hm.save_pretrained(str(tmp_path), safe_serialization=True)
        mask = np.ones_like(IDS)
        with torch.no_grad():
            golden = hm(input_ids=torch.tensor(IDS),
                        attention_mask=torch.tensor(mask)).logits.numpy()
        model = RoFormerForMaskedLM.from_pretrained(str(tmp_path))
        mine = model(input_ids=jnp.asarray(IDS, jnp.int32),
                     attention_mask=jnp.asarray(mask, jnp.int32)).logits
        np.testing.assert_allclose(np.asarray(mine), golden, atol=3e-4)


class TestDistilledReExports:
    def test_tinybert_and_ppminilm(self, tmp_path):
        m = TinyBertForSequenceClassification.from_config(
            TinyBertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                           num_attention_heads=2, intermediate_size=48, num_labels=3), seed=0)
        out = m(input_ids=jnp.asarray(IDS, jnp.int32))
        assert out.logits.shape == (1, 3)
        m.save_pretrained(str(tmp_path))
        from paddlenlp_tpu.transformers.auto import AutoModel

        auto = AutoModel.from_pretrained(str(tmp_path))
        assert type(auto).__name__ == "TinyBertModel"
        p = PPMiniLMModel.from_config(PPMiniLMConfig(vocab_size=64, hidden_size=32,
                                                     num_attention_heads=2, intermediate_size=48), seed=0)
        assert p.config.num_hidden_layers == 6
