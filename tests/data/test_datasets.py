"""Data subsystem tests: mmap indexed dataset, GPT pretraining dataset (native +
numpy index builders), blending, collators, zero-padding packing."""

import numpy as np
import pytest

from paddlenlp_tpu.data import (
    BlendableDataset,
    GPTDataset,
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    build_train_valid_test_datasets,
)
from paddlenlp_tpu.data.native import _build_sample_idx_np, build_sample_idx, native_available
from paddlenlp_tpu.datasets import ZeroPaddingMapDataset, greedy_pack


@pytest.fixture()
def corpus(tmp_path):
    """20 docs of varying lengths, token value == doc id (provenance-checkable)."""
    prefix = str(tmp_path / "corpus")
    builder = MMapIndexedDatasetBuilder(prefix, dtype=np.uint16)
    rng = np.random.default_rng(0)
    for d in range(20):
        builder.add_document(np.full(int(rng.integers(5, 40)), d, dtype=np.uint16))
    builder.finalize()
    return prefix


class TestIndexedDataset:
    def test_roundtrip(self, corpus):
        ds = MMapIndexedDataset(corpus)
        assert len(ds) == 20 and ds.n_docs == 20
        np.testing.assert_array_equal(np.unique(ds[3]), [3])

    def test_partial_get(self, corpus):
        ds = MMapIndexedDataset(corpus)
        full = ds[5]
        np.testing.assert_array_equal(ds.get(5, 2, 3), full[2:5])

    def test_bad_magic(self, tmp_path):
        p = tmp_path / "x.idx"
        p.write_bytes(b"NOTMAGIC" + b"\0" * 64)
        with pytest.raises(ValueError, match="bad magic"):
            MMapIndexedDataset(str(tmp_path / "x"))


class TestSampleIdx:
    def test_native_matches_numpy(self, corpus):
        ds = MMapIndexedDataset(corpus)
        doc_idx = np.concatenate([np.random.default_rng(1).permutation(20) for _ in range(4)]).astype(np.int64)
        got = build_sample_idx(np.asarray(ds.sizes), doc_idx, seq_length=16, n_samples=30)
        want = _build_sample_idx_np(np.asarray(ds.sizes), doc_idx, 16, 30)
        np.testing.assert_array_equal(got, want)

    def test_native_compiled(self):
        assert native_available(), "g++ helper should compile on this image"

    def test_exhaustion_raises(self, corpus):
        ds = MMapIndexedDataset(corpus)
        doc_idx = np.arange(20, dtype=np.int64)
        with pytest.raises(ValueError, match="exhausted"):
            build_sample_idx(np.asarray(ds.sizes), doc_idx, seq_length=64, n_samples=10**4)


class TestGPTDataset:
    def test_samples_fixed_length_and_shifted(self, corpus):
        ds = MMapIndexedDataset(corpus)
        g = GPTDataset(ds, np.arange(20), seq_length=32, n_samples=50, seed=0)
        assert len(g) == 50
        s = g[7]
        assert s["input_ids"].shape == (32,) and s["labels"].shape == (32,)
        # labels are inputs shifted by one within the sample window
        np.testing.assert_array_equal(s["input_ids"][1:], s["labels"][:-1])

    def test_deterministic_and_cached(self, corpus):
        ds = MMapIndexedDataset(corpus)
        a = GPTDataset(ds, np.arange(20), 32, 50, seed=3)
        b = GPTDataset(ds, np.arange(20), 32, 50, seed=3)  # second build hits the cache
        for i in (0, 13, 49):
            np.testing.assert_array_equal(a[i]["input_ids"], b[i]["input_ids"])

    def test_split_builder(self, corpus):
        train, valid, test = build_train_valid_test_datasets(
            corpus, seq_length=16, train_valid_test_num_samples=(40, 8, 0), splits_string="80,20,0"
        )
        assert len(train) == 40 and len(valid) == 8 and test is None
        # valid draws only from the last 20% of documents (ids 16..19)
        v = valid[0]
        assert set(np.unique(v["input_ids"])) <= set(range(16, 20))

    def test_blendable_mixture(self, corpus, tmp_path):
        ds = MMapIndexedDataset(corpus)
        g1 = GPTDataset(ds, np.arange(10), 16, 40, seed=0, name="a")
        g2 = GPTDataset(ds, np.arange(10, 20), 16, 40, seed=0, name="b")
        blend = BlendableDataset([g1, g2], [0.75, 0.25], n_samples=40)
        counts = np.bincount(blend.dataset_index, minlength=2)
        assert counts[0] == 30 and counts[1] == 10


class TestCollators:
    def _tok(self):
        class Tok:
            pad_token_id = 0
            cls_token_id = 1
            sep_token_id = 2
            mask_token_id = 3
            vocab_size = 50
            padding_side = "right"

        return Tok()

    def test_padding_collator(self):
        from paddlenlp_tpu.data import DataCollatorWithPadding

        coll = DataCollatorWithPadding(self._tok())
        out = coll([{"input_ids": [5, 6, 7]}, {"input_ids": [8, 9]}])
        np.testing.assert_array_equal(out["input_ids"], [[5, 6, 7], [8, 9, 0]])
        np.testing.assert_array_equal(out["attention_mask"], [[1, 1, 1], [1, 1, 0]])

    def test_label_padding_uses_ignore(self):
        from paddlenlp_tpu.data import DataCollatorForSeq2Seq

        coll = DataCollatorForSeq2Seq(self._tok())
        out = coll([{"input_ids": [5, 6, 7], "labels": [5, 6, 7]}, {"input_ids": [8], "labels": [8]}])
        np.testing.assert_array_equal(out["labels"][1], [8, -100, -100])

    def test_mlm_collator(self):
        from paddlenlp_tpu.data import DataCollatorForLanguageModeling

        coll = DataCollatorForLanguageModeling(self._tok(), mlm_probability=0.5, seed=0)
        feats = [{"input_ids": np.arange(4, 30)} for _ in range(4)]
        out = coll(feats)
        masked = out["labels"] != -100
        assert masked.any()
        # masked positions mostly replaced with mask_token (3)
        assert (out["input_ids"][masked] == 3).sum() > 0
        # non-masked labels are ignored
        assert (out["labels"][~masked] == -100).all()


class TestZeroPadding:
    def test_greedy_pack(self):
        examples = [{"input_ids": np.arange(5) + 1}, {"input_ids": np.arange(6) + 1},
                    {"input_ids": np.arange(10) + 1}, {"input_ids": np.arange(3) + 1}]
        packs = greedy_pack(examples, max_length=12)
        assert len(packs) == 3  # first-fit-in-order: [5,6] | [10] | [3]
        p = packs[0]
        assert p["input_ids"].shape == (12,)
        np.testing.assert_array_equal(p["segment_ids"][:11], [0] * 5 + [1] * 6)
        np.testing.assert_array_equal(p["position_ids"][:11], list(range(5)) + list(range(6)))
        assert p["labels"][11] == -100  # padding ignored in loss

    def test_map_dataset(self):
        class DS:
            def __len__(self):
                return 6

            def __getitem__(self, i):
                return {"input_ids": np.arange(4 + i) + 1}

        z = ZeroPaddingMapDataset(DS(), max_length=16)
        assert len(z) >= 2
        total = sum((p["labels"] != -100).sum() for p in [z[i] for i in range(len(z))])
        assert total == sum(4 + i for i in range(6))

    def test_packed_training_correctness(self):
        """Packed rows train like separate rows (segment mask + positions)."""
        import jax.numpy as jnp

        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=1,
                          num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=32)
        model = LlamaForCausalLM.from_config(cfg, seed=0)
        a = {"input_ids": np.asarray([5, 6, 7, 8])}
        b = {"input_ids": np.asarray([9, 10, 11])}
        pack = greedy_pack([a, b], max_length=8)[0]
        out = model(
            input_ids=jnp.asarray(pack["input_ids"][None]),
            segment_ids=jnp.asarray(pack["segment_ids"][None]),
            position_ids=jnp.asarray(pack["position_ids"][None]),
        ).logits
        sep_a = model(input_ids=jnp.asarray(a["input_ids"][None])).logits
        np.testing.assert_allclose(np.asarray(out[0, :4]), np.asarray(sep_a[0]), atol=2e-5)


class TestLoadDataset:
    def test_local_files_and_splits(self, tmp_path):
        import json

        from paddlenlp_tpu.datasets import load_dataset

        d = tmp_path / "corpus"
        d.mkdir()
        (d / "train.jsonl").write_text("\n".join(json.dumps({"text": f"t{i}"}) for i in range(4)))
        (d / "dev.json").write_text(json.dumps([{"text": "v0"}, {"text": "v1"}]))
        (d / "test.tsv").write_text("text\tlabel\na\t1\nb\t0\n")
        train, dev, test = load_dataset(str(d), splits=("train", "dev", "test"))
        assert len(train) == 4 and train[0]["text"] == "t0"
        assert len(dev) == 2 and dev[1]["text"] == "v1"
        assert len(test) == 2 and test[0] == {"text": "a", "label": "1"}

    def test_map_filter_shuffle(self, tmp_path):
        from paddlenlp_tpu.datasets import MapDataset

        ds = MapDataset([{"x": i} for i in range(10)])
        ds.map(lambda r: {"x": r["x"] * 2}).filter(lambda r: r["x"] >= 8)
        assert sorted(r["x"] for r in ds) == [8, 10, 12, 14, 16, 18]
        lazy = ds.map(lambda r: {"x": r["x"] + 1}, lazy=True)
        assert lazy[0]["x"] == ds[0]["x"] + 1

    def test_lazy_map_chains_eager_transforms(self):
        from paddlenlp_tpu.datasets import MapDataset

        base = MapDataset([{"x": i} for i in range(6)])
        lazy = base.map(lambda r: {"x": r["x"] * 2}, lazy=True)
        got = lazy.filter(lambda r: r["x"] >= 4)
        assert sorted(r["x"] for r in got) == [4, 6, 8, 10]
        shuffled = base.map(lambda r: {"x": r["x"]}, lazy=True).shuffle(seed=3)
        assert sorted(r["x"] for r in shuffled) == list(range(6))
        double_lazy = base.map(lambda r: {"x": r["x"] + 1}, lazy=True).map(
            lambda r: {"x": r["x"] * 10}, lazy=True
        )
        assert double_lazy[1]["x"] == (1 + 1) * 10
        eager_after = base.map(lambda r: {"x": r["x"]}, lazy=True).map(lambda r: {"x": -r["x"]})
        assert [r["x"] for r in eager_after] == [0, -1, -2, -3, -4, -5]

    def test_multihost_sampler_marks_filler_rows(self):
        import numpy as np

        from paddlenlp_tpu.data.dataloader import DataLoader

        ds = [{"labels": np.full((4,), i, np.int64)} for i in range(10)]
        # 10 rows, global batch 8, 2 shards: batch 2 is partial (2 real rows)
        loaders = [
            DataLoader(ds, batch_size=8, shuffle=False, drop_last=False,
                       num_shards=2, shard_id=s, shard_span=1)
            for s in (0, 1)
        ]
        b0 = list(loaders[0])
        b1 = list(loaders[1])
        assert len(b0) == len(b1) == 2
        # final batch: global rows 8..9 real, 10..15 wrap-filler
        # shard 0 holds rows 8,9,(10,11 filler); shard 1 all filler
        assert (b0[1]["labels"][:2] >= 0).all()
        assert (b0[1]["labels"][2:] == -100).all()
        assert (b1[1]["labels"] == -100).all()

    def test_registry_builder(self):
        from paddlenlp_tpu.datasets import load_dataset, register_dataset

        @register_dataset("unit_test_corpus")
        def build(split, name=None, **kw):
            return [{"split": split, "i": i} for i in range(3)]

        ds = load_dataset("unit_test_corpus", splits="dev")
        assert len(ds) == 3 and ds[0]["split"] == "dev"

    def test_missing_named_dataset_errors(self):
        import pytest

        from paddlenlp_tpu.datasets import load_dataset

        with pytest.raises(FileNotFoundError, match="register_dataset"):
            load_dataset("no_such_dataset_xyz")

    def test_iter_dataset_streaming(self):
        from paddlenlp_tpu.datasets import IterDataset

        ds = IterDataset(lambda: ({"x": i} for i in range(6)))
        ds.map(lambda r: {"x": r["x"] * 10}).filter(lambda r: r["x"] >= 30)
        assert [r["x"] for r in ds] == [30, 40, 50]
        assert [r["x"] for r in ds] == [30, 40, 50]  # re-iterable
