"""Legacy-scope libraries: CRF, RDrop, seq2vec encoders, TokenEmbedding,
dataaug, AutoNLP-lite (reference: paddlenlp/layers, losses, seq2vec,
embeddings, dataaug, experimental/autonlp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


class TestCRF:
    def _setup(self, B=3, T=5, N=4, seed=0):
        rng = np.random.default_rng(seed)
        emissions = jnp.asarray(rng.normal(size=(B, T, N)), jnp.float32)
        lengths = jnp.asarray([5, 3, 4], jnp.int32)
        tags = jnp.asarray(rng.integers(0, N, size=(B, T)), jnp.int32)
        return emissions, lengths, tags

    def test_nll_matches_bruteforce(self):
        """Forward-algorithm log Z == brute-force enumeration over all paths."""
        import itertools

        from paddlenlp_tpu.layers import LinearChainCrf

        B, T, N = 2, 4, 3
        rng = np.random.default_rng(1)
        emissions = jnp.asarray(rng.normal(size=(B, T, N)), jnp.float32)
        lengths = jnp.asarray([4, 2], jnp.int32)
        tags = jnp.asarray(rng.integers(0, N, size=(B, T)), jnp.int32)
        crf = LinearChainCrf(num_labels=N)
        params = crf.init(jax.random.key(0), emissions, lengths, tags)
        nll = crf.apply(params, emissions, lengths, tags)

        trans = np.asarray(params["params"]["transitions"])
        start = np.asarray(params["params"]["start_scores"])
        stop = np.asarray(params["params"]["stop_scores"])
        em = np.asarray(emissions)
        for b in range(B):
            L = int(lengths[b])
            scores = []
            for path in itertools.product(range(N), repeat=L):
                s = start[path[0]] + em[b, 0, path[0]] + stop[path[-1]]
                for t in range(1, L):
                    s += trans[path[t - 1], path[t]] + em[b, t, path[t]]
                scores.append(s)
            logZ = np.logaddexp.reduce(scores)
            gold_path = tuple(int(x) for x in np.asarray(tags[b])[:L])
            gold = start[gold_path[0]] + em[b, 0, gold_path[0]] + stop[gold_path[-1]]
            for t in range(1, L):
                gold += trans[gold_path[t - 1], gold_path[t]] + em[b, t, gold_path[t]]
            np.testing.assert_allclose(float(nll[b]), logZ - gold, rtol=1e-4, atol=1e-4)

    def test_viterbi_matches_bruteforce(self):
        import itertools

        from paddlenlp_tpu.layers import viterbi_decode

        B, T, N = 2, 4, 3
        rng = np.random.default_rng(2)
        emissions = jnp.asarray(rng.normal(size=(B, T, N)), jnp.float32)
        trans = jnp.asarray(rng.normal(size=(N, N)), jnp.float32)
        lengths = jnp.asarray([4, 3], jnp.int32)
        scores, paths = viterbi_decode(emissions, trans, lengths)
        em, tr = np.asarray(emissions), np.asarray(trans)
        for b in range(B):
            L = int(lengths[b])
            best, best_path = -np.inf, None
            for path in itertools.product(range(N), repeat=L):
                s = em[b, 0, path[0]] + sum(tr[path[t - 1], path[t]] + em[b, t, path[t]]
                                            for t in range(1, L))
                if s > best:
                    best, best_path = s, path
            np.testing.assert_allclose(float(scores[b]), best, rtol=1e-5)
            assert tuple(int(x) for x in np.asarray(paths[b])[:L]) == best_path

    def test_crf_loss_trains(self):
        """CRF NLL decreases under gradient descent on a learnable pattern."""
        from paddlenlp_tpu.layers import LinearChainCrfLoss

        emissions, lengths, tags = self._setup()
        loss_mod = LinearChainCrfLoss(num_labels=4)
        params = loss_mod.init(jax.random.key(0), emissions, lengths, tags)
        loss_fn = lambda p: loss_mod.apply(p, emissions, lengths, tags)
        l0 = float(loss_fn(params))
        for _ in range(20):
            grads = jax.grad(loss_fn)(params)
            params = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
        assert float(loss_fn(params)) < l0


class TestRDrop:
    def test_zero_for_identical(self):
        from paddlenlp_tpu.losses import RDropLoss

        p = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8)), jnp.float32)
        loss = RDropLoss(reduction="mean")(p, p)
        np.testing.assert_allclose(float(loss), 0.0, atol=1e-6)

    def test_positive_and_symmetric(self):
        from paddlenlp_tpu.losses import RDropLoss

        rng = np.random.default_rng(1)
        p = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
        q = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
        crit = RDropLoss(reduction="mean")
        assert float(crit(p, q)) > 0
        np.testing.assert_allclose(float(crit(p, q)), float(crit(q, p)), rtol=1e-6)

    def test_bad_reduction(self):
        from paddlenlp_tpu.losses import RDropLoss

        with pytest.raises(ValueError):
            RDropLoss(reduction="avg")


class TestSeq2Vec:
    def _inputs(self, B=2, T=6, D=8):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(B, T, D)), jnp.float32)
        mask = jnp.asarray([[1, 1, 1, 1, 0, 0], [1, 1, 1, 1, 1, 1]], jnp.int32)
        return x, mask

    def test_bow_masked_sum(self):
        from paddlenlp_tpu.seq2vec import BoWEncoder

        x, mask = self._inputs()
        out = BoWEncoder(emb_dim=8)(x, mask)
        ref = np.asarray(x[0, :4]).sum(0)
        np.testing.assert_allclose(np.asarray(out[0]), ref, rtol=1e-5)

    def test_cnn_shapes(self):
        from paddlenlp_tpu.seq2vec import CNNEncoder

        x, mask = self._inputs()
        enc = CNNEncoder(emb_dim=8, num_filter=16, ngram_filter_sizes=(2, 3))
        params = enc.init(jax.random.key(0), x, mask)
        out = enc.apply(params, x, mask)
        assert out.shape == (2, 32)
        assert np.isfinite(np.asarray(out)).all()

    @pytest.mark.parametrize("cls_name", ["LSTMEncoder", "GRUEncoder", "RNNEncoder"])
    def test_recurrent_encoders(self, cls_name):
        import paddlenlp_tpu.seq2vec as s2v

        x, mask = self._inputs()
        enc = getattr(s2v, cls_name)(input_size=8, hidden_size=12, direction="bidirect",
                                     pooling_type="mean")
        params = enc.init(jax.random.key(0), x, mask)
        out = enc.apply(params, x, mask)
        assert out.shape == (2, 24)
        assert np.isfinite(np.asarray(out)).all()

    def test_mask_freezes_padded_state(self):
        """Last-state pooling must ignore pad positions: shorter sequence's
        state equals running it without the padding."""
        from paddlenlp_tpu.seq2vec import LSTMEncoder

        x, mask = self._inputs()
        enc = LSTMEncoder(input_size=8, hidden_size=6)
        params = enc.init(jax.random.key(0), x, mask)
        full = enc.apply(params, x, mask)
        trimmed = enc.apply(params, x[:1, :4], jnp.ones((1, 4), jnp.int32))
        np.testing.assert_allclose(np.asarray(full[0]), np.asarray(trimmed[0]), rtol=1e-5, atol=1e-6)


class TestTokenEmbedding:
    def test_search_and_sim(self, tmp_path):
        from paddlenlp_tpu.embeddings import TokenEmbedding

        vocab = ["king", "queen", "apple"]
        mat = np.asarray([[1, 0, 0], [0.9, 0.1, 0], [0, 0, 1]], np.float32)
        emb = TokenEmbedding(vocab=vocab, matrix=mat)
        assert emb.search("king").shape == (1, 3)
        assert emb.cosine_sim("king", "queen") > emb.cosine_sim("king", "apple")
        # unknown word resolves to [UNK], not a crash
        assert emb.search("zebra").shape == (1, 3)

    def test_word2vec_text_load(self, tmp_path):
        from paddlenlp_tpu.embeddings import TokenEmbedding

        p = tmp_path / "vecs.txt"
        p.write_text("2 3\nfoo 1.0 0.0 0.0\nbar 0.0 1.0 0.0\n")
        emb = TokenEmbedding(str(p))
        np.testing.assert_allclose(emb.search("foo")[0], [1, 0, 0])
        ids = emb([emb.get_idx_from_word("bar")])
        np.testing.assert_allclose(np.asarray(ids)[0], [0, 1, 0])


class TestDataAug:
    def test_substitute_and_insert(self):
        from paddlenlp_tpu.dataaug import WordInsert, WordSubstitute

        table = {"good": ["great", "fine"], "movie": ["film"]}
        subst = WordSubstitute(custom_file_or_dict=table, create_n=2, aug_n=1, seed=0)
        outs = subst("a good movie")
        assert outs and all(o != "a good movie" for o in outs)
        ins = WordInsert(custom_file_or_dict=table, create_n=1, aug_n=1, seed=0)
        outs = ins("a good movie")
        assert outs and len(outs[0].split()) == 4

    def test_swap_delete(self):
        from paddlenlp_tpu.dataaug import WordDelete, WordSwap

        assert WordSwap(create_n=1, seed=1)("a b c d")[0] != "a b c d"
        out = WordDelete(create_n=1, aug_n=2, seed=1)("a b c d")[0]
        assert len(out.split()) == 2

    def test_requires_table(self):
        from paddlenlp_tpu.dataaug import WordSubstitute

        with pytest.raises(ValueError):
            WordSubstitute()


class TestAutoNLP:
    def test_search_picks_better_lr(self, tmp_path):
        from paddlenlp_tpu.experimental.autonlp import AutoTrainerForTextClassification
        from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

        rng = np.random.default_rng(0)
        rows = [rng.integers(2, 60, 12).astype(np.int32) for _ in range(32)]

        class DS:
            def __len__(self):
                return len(rows)

            def __getitem__(self, i):
                return {"input_ids": rows[i], "labels": rows[i].copy()}

        def factory(cand):
            cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                              num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
                              max_position_embeddings=32)
            return LlamaForCausalLM.from_config(cfg, seed=0)

        auto = AutoTrainerForTextClassification(
            DS(), DS(), model_factory=factory, output_dir=str(tmp_path),
            model_candidates=[{"learning_rate": 1e-6}, {"learning_rate": 5e-3}],
        )
        best = auto.train(max_steps=8, per_device_train_batch_size=4)
        assert len(auto.trials) == 2
        # the larger lr must fit the toy data far better over 8 steps
        assert best.candidate["learning_rate"] == 5e-3
        board = auto.visualize()
        assert board[0]["trial_id"] == best.trial_id
        export = auto.export(str(tmp_path / "best"))
        import os

        assert os.path.isfile(os.path.join(export, "model.safetensors"))


class TestCharDataAug:
    def test_char_substitute_and_insert(self):
        from paddlenlp_tpu.dataaug import CharInsert, CharSubstitute

        table = {"好": ["佳", "良"], "天": ["日"]}
        subst = CharSubstitute(custom_file_or_dict=table, create_n=2, aug_n=1, seed=0)
        outs = subst("今天天气好")
        assert outs and all(o != "今天天气好" for o in outs)
        assert all(len(o) == 5 for o in outs)  # substitution preserves length
        ins = CharInsert(custom_file_or_dict=table, create_n=1, aug_n=1, seed=0)
        outs = ins("今天好")
        assert outs and len(outs[0]) == 4  # one char inserted, no spaces

    def test_char_swap_delete(self):
        from paddlenlp_tpu.dataaug import CharDelete, CharSwap

        sw = CharSwap(create_n=1, aug_n=1, seed=0)
        outs = sw("abcdef")
        assert outs and sorted(outs[0]) == list("abcdef") and outs[0] != "abcdef"
        de = CharDelete(create_n=1, aug_n=2, seed=0)
        outs = de("abcdef")
        assert outs and len(outs[0]) == 4

    def test_batch_and_determinism(self):
        from paddlenlp_tpu.dataaug import CharSwap

        a = CharSwap(create_n=1, seed=3)("hello world")
        b = CharSwap(create_n=1, seed=3)("hello world")
        assert a == b
        batch = CharSwap(create_n=1, seed=0)(["abcd", "efgh"])
        assert len(batch) == 2
