"""Generation tests: greedy parity with manual decode, sampling determinism,
logits processors, batched left-pad decode, eos stopping."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.generation import GenerationConfig, LogitsProcessorList, TopKLogitsWarper, TopPLogitsWarper
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(
        vocab_size=96,
        hidden_size=64,
        intermediate_size=112,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        eos_token_id=2,
        pad_token_id=0,
    )
    return LlamaForCausalLM.from_config(cfg, seed=0)


class TestGreedy:
    def test_greedy_matches_manual_loop(self, model):
        """Jitted while_loop decode == naive re-forward-everything greedy."""
        prompt = jnp.array([[5, 6, 7, 8]], dtype=jnp.int32)
        out, _ = model.generate(prompt, max_new_tokens=6, do_sample=False)
        # manual: full forward each step, argmax
        ids = np.asarray(prompt)
        for _ in range(6):
            logits = model(input_ids=jnp.asarray(ids)).logits
            nxt = int(jnp.argmax(logits[0, -1]))
            ids = np.concatenate([ids, [[nxt]]], axis=1)
            if nxt == 2:
                break
        manual = ids[0, 4:]
        got = np.asarray(out[0])[: len(manual)]
        np.testing.assert_array_equal(got, manual)

    def test_batched_left_padding(self, model):
        """Left-padded batch rows decode identically to unpadded single rows."""
        single, _ = model.generate(jnp.array([[5, 6, 7]], jnp.int32), max_new_tokens=4, do_sample=False)
        batch_ids = jnp.array([[0, 0, 5, 6, 7], [11, 12, 13, 14, 15]], jnp.int32)
        mask = jnp.array([[0, 0, 1, 1, 1], [1, 1, 1, 1, 1]], jnp.int32)
        batched, _ = model.generate(batch_ids, attention_mask=mask, max_new_tokens=4, do_sample=False)
        np.testing.assert_array_equal(np.asarray(batched[0]), np.asarray(single[0]))

    def test_eos_stops_row(self, model):
        """After a row hits eos, it must emit pad only."""
        prompt = jnp.array([[5, 6, 7, 8]], dtype=jnp.int32)
        out, _ = model.generate(prompt, max_new_tokens=20, do_sample=False)
        toks = np.asarray(out[0])
        if 2 in toks:
            i = int(np.argmax(toks == 2))
            assert (toks[i + 1 :] == 0).all()

    def test_trunc_input_false(self, model):
        prompt = jnp.array([[5, 6, 7]], dtype=jnp.int32)
        out, _ = model.generate(prompt, max_new_tokens=2, do_sample=False, trunc_input=False)
        np.testing.assert_array_equal(np.asarray(out[0, :3]), [5, 6, 7])
        assert out.shape == (1, 5)


class TestSampling:
    def test_seeded_reproducible(self, model):
        prompt = jnp.array([[5, 6, 7]], dtype=jnp.int32)
        a, _ = model.generate(prompt, max_new_tokens=8, do_sample=True, top_k=20, seed=13)
        b, _ = model.generate(prompt, max_new_tokens=8, do_sample=True, top_k=20, seed=13)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_temperature_zero_k1_is_greedy(self, model):
        prompt = jnp.array([[5, 6, 7]], dtype=jnp.int32)
        greedy, _ = model.generate(prompt, max_new_tokens=5, do_sample=False)
        k1, _ = model.generate(prompt, max_new_tokens=5, do_sample=True, top_k=1, seed=3)
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))


class TestWarpers:
    def test_top_k_masks(self):
        warper = TopKLogitsWarper(2)
        logits = jnp.asarray([[1.0, 3.0, 2.0, 0.5]])
        out = warper(None, logits, 0)
        assert out[0, 1] == 3.0 and out[0, 2] == 2.0
        assert out[0, 0] < -1e8 and out[0, 3] < -1e8

    def test_top_p_keeps_head(self):
        warper = TopPLogitsWarper(0.5)
        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.1, 0.1]]))
        out = warper(None, logits, 0)
        assert out[0, 0] > -1e8  # top-1 always kept
        assert out[0, 2] < -1e8 and out[0, 3] < -1e8

    def test_repetition_penalty_blocks_loop(self, model):
        prompt = jnp.array([[5, 6, 5, 6, 5, 6]], dtype=jnp.int32)
        plain, _ = model.generate(prompt, max_new_tokens=8, do_sample=False)
        pen, _ = model.generate(prompt, max_new_tokens=8, do_sample=False, repetition_penalty=2.0)
        # both valid sequences; penalized must differ if plain repeats the prompt bigram
        assert plain.shape == pen.shape

    def test_no_repeat_ngram(self, model):
        prompt = jnp.array([[5, 6, 7]], dtype=jnp.int32)
        out, _ = model.generate(prompt, max_new_tokens=16, do_sample=False, no_repeat_ngram_size=2, eos_token_id=None)
        full = np.concatenate([np.asarray(prompt[0]), np.asarray(out[0])])
        bigrams = set()
        for i in range(len(full) - 1):
            bg = (full[i], full[i + 1])
            if 0 in bg:
                continue
            assert bg not in bigrams, f"repeated bigram {bg}"
            bigrams.add(bg)


class TestBeamSearch:
    def test_matches_naive_beam(self, model):
        """while_loop beam search == re-forward-everything reference beam."""
        prompt = jnp.asarray([[5, 6, 7]], jnp.int32)
        out, scores = model.generate(prompt, max_new_tokens=5, num_beams=3, eos_token_id=None)

        beams = [(list(np.asarray(prompt[0])), 0.0)]
        for _ in range(5):
            cand = []
            for ids, sc in beams:
                logits = model(input_ids=jnp.asarray([ids], jnp.int32)).logits[0, -1]
                logp = np.asarray(jax.nn.log_softmax(logits.astype(jnp.float32)))
                for t in np.argsort(logp)[::-1][:4]:
                    cand.append((ids + [int(t)], sc + float(logp[t])))
            cand.sort(key=lambda x: -x[1])
            beams = cand[:3]
        np.testing.assert_array_equal(np.asarray(out[0]), beams[0][0][3:])
        np.testing.assert_allclose(float(scores[0]), beams[0][1] / 5.0, rtol=1e-5)

    def test_beam_beats_greedy_score(self, model):
        """Beam-3's sequence log-prob must be >= the greedy sequence's."""
        prompt = jnp.asarray([[11, 12, 13]], jnp.int32)
        greedy, _ = model.generate(prompt, max_new_tokens=6, do_sample=False, eos_token_id=None)
        beam, beam_score = model.generate(prompt, max_new_tokens=6, num_beams=4, eos_token_id=None)

        def seq_logp(gen):
            ids = np.concatenate([np.asarray(prompt[0]), np.asarray(gen)])
            logits = model(input_ids=jnp.asarray([ids[:-1]], jnp.int32)).logits[0].astype(jnp.float32)
            lp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
            return sum(lp[2 + i, t] for i, t in enumerate(np.asarray(gen)))

        assert seq_logp(beam[0]) >= seq_logp(greedy[0]) - 1e-4

    def test_eos_freezes_beam(self, model):
        """A beam that emits eos must continue with pad only."""
        out, _ = model.generate(jnp.asarray([[5, 6, 7, 8]], jnp.int32), max_new_tokens=16,
                                num_beams=2, eos_token_id=2)
        toks = np.asarray(out[0])
        if 2 in toks:
            i = int(np.argmax(toks == 2))
            assert (toks[i + 1:] == 0).all()

    def test_group_beam_runs(self, model):
        out, scores = model.generate(jnp.asarray([[5, 6, 7]], jnp.int32), max_new_tokens=5,
                                     num_beams=4, num_beam_groups=2, diversity_penalty=1.0,
                                     decode_strategy="group_beam_search", eos_token_id=None)
        assert out.shape == (1, 5)
        assert np.isfinite(float(scores[0]))

    def test_batched_beams_isolated(self, model):
        """Each batch row's beams must be independent."""
        single, _ = model.generate(jnp.asarray([[5, 6, 7]], jnp.int32), max_new_tokens=4,
                                   num_beams=3, eos_token_id=None)
        batch, _ = model.generate(jnp.asarray([[5, 6, 7], [40, 41, 42]], jnp.int32),
                                  max_new_tokens=4, num_beams=3, eos_token_id=None)
        np.testing.assert_array_equal(np.asarray(batch[0]), np.asarray(single[0]))


class TestProcessorFixes:
    def test_min_length_blocks_all_eos_ids(self):
        from paddlenlp_tpu.generation import MinLengthLogitsProcessor

        proc = MinLengthLogitsProcessor(4, [2, 5], prompt_len=0)
        logits = jnp.zeros((1, 8))
        out = proc(jnp.zeros((1, 8), jnp.int32), logits, jnp.asarray(1))
        assert out[0, 2] < -1e8 and out[0, 5] < -1e8
        assert out[0, 3] == 0.0

    def test_valid_counts_sentinel_excluded(self):
        from paddlenlp_tpu.generation.logits_process import _valid_counts

        ids = jnp.asarray([[8, 1, 1, 3]], jnp.int32)  # 8 == vocab_size sentinel
        counts = _valid_counts(ids, jnp.asarray(4), 8)
        assert int(counts[0, 1]) == 2 and int(counts.sum()) == 3

    def test_left_pad_parity_with_penalties(self, model):
        """Pad slots must not feed the penalty counts: a left-padded row decodes
        identically to the same row unpadded even with frequency penalty on."""
        kw = dict(max_new_tokens=4, do_sample=False, frequency_penalty=0.5, repetition_penalty=1.3)
        single, _ = model.generate(jnp.array([[5, 6, 7]], jnp.int32), **kw)
        batch_ids = jnp.array([[0, 0, 5, 6, 7], [11, 12, 13, 14, 15]], jnp.int32)
        mask = jnp.array([[0, 0, 1, 1, 1], [1, 1, 1, 1, 1]], jnp.int32)
        batched, _ = model.generate(batch_ids, attention_mask=mask, **kw)
        np.testing.assert_array_equal(np.asarray(batched[0]), np.asarray(single[0]))


class TestGenerationConfig:
    def test_save_load(self, tmp_path):
        g = GenerationConfig(max_new_tokens=32, do_sample=True, top_p=0.9, eos_token_id=2)
        g.save_pretrained(str(tmp_path))
        g2 = GenerationConfig.from_pretrained(str(tmp_path))
        assert g2.max_new_tokens == 32 and g2.top_p == 0.9
