"""Paged inference runtime: block manager accounting, paged-vs-dense decode
parity, continuous batching with staggered arrivals, preemption recovery."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.experimental import BlockManager, InferenceEngine, SamplingParams
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


class TestBlockManager:
    def test_alloc_free_cycle(self):
        mgr = BlockManager(num_blocks=17, block_size=4, max_blocks_per_seq=8)
        assert mgr.num_free == 16  # block 0 is the sentinel
        mgr.allocate(1, 10)  # 3 blocks
        assert mgr.num_free == 13
        mgr.extend(1, 3)  # 13 tokens -> 4 blocks
        assert mgr.num_free == 12
        mgr.free_seq(1)
        assert mgr.num_free == 16

    def test_oom_returns_none_on_extend(self):
        mgr = BlockManager(num_blocks=3, block_size=4, max_blocks_per_seq=8)
        mgr.allocate(1, 8)  # uses both free blocks
        assert mgr.extend(1, 1) is None

    def test_table_array_sentinel_padding(self):
        mgr = BlockManager(num_blocks=9, block_size=4, max_blocks_per_seq=6)
        mgr.allocate(5, 6)
        t = mgr.table_array(5)
        assert t.shape == (6,)
        assert (t[2:] == 0).all() and (t[:2] > 0).all()


class TestPagedParity:
    def test_greedy_matches_generate(self, model):
        """Engine greedy decode == the training-side generate() greedy decode."""
        prompt = [5, 6, 7, 8, 9]
        ref, _ = model.generate(jnp.asarray([prompt], jnp.int32), max_new_tokens=8,
                                do_sample=False, eos_token_id=None)
        eng = InferenceEngine(model, max_batch_size=2, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        out = eng.generate([prompt], SamplingParams(max_new_tokens=8))
        np.testing.assert_array_equal(np.asarray(ref[0]), np.asarray(out[0]))

    def test_batch_isolation(self, model):
        """Two sequences decoded together == each decoded alone."""
        p1, p2 = [5, 6, 7], [40, 41, 42, 43, 44, 45]
        eng = InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        together = eng.generate([p1, p2], SamplingParams(max_new_tokens=6))
        eng1 = InferenceEngine(model, max_batch_size=1, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        alone1 = eng1.generate([p1], SamplingParams(max_new_tokens=6))[0]
        eng2 = InferenceEngine(model, max_batch_size=1, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        alone2 = eng2.generate([p2], SamplingParams(max_new_tokens=6))[0]
        np.testing.assert_array_equal(together[0], alone1)
        np.testing.assert_array_equal(together[1], alone2)

    def test_staggered_arrivals(self, model):
        """A request arriving mid-decode (continuous batching) must not disturb
        the running request's tokens."""
        p1, p2 = [5, 6, 7, 8], [30, 31, 32]
        ref_eng = InferenceEngine(model, max_batch_size=1, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        ref1 = ref_eng.generate([p1], SamplingParams(max_new_tokens=8))[0]

        eng = InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        eng.add_request(p1, SamplingParams(max_new_tokens=8))
        done = []
        done += eng.step()  # prefill p1 + first decode
        done += eng.step()
        eng.add_request(p2, SamplingParams(max_new_tokens=4))  # arrives mid-flight
        while eng.has_work():
            done += eng.step()
        by_id = {r.req_id: r.output_ids for r in done}
        np.testing.assert_array_equal(by_id[0], ref1)
        assert len(by_id[1]) == 4

    def test_sampling_seeded(self, model):
        eng = InferenceEngine(model, max_batch_size=2, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        a = eng.generate([[5, 6, 7]], SamplingParams(max_new_tokens=6, do_sample=True, top_p=0.9, seed=7))
        eng2 = InferenceEngine(model, max_batch_size=2, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        b = eng2.generate([[5, 6, 7]], SamplingParams(max_new_tokens=6, do_sample=True, top_p=0.9, seed=7))
        np.testing.assert_array_equal(a[0], b[0])

    def test_streaming_callback(self, model):
        eng = InferenceEngine(model, max_batch_size=1, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        got = []
        eng.add_request([5, 6, 7], SamplingParams(max_new_tokens=5),
                        stream_cb=lambda tok, done: got.append((tok, done)))
        while eng.has_work():
            eng.step()
        assert len(got) == 5
        assert got[-1][1] is True and not any(d for _, d in got[:-1])


class TestDeviceSampling:
    def test_sample_tokens_top_k1_is_greedy(self):
        from paddlenlp_tpu.experimental.inference_model import sample_tokens

        logits = jnp.asarray(np.random.default_rng(0).normal(size=(3, 16)), jnp.float32)
        kw = dict(positions=jnp.zeros(3, jnp.int32), seeds=jnp.arange(3, dtype=jnp.int32),
                  temperature=jnp.ones(3), top_k=jnp.full(3, 1, jnp.int32), top_p=jnp.ones(3),
                  do_sample=jnp.ones(3, bool))
        toks = sample_tokens(logits, **kw)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(jnp.argmax(logits, -1)))

    def test_sample_tokens_penalties_shift_argmax(self):
        from paddlenlp_tpu.experimental.inference_model import sample_tokens

        logits = jnp.asarray([[2.0, 1.9, 0.0, -1.0]], jnp.float32)
        counts = jnp.asarray([[3, 0, 0, 0]], jnp.int32)  # token 0 heavily repeated
        kw = dict(positions=jnp.zeros(1, jnp.int32), seeds=jnp.zeros(1, jnp.int32),
                  temperature=jnp.ones(1), top_k=jnp.zeros(1, jnp.int32), top_p=jnp.ones(1),
                  do_sample=jnp.zeros(1, bool), counts=counts,
                  repetition_penalty=jnp.asarray([2.0]), presence_penalty=jnp.asarray([0.5]),
                  frequency_penalty=jnp.asarray([0.1]))
        tok = sample_tokens(logits, **kw)
        assert int(tok[0]) == 1  # penalized 2.0/2 - 0.5 - 0.3 < 1.9

    def test_engine_repetition_penalty_changes_greedy(self, model):
        prompt = [5, 6, 5, 6, 5, 6]
        eng = InferenceEngine(model, max_batch_size=1, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        plain = eng.generate([prompt], SamplingParams(max_new_tokens=8))
        eng2 = InferenceEngine(model, max_batch_size=1, block_size=4, num_blocks=64, max_blocks_per_seq=16)
        pen = eng2.generate([prompt], SamplingParams(max_new_tokens=8, repetition_penalty=5.0,
                                                     presence_penalty=1.0))
        assert len(pen[0]) == 8
        # a strong penalty must perturb the greedy continuation of a looping prompt
        assert plain[0] != pen[0], (plain, pen)
        # and the penalized run must not emit the same token twice in a row
        assert all(a != b for a, b in zip(pen[0], pen[0][1:])), pen[0]

    def test_multistep_single_host_iteration(self, model):
        """decode_steps=8 finishes an 8-token request in one engine.step()."""
        eng = InferenceEngine(model, max_batch_size=2, block_size=4, num_blocks=64,
                              max_blocks_per_seq=16, decode_steps=8)
        eng.add_request([5, 6, 7], SamplingParams(max_new_tokens=8))
        finished = eng.step()
        assert len(finished) == 1 and len(finished[0].output_ids) == 8
        assert not eng.has_work()


class TestPagedKernel:
    def test_kernel_matches_gather_path(self):
        from paddlenlp_tpu.ops.pallas.paged_attention import paged_decode_attention

        rng = np.random.default_rng(1)
        B, N, K, H, nb, bs, mb = 2, 4, 2, 64, 12, 8, 4
        q = jnp.asarray(rng.standard_normal((B, N, H)), jnp.float32)
        pk = jnp.asarray(rng.standard_normal((nb, K, bs, H)), jnp.float32)
        pv = jnp.asarray(rng.standard_normal((nb, K, bs, H)), jnp.float32)
        tables = jnp.asarray(rng.permutation(np.arange(1, nb))[: B * mb].reshape(B, mb), jnp.int32)
        ctx = jnp.asarray([7, 22], jnp.int32)
        out = paged_decode_attention(q, pk, pv, tables, ctx, interpret=True)

        def flat(pool):  # [nb,K,bs,H] gathered -> [B, mb*bs, K, H]
            return pool[tables].transpose(0, 1, 3, 2, 4).reshape(B, mb * bs, K, H)

        k_all = jnp.repeat(flat(pk), N // K, axis=2)
        v_all = jnp.repeat(flat(pv), N // K, axis=2)
        s = jnp.einsum("bnh,bsnh->bns", q, k_all) * H**-0.5
        mask = jnp.arange(mb * bs)[None, :] <= ctx[:, None]
        ref = jnp.einsum("bns,bsnh->bnh",
                         jax.nn.softmax(jnp.where(mask[:, None, :], s, -1e30), axis=-1), v_all)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_engine_parity_with_kernel(self, model):
        """Whole-engine greedy decode through the Pallas paged kernel (interpret)
        must equal the XLA gather path."""
        prompts = [[5, 6, 7, 8, 9], [40, 41, 42]]
        ref_eng = InferenceEngine(model, max_batch_size=2, block_size=4, num_blocks=64,
                                  max_blocks_per_seq=16)
        want = ref_eng.generate(prompts, SamplingParams(max_new_tokens=6))
        eng = InferenceEngine(model, max_batch_size=2, block_size=4, num_blocks=64,
                              max_blocks_per_seq=16)
        eng.infer.use_paged_kernel = True  # interpret mode on CPU
        got = eng.generate(prompts, SamplingParams(max_new_tokens=6))
        np.testing.assert_array_equal(want[0], got[0])
        np.testing.assert_array_equal(want[1], got[1])


class TestRaggedKernel:
    def _ref(self, q, pk, pv, tables, q_start, q_lens):
        """XLA reference: gather + per-row causal mask + zeroed dead rows."""
        B, T, N, H = q.shape
        nb, K, bs, _ = pk.shape
        mb = tables.shape[1]

        def flat(pool):
            return pool[tables].transpose(0, 1, 3, 2, 4).reshape(B, mb * bs, K, H)

        k_all = jnp.repeat(flat(pk), N // K, axis=2)
        v_all = jnp.repeat(flat(pv), N // K, axis=2)
        s = jnp.einsum("btnh,bsnh->bnts", q, k_all) * H**-0.5
        q_pos = q_start[:, None] + jnp.arange(T)[None, :]
        mask = jnp.arange(mb * bs)[None, None, :] <= q_pos[:, :, None]
        out = jnp.einsum("bnts,bsnh->btnh",
                         jax.nn.softmax(jnp.where(mask[:, None], s, -1e30), axis=-1),
                         v_all)
        live = jnp.arange(T)[None, :, None, None] < q_lens[:, None, None, None]
        return jnp.where(live, out, 0.0)

    def test_mixed_prefill_decode_rows(self):
        """One launch over a ragged batch: a mid-prompt chunk, a decode row
        (q_lens=1) and an inactive row (q_lens=0) against the same pool."""
        from paddlenlp_tpu.ops.pallas.paged_attention import ragged_paged_attention

        rng = np.random.default_rng(2)
        B, T, N, K, H, nb, bs, mb = 3, 8, 4, 2, 64, 16, 8, 5
        q = jnp.asarray(rng.standard_normal((B, T, N, H)), jnp.float32)
        pk = jnp.asarray(rng.standard_normal((nb, K, bs, H)), jnp.float32)
        pv = jnp.asarray(rng.standard_normal((nb, K, bs, H)), jnp.float32)
        tables = jnp.asarray(rng.permutation(np.arange(1, nb))[: B * mb].reshape(B, mb),
                             jnp.int32)
        q_start = jnp.asarray([9, 22, 0], jnp.int32)  # chunk @9, decode @22, dead
        q_lens = jnp.asarray([8, 1, 0], jnp.int32)
        out = ragged_paged_attention(q, pk, pv, tables, q_start, q_lens, interpret=True)
        ref = self._ref(q, pk, pv, tables, q_start, q_lens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
        assert np.all(np.asarray(out)[2] == 0.0)  # dead row is exact zeros
        assert np.all(np.asarray(out)[1, 1:] == 0.0)  # decode row padding zeroed

    def test_chunk_boundary_on_block_boundary(self):
        """q_start on an exact block boundary: the first kv block of the chunk
        is fully visible, later in-chunk positions unmask one column at a time."""
        from paddlenlp_tpu.ops.pallas.paged_attention import ragged_paged_attention

        rng = np.random.default_rng(3)
        B, T, N, K, H, nb, bs, mb = 1, 8, 2, 2, 64, 10, 8, 4
        q = jnp.asarray(rng.standard_normal((B, T, N, H)), jnp.float32)
        pk = jnp.asarray(rng.standard_normal((nb, K, bs, H)), jnp.float32)
        pv = jnp.asarray(rng.standard_normal((nb, K, bs, H)), jnp.float32)
        tables = jnp.asarray([[3, 7, 1, 5]], jnp.int32)
        q_start = jnp.asarray([8], jnp.int32)  # exactly one full block prefilled
        q_lens = jnp.asarray([8], jnp.int32)
        out = ragged_paged_attention(q, pk, pv, tables, q_start, q_lens, interpret=True)
        ref = self._ref(q, pk, pv, tables, q_start, q_lens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_decode_wrapper_matches_ragged(self):
        from paddlenlp_tpu.ops.pallas.paged_attention import (
            paged_decode_attention, ragged_paged_attention)

        rng = np.random.default_rng(4)
        B, N, K, H, nb, bs, mb = 2, 4, 2, 64, 12, 8, 4
        q = jnp.asarray(rng.standard_normal((B, N, H)), jnp.float32)
        pk = jnp.asarray(rng.standard_normal((nb, K, bs, H)), jnp.float32)
        pv = jnp.asarray(rng.standard_normal((nb, K, bs, H)), jnp.float32)
        tables = jnp.asarray(rng.permutation(np.arange(1, nb))[: B * mb].reshape(B, mb),
                             jnp.int32)
        ctx = jnp.asarray([7, 22], jnp.int32)
        a = paged_decode_attention(q, pk, pv, tables, ctx, interpret=True)
        b = ragged_paged_attention(q[:, None], pk, pv, tables, ctx,
                                   jnp.ones((B,), jnp.int32), interpret=True)[:, 0]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)


class TestPreemption:
    def test_preempt_and_recover(self, model):
        """Tiny pool forces preemption; the preempted request must still finish
        with identical output (recompute path)."""
        ref_eng = InferenceEngine(model, max_batch_size=2, block_size=4, num_blocks=128, max_blocks_per_seq=32)
        want = ref_eng.generate([[5, 6, 7], [40, 41, 42]], SamplingParams(max_new_tokens=10))

        # 9 usable blocks; two seqs decoding 10 tokens each will collide
        eng = InferenceEngine(model, max_batch_size=2, block_size=4, num_blocks=10, max_blocks_per_seq=32)
        got = eng.generate([[5, 6, 7], [40, 41, 42]], SamplingParams(max_new_tokens=10))
        np.testing.assert_array_equal(got[0], want[0])
        np.testing.assert_array_equal(got[1], want[1])


class TestQuantizedKVCache:
    def test_engine_parity_int8_and_fp8(self, model):
        """Quantized-cache greedy decode must stay close to the fp path
        (VERDICT r2 item 4: cosine > 0.99 on sampled logprob trajectories is
        approximated here by token-level agreement on short continuations +
        quantize/dequant cosine on the pool content)."""
        prompts = [[5, 6, 7, 8, 9], [40, 41, 42]]
        ref_eng = InferenceEngine(model, max_batch_size=2, block_size=8, num_blocks=64,
                                  max_blocks_per_seq=16)
        want = ref_eng.generate(prompts, SamplingParams(max_new_tokens=6))
        for quant in ("int8", "fp8"):
            eng = InferenceEngine(model, max_batch_size=2, block_size=8, num_blocks=64,
                                  max_blocks_per_seq=16, kv_cache_quant=quant)
            got = eng.generate(prompts, SamplingParams(max_new_tokens=6))
            assert len(got) == 2 and all(len(g) == 6 for g in got)
            # tiny random models have near-uniform logits; require agreement on
            # the first tokens (cache content identical at step 1) and finite IDs
            assert got[0][0] == want[0][0] and got[1][0] == want[1][0], (quant, got, want)

    def test_quantize_roundtrip_cosine(self):
        from paddlenlp_tpu.experimental.paged_cache import quantize_kv

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(32, 2, 64)), jnp.float32)
        for qd in (jnp.int8, jnp.float8_e4m3fn):
            q, s = quantize_kv(x, qd)
            deq = q.astype(jnp.float32) * s
            num = float(jnp.sum(x * deq))
            den = float(jnp.linalg.norm(x) * jnp.linalg.norm(deq))
            assert num / den > 0.99, (qd, num / den)

    def test_pool_memory_halved(self, model):
        from paddlenlp_tpu.experimental.paged_cache import init_paged_pool

        fp = init_paged_pool(model.config, num_blocks=32, block_size=8, dtype=jnp.bfloat16)
        q8 = init_paged_pool(model.config, num_blocks=32, block_size=8, quant="int8")
        fp_bytes = fp.kv.size * fp.kv.dtype.itemsize
        q_bytes = q8.kv.size * q8.kv.dtype.itemsize + q8.scale.size * q8.scale.dtype.itemsize
        # int8 payload is half of bf16; fp32 per-token scales add 4/(2H) overhead
        # (this tiny model's H=16 -> 0.625x; real models H>=128 -> ~0.52x)
        assert q_bytes <= 0.63 * fp_bytes, (q_bytes, fp_bytes)

    def test_paged_kernel_dequant_matches_gather(self):
        from paddlenlp_tpu.experimental.paged_cache import quantize_kv
        from paddlenlp_tpu.ops.pallas.paged_attention import paged_decode_attention

        rng = np.random.default_rng(3)
        B, N, K, H, nb, bs, mb = 2, 4, 2, 64, 12, 8, 4
        q = jnp.asarray(rng.standard_normal((B, N, H)), jnp.float32)
        pk = jnp.asarray(rng.standard_normal((nb, K, bs, H)), jnp.float32)
        pv = jnp.asarray(rng.standard_normal((nb, K, bs, H)), jnp.float32)
        pk_q, pk_s = quantize_kv(pk, jnp.int8)
        pv_q, pv_s = quantize_kv(pv, jnp.int8)
        tables = jnp.asarray(rng.permutation(np.arange(1, nb))[: B * mb].reshape(B, mb), jnp.int32)
        ctx = jnp.asarray([7, 22], jnp.int32)
        out = paged_decode_attention(q, pk_q, pv_q, tables, ctx, interpret=True,
                                     k_scale=pk_s, v_scale=pv_s)

        def flat(pool):
            return pool[tables].transpose(0, 1, 3, 2, 4).reshape(B, mb * bs, K, H)

        k_all = jnp.repeat(flat(pk_q.astype(jnp.float32) * pk_s), N // K, axis=2)
        v_all = jnp.repeat(flat(pv_q.astype(jnp.float32) * pv_s), N // K, axis=2)
        s = jnp.einsum("bnh,bsnh->bns", q, k_all) * H**-0.5
        mask = jnp.arange(mb * bs)[None, :] <= ctx[:, None]
        ref = jnp.einsum("bns,bsnh->bnh",
                         jax.nn.softmax(jnp.where(mask[:, None, :], s, -1e30), axis=-1), v_all)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestQuantizedServing:
    """Scan-layout quantized weights through the paged engine (VERDICT r3 #3:
    quantized serving must be reachable in the DEFAULT layout)."""

    def _engine_tokens(self, m, prompt, **kw):
        eng = InferenceEngine(m, max_batch_size=2, block_size=4, num_blocks=64,
                              max_blocks_per_seq=16, **kw)
        return eng.generate([prompt], SamplingParams(max_new_tokens=6))[0]

    @pytest.mark.parametrize("algo", ["wint8", "a8w8", "fp8"])
    def test_scan_quantized_engine_close_to_fp(self, model, algo):
        from paddlenlp_tpu.quantization import QuantizationConfig, QuantizedModel

        prompt = [5, 6, 7, 8, 9]
        ref = self._engine_tokens(model, prompt)
        qm = QuantizedModel(model, QuantizationConfig(weight_quantize_algo=algo))
        # stacked layout preserved: qweight leaves are [L, in, out]
        from paddlenlp_tpu.transformers.conversion_utils import flatten_params
        qflat = flatten_params(qm.params)
        assert any(p.endswith("/qweight") and v.ndim == 3 for p, v in qflat.items())
        got = self._engine_tokens(qm, prompt)
        # int8 on a tiny random model: most tokens agree with fp greedy
        agree = np.mean(np.asarray(ref) == np.asarray(got))
        assert agree >= 0.5, (ref, got)
        assert len(got) == len(ref)
