"""BlockManager accounting invariants: across any allocate/extend/shrink/free/
preempt interleaving, no block is leaked or double-owned and ``num_free`` is
conserved (free + owned == total)."""

import numpy as np
import pytest

from paddlenlp_tpu.experimental import BlockManager


def owned_blocks(mgr):
    out = []
    for blocks in mgr.tables.values():
        out.extend(blocks)
    return out


def check_conserved(mgr, total_usable):
    owned = owned_blocks(mgr)
    # sentinel block 0 is never handed out
    assert 0 not in owned and 0 not in mgr.free
    # no block owned twice, none both free and owned
    assert len(owned) == len(set(owned))
    assert not (set(owned) & set(mgr.free))
    assert len(mgr.free) + len(owned) == total_usable
    assert mgr.num_free == len(mgr.free)


class TestInvariants:
    def test_allocate_free_conserves(self):
        mgr = BlockManager(num_blocks=33, block_size=4, max_blocks_per_seq=16)
        total = mgr.total_usable_blocks
        mgr.allocate(1, 10)
        mgr.allocate(2, 1)
        check_conserved(mgr, total)
        mgr.free_seq(1)
        check_conserved(mgr, total)
        mgr.free_seq(2)
        check_conserved(mgr, total)
        assert mgr.num_free == total

    def test_extend_then_shrink_returns_blocks(self):
        mgr = BlockManager(num_blocks=17, block_size=4, max_blocks_per_seq=16)
        total = mgr.total_usable_blocks
        mgr.allocate(7, 4)  # 1 block
        assert mgr.extend(7, 12) is not None  # 16 tokens -> 4 blocks
        check_conserved(mgr, total)
        assert len(mgr.tables[7]) == 4
        mgr.shrink(7, 5)  # keep 2 blocks
        check_conserved(mgr, total)
        assert len(mgr.tables[7]) == 2 and mgr.lengths[7] == 5

    def test_shrink_keeps_at_least_one_block(self):
        mgr = BlockManager(num_blocks=9, block_size=4, max_blocks_per_seq=8)
        mgr.allocate(1, 8)
        mgr.shrink(1, 0)
        assert len(mgr.tables[1]) == 1  # a live sequence never loses its last block
        check_conserved(mgr, mgr.total_usable_blocks)

    def test_failed_extend_leaks_nothing(self):
        mgr = BlockManager(num_blocks=4, block_size=4, max_blocks_per_seq=8)
        total = mgr.total_usable_blocks
        mgr.allocate(1, 12)  # all 3 usable blocks
        before_len = mgr.lengths[1]
        assert mgr.extend(1, 8) is None  # OOM
        # a refused extend must not mutate length or ownership
        assert mgr.lengths[1] == before_len
        check_conserved(mgr, total)

    def test_over_cap_extend_refused(self):
        mgr = BlockManager(num_blocks=64, block_size=4, max_blocks_per_seq=2)
        mgr.allocate(1, 8)  # at the per-seq cap
        assert mgr.extend(1, 4) is None
        check_conserved(mgr, mgr.total_usable_blocks)

    def test_free_seq_idempotent_and_unknown(self):
        mgr = BlockManager(num_blocks=9, block_size=4, max_blocks_per_seq=8)
        total = mgr.total_usable_blocks
        mgr.allocate(3, 6)
        mgr.free_seq(3)
        mgr.free_seq(3)  # double-free: no-op
        mgr.free_seq(999)  # unknown id: no-op
        check_conserved(mgr, total)
        assert mgr.num_free == total

    def test_preempt_free_realloc_cycle(self):
        """The engine's preemption pattern: free the victim, re-admit later with
        a longer prompt — accounting must survive many cycles."""
        mgr = BlockManager(num_blocks=12, block_size=4, max_blocks_per_seq=8)
        total = mgr.total_usable_blocks
        rng = np.random.default_rng(0)
        live = {}
        next_id = 0
        for _ in range(300):
            op = rng.choice(["alloc", "extend", "shrink", "free"])
            if op == "alloc":
                n = int(rng.integers(1, 20))
                if mgr.can_allocate(n) and mgr.blocks_needed(n) <= mgr.max_blocks_per_seq:
                    mgr.allocate(next_id, n)
                    live[next_id] = n
                    next_id += 1
            elif op == "extend" and live:
                sid = int(rng.choice(list(live)))
                grew = mgr.extend(sid, int(rng.integers(1, 8)))
                if grew is not None:
                    live[sid] = mgr.lengths[sid]
            elif op == "shrink" and live:
                sid = int(rng.choice(list(live)))
                new_len = int(rng.integers(0, live[sid] + 1))
                mgr.shrink(sid, new_len)
                live[sid] = new_len
            elif op == "free" and live:
                sid = int(rng.choice(list(live)))
                mgr.free_seq(sid)
                del live[sid]
            check_conserved(mgr, total)
        for sid in list(live):
            mgr.free_seq(sid)
        assert mgr.num_free == total

    def test_table_array_matches_ownership(self):
        mgr = BlockManager(num_blocks=17, block_size=4, max_blocks_per_seq=6)
        mgr.allocate(1, 9)  # 3 blocks
        t = mgr.table_array(1)
        assert list(t[:3]) == mgr.tables[1]
        assert (t[3:] == 0).all()

    def test_allocate_raises_cleanly_when_oom(self):
        mgr = BlockManager(num_blocks=3, block_size=4, max_blocks_per_seq=8)
        mgr.allocate(1, 8)
        with pytest.raises(RuntimeError):
            mgr.allocate(2, 4)
        check_conserved(mgr, mgr.total_usable_blocks)
