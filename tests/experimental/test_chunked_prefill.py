"""Chunked prefill: mixed ragged prefill/decode steps must be token-identical
to monolithic prefill (greedy AND seeded sampling, with and without the prefix
cache), bound per-step prefill work, keep decode flowing while a long prompt
fills, and fold preempted half-prefilled requests correctly on re-admission.

The monolithic and chunked engines are module-scoped and REUSED across parity
tests (each fresh engine pays several jit compiles); every test uses distinct
prompts so runs stay independent — and any cross-test prefix-cache hit must
leave outputs identical anyway, which is exactly the property under test."""

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


KW = dict(max_batch_size=4, block_size=4, num_blocks=128, max_blocks_per_seq=32)
PROMPTS = [list(range(5, 30)), [40, 41, 42], list(range(50, 67))]


@pytest.fixture(scope="module")
def eng_mono(model):
    return InferenceEngine(model, **KW)


@pytest.fixture(scope="module")
def eng_chunk(model):
    return InferenceEngine(model, prefill_chunk_tokens=8, **KW)


@pytest.fixture(scope="module")
def eng_chunk4(model):
    """Shared chunk-budget-4 engine for the interleave/bounding tests; each
    test uses prompts with unique leading blocks so cross-test prefix-cache
    hits can't change the chunk walk under test."""
    return InferenceEngine(model, prefill_chunk_tokens=4, **KW)


class TestChunkedParity:
    def test_greedy_token_identical(self, eng_mono, eng_chunk):
        want = eng_mono.generate(PROMPTS, SamplingParams(max_new_tokens=8))
        c0 = dict(eng_chunk.chunk_stats)
        got = eng_chunk.generate(PROMPTS, SamplingParams(max_new_tokens=8))
        assert got == want
        # 25+3+17 prompt tokens in chunks of <=8 across several mixed steps
        assert eng_chunk.chunk_stats["chunk_tokens"] - c0["chunk_tokens"] \
            == sum(len(p) for p in PROMPTS)
        assert eng_chunk.chunk_stats["chunks"] - c0["chunks"] >= 7

    def test_seeded_sampling_token_identical(self, eng_mono, eng_chunk):
        prompts = [list(range(60, 85)), [33, 34, 35]]
        sp = SamplingParams(max_new_tokens=8, do_sample=True, temperature=0.8,
                            top_p=0.9, seed=11)
        assert eng_chunk.generate(prompts, sp) == eng_mono.generate(prompts, sp)

    def test_penalties_accumulate_across_chunks(self, eng_mono, eng_chunk):
        """Penalty counts must cover every earlier chunk of the prompt, not
        just the chunk that samples."""
        prompts = [list(range(20, 45)), [70, 71, 72, 73]]
        sp = SamplingParams(max_new_tokens=8, repetition_penalty=1.3,
                            presence_penalty=0.2, frequency_penalty=0.1)
        assert eng_chunk.generate(prompts, sp) == eng_mono.generate(prompts, sp)

    def test_prompt_smaller_than_chunk(self, eng_mono, model):
        eng = InferenceEngine(model, prefill_chunk_tokens=64, **KW)
        want = eng_mono.generate([[7, 8, 9]], SamplingParams(max_new_tokens=6))
        assert eng.generate([[7, 8, 9]], SamplingParams(max_new_tokens=6)) == want
        assert eng.chunk_stats["chunks"] == 1  # one (short) chunk, sampler fired

    def test_chunk_boundary_on_block_boundary(self, eng_mono, eng_chunk):
        """A chunk boundary landing exactly on a KV block boundary (chunk=8,
        block_size=4, prompt lengths 16 and 17) must not corrupt the walk."""
        prompts = [list(range(5, 21)), list(range(30, 47))]
        want = eng_mono.generate(prompts, SamplingParams(max_new_tokens=6))
        assert eng_chunk.generate(prompts, SamplingParams(max_new_tokens=6)) == want

    def test_chunked_with_ragged_kernel(self, eng_mono, model):
        """Whole-engine chunked decode through the Pallas ragged kernel
        (interpret) must equal the XLA gather path. Fresh engine: the kernel
        flag is read at trace time, so it cannot flip on a warm engine."""
        want = eng_mono.generate(PROMPTS, SamplingParams(max_new_tokens=6))
        eng = InferenceEngine(model, prefill_chunk_tokens=8, **KW)
        eng.infer.use_paged_kernel = True  # interpret mode on CPU
        assert eng.generate(PROMPTS, SamplingParams(max_new_tokens=6)) == want

    def test_prefix_cache_fed_suffix_chunked(self, model, eng_mono):
        """Warm admissions start chunking at the cached length; outputs match
        monolithic with the cache AND chunked without it. The chunked arms use
        fresh engines (the test asserts exact hit counts, so their caches must
        start empty); the monolithic arm rides the shared engine — a warm
        cache must not change its outputs, which is the invariant itself."""
        shared = list(range(5, 21))  # 16 tokens = 4 full blocks
        first = [shared + [50]]
        warm = [shared + [60, 61, 62]]
        eng_mono.generate(first, SamplingParams(max_new_tokens=4))
        results = {"mono_cache": eng_mono.generate(warm, SamplingParams(max_new_tokens=6))}
        for key, cache in (("chunk_cache", True), ("chunk_nocache", False)):
            eng = InferenceEngine(model, prefill_chunk_tokens=8,
                                  enable_prefix_cache=cache, **KW)
            eng.generate(first, SamplingParams(max_new_tokens=4))
            results[key] = eng.generate(warm, SamplingParams(max_new_tokens=6))
            if key == "chunk_cache":
                assert eng.mgr.cache_hits == 1  # the warm admission
                # the cached span never re-fed: only the suffix was chunked
                assert eng.chunk_stats["chunk_tokens"] < len(first[0]) + len(warm[0])
        assert results["chunk_cache"] == results["mono_cache"]
        assert results["chunk_nocache"] == results["mono_cache"]

    def test_per_step_prefill_bounded(self, eng_chunk4):
        """No engine step feeds more prompt tokens than the chunk budget."""
        eng = eng_chunk4
        eng.add_request(list(range(5, 35)), SamplingParams(max_new_tokens=2))
        fed_per_step = []
        while eng.has_work():
            before = eng.chunk_stats["chunk_tokens"]
            eng.step()
            fed_per_step.append(eng.chunk_stats["chunk_tokens"] - before)
        assert max(fed_per_step) <= 4
        assert sum(fed_per_step) == 30


class TestChunkedInterleave:
    def test_decode_flows_during_long_prefill(self, eng_mono, eng_chunk4):
        """The serving property itself: a running request keeps emitting
        tokens on the very steps a long prompt is chunk-prefilling."""
        want = eng_mono.generate([[5, 6, 7, 8]], SamplingParams(max_new_tokens=12))[0]

        eng = eng_chunk4
        stalls0 = len(eng.recent_decode_stalls)
        short = eng.add_request([5, 6, 7, 8], SamplingParams(max_new_tokens=12))
        done = list(eng.step())  # prefill chunk(s) + first token
        chunks0 = eng.chunk_stats["chunks"]  # long-prompt chunking not started
        eng.add_request(list(range(10, 40)), SamplingParams(max_new_tokens=4))
        interleaved = 0
        while eng.has_work():
            running = next((r for r in eng.slots
                            if r is not None and r.req_id == short), None)
            n_before = len(running.output_ids) if running is not None else None
            done += eng.step()
            if n_before is not None and len(running.output_ids) > n_before \
                    and eng.chunk_stats["chunks"] > chunks0:
                interleaved += 1
        res = {r.req_id: r.output_ids for r in done}
        assert res[short] == list(want)
        assert interleaved > 0  # decode advanced while the long prompt filled
        assert len(eng.recent_decode_stalls) > stalls0  # stall events recorded

    def test_preempt_half_prefilled_folds_state(self, model, eng_mono):
        """Pool pressure evicts the youngest slot mid-prefill; after requeue +
        re-admission the stream is token-exact and no KV block leaks. The
        reference run rides the shared monolithic engine — both requests fit
        its batch at once, so the outputs are batch-capacity-independent."""
        long_p = list(range(10, 34))  # 24 tokens
        want = eng_mono.generate([[5, 6, 7], long_p], SamplingParams(max_new_tokens=10))

        eng = InferenceEngine(model, prefill_chunk_tokens=4, max_batch_size=2,
                              block_size=4, num_blocks=11, max_blocks_per_seq=32)
        streams = {0: [], 1: []}
        eng.add_request([5, 6, 7], SamplingParams(max_new_tokens=10),
                        stream_cb=lambda t, d: streams[0].append(t))
        eng.add_request(long_p, SamplingParams(max_new_tokens=10),
                        stream_cb=lambda t, d: streams[1].append(t))
        while eng.has_work():
            eng.step()
        assert eng.num_preemptions > 0
        assert streams[0] == want[0]
        assert streams[1] == want[1]
        assert eng.mgr.num_free == eng.mgr.total_usable_blocks  # no leak

    def test_oldest_prefill_gets_budget_first(self, eng_chunk4):
        """A newly-admitted prompt landing in a lower slot index must not
        starve an older mid-prefill request: the chunk budget is handed out
        oldest-request-first, not in slot order."""
        eng = eng_chunk4
        eng.add_request([65, 66, 67], SamplingParams(max_new_tokens=2))  # slot 0
        eng.step()  # chunk + first token
        a = eng.add_request(list(range(36, 66)), SamplingParams(max_new_tokens=2))
        eng.step()  # A -> slot 1, first chunk; the short request finishes
        assert eng.slots[0] is None  # a free slot BELOW mid-prefill A
        b = eng.add_request(list(range(48, 78)), SamplingParams(max_new_tokens=2))
        eng.step()  # B admitted into slot 0, younger than A
        req_a = next(r for r in eng.slots if r is not None and r.req_id == a)
        req_b = next(r for r in eng.slots if r is not None and r.req_id == b)
        assert eng.slots.index(req_b) < eng.slots.index(req_a)
        assert req_a.prefilled_len == 8  # A drank the whole budget...
        assert req_b.prefilled_len == 0  # ...B waited its turn
        while eng.has_work():
            eng.step()

    def test_abort_mid_prefill_frees_blocks(self, eng_chunk4):
        eng = eng_chunk4
        rid = eng.add_request(list(range(2, 32)), SamplingParams(max_new_tokens=4))
        eng.step()  # admitted, one chunk in
        req = next(r for r in eng.slots if r is not None)
        assert req.needs_prefill and req.prefilled_len > 0
        out = eng.abort(rid)
        assert out is not None and out.finish_reason == "abort"
        assert eng.mgr.num_free == eng.mgr.total_usable_blocks
        assert not eng.has_work()


class TestChunkedMetrics:
    def test_serving_metrics_chunk_series(self, model):
        """ServingMetrics consumes the engine's chunk totals + event rings:
        chunks counter, chunk-size histogram, decode-stall histogram."""
        from paddlenlp_tpu.serving.engine_loop import ServingMetrics
        from paddlenlp_tpu.serving.metrics import MetricsRegistry

        registry = MetricsRegistry()
        eng = InferenceEngine(model, prefill_chunk_tokens=4, **KW)
        metrics = ServingMetrics(eng, registry=registry)
        eng.add_request([5, 6, 7, 8], SamplingParams(max_new_tokens=10))
        eng.step()
        eng.add_request(list(range(10, 30)), SamplingParams(max_new_tokens=2))
        while eng.has_work():
            pre = eng.num_preemptions
            eng.step()
            metrics.on_step(eng.stats(), eng.num_preemptions - pre)
        # deltas off monotone totals: the pre-on_step first step is swept up
        # by the next on_step, so the counter converges on the engine total
        chunks = metrics.prefill_chunks.value()
        assert chunks == eng.chunk_stats["chunks"]
        assert metrics.prefill_chunk_tokens.count() == chunks
        assert metrics.prefill_chunk_tokens.sum() == eng.chunk_stats["chunk_tokens"]
        assert metrics.decode_stall.count() == len(
            [1 for s, _ in eng.recent_decode_stalls])
        # re-running on_step with unchanged stats must not double-observe
        before = metrics.prefill_chunk_tokens.count()
        metrics.on_step(eng.stats(), 0)
        assert metrics.prefill_chunk_tokens.count() == before

        # rebind (the supervisor's rebuild path) must rebaseline, not replay
        registry2 = MetricsRegistry()
        metrics2 = ServingMetrics(eng, registry=registry2)
        metrics2.rebind(eng)
        metrics2.on_step(eng.stats(), 0)
        assert metrics2.prefill_chunks.value() == 0
        assert metrics2.prefill_chunk_tokens.count() == 0


class TestTokenFlattenedLayout:
    """The token-flattened mixed-step layout (PR 7 follow-up): decode rows no
    longer pad to the chunk bucket on the XLA fallback. It must be
    token-identical to the padded layout AND to monolithic prefill, and it is
    the auto default off-TPU (``token_flatten=None`` -> flat when the Pallas
    ragged kernel is inactive)."""

    def test_flat_is_auto_default_off_tpu(self, eng_chunk):
        assert not eng_chunk.infer.use_paged_kernel
        assert eng_chunk.backend.token_flatten is None  # auto -> flat

    def test_flat_vs_padded_token_identical(self, model, eng_chunk):
        """eng_chunk runs the flat layout (auto); a token_flatten=False twin
        runs the padded [B, chunk] launch — greedy + seeded sampling with
        penalties must agree row for row."""
        eng_pad = InferenceEngine(model, prefill_chunk_tokens=8,
                                  token_flatten=False, **KW)
        prompts = [list(range(8, 31)), [88, 89], list(range(61, 74))]
        for sp in (SamplingParams(max_new_tokens=7),
                   SamplingParams(max_new_tokens=7, do_sample=True, temperature=0.8,
                                  top_p=0.9, seed=3, repetition_penalty=1.2,
                                  presence_penalty=0.1, frequency_penalty=0.05)):
            assert eng_chunk.generate(prompts, sp) == eng_pad.generate(prompts, sp)

    def test_flat_preemption_parity(self, model):
        """Preemption pressure mid-prefill behaves identically under both
        layouts (the capacity pass is engine-side and layout-blind)."""
        kw = dict(max_batch_size=4, block_size=4, num_blocks=18, max_blocks_per_seq=32)
        prompts = [list(range(5, 25)), list(range(30, 50))]
        outs = {}
        for flat in (True, False):
            eng = InferenceEngine(model, prefill_chunk_tokens=8, token_flatten=flat, **kw)
            outs[flat] = eng.generate(prompts, SamplingParams(max_new_tokens=10))
        assert outs[True] == outs[False]

    def test_flat_feeds_fewer_padded_rows(self, eng_chunk):
        """The point of the layout: with one long prompt chunking while three
        short requests decode, the flat step's chunk segment holds 1 row, not
        max_batch_size — assert via the backend's segment shapes. Rides the
        shared chunk engine (the spy is restored); the long prompt's leading
        block is unique to this test so no cache hit shortens the chunk walk."""
        eng = eng_chunk
        seen = []
        orig = eng.backend._mixed_flat_launch

        def spy(chunk_rows, decode_rows):
            seen.append((len(chunk_rows), len(decode_rows)))
            return orig(chunk_rows, decode_rows)

        eng.backend._mixed_flat_launch = spy
        try:
            for p in ([40 + i] for i in range(3)):
                eng.add_request(list(p) + [7, 8], SamplingParams(max_new_tokens=24))
            for _ in range(3):
                eng.step()  # the shorties admit + start decoding
            eng.add_request(list(range(41, 73)), SamplingParams(max_new_tokens=4))
            for _ in range(4):
                eng.step()
            while eng.has_work():
                eng.step()
        finally:
            eng.backend._mixed_flat_launch = orig
        mixed = [s for s in seen if s[0] and s[1]]
        assert mixed, "no step carried chunks and decodes together"
        # every mixed step fed exactly the live rows: 1 chunk row + <=3 decodes
        assert all(c == 1 and 1 <= d <= 3 for c, d in mixed), mixed
