"""Sharded serving backend: token-identity + layout on a host-device CPU mesh.

``InferenceEngine(mesh_shape=...)`` lays weights and the paged KV pool out
with NamedSharding over the parallel/mesh ``tp`` axis and compiles every step
with explicit in/out shardings. The all-gather column-parallel layout makes
every floating-point reduction read replicated operands, so the sharded
engine must be BITWISE token-identical to the single-device one — greedy,
seeded sampling with penalties, with the prefix cache and chunked prefill on.
The conftest forces 8 virtual CPU devices, so the 8-way mesh runs in tier-1.

Engines are module-scoped and reused (each fresh engine pays several jit
compiles x 8 devices); tests use distinct prompts so runs stay independent —
and any cross-test prefix-cache hit must leave outputs identical anyway,
which is the property under test."""

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model(eight_devices):
    # 8 heads / 8 kv heads (head_dim 8): the tp=8 axis divides both, so the
    # KV pool and attention actually shard instead of falling back replicated
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=8,
                      max_position_embeddings=256, eos_token_id=None, pad_token_id=0,
                      use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


KW = dict(max_batch_size=4, block_size=4, num_blocks=128, max_blocks_per_seq=32,
          decode_steps=4)


@pytest.fixture(scope="module")
def eng_ref(model):
    return InferenceEngine(model, **KW)


@pytest.fixture(scope="module")
def eng_tp8(model):
    return InferenceEngine(model, mesh_shape=(1, 8), **KW)


@pytest.fixture(scope="module")
def eng_tp8_chunked(model):
    return InferenceEngine(model, mesh_shape=(1, 8), prefill_chunk_tokens=8, **KW)


class TestLayout:
    def test_kv_pool_sharded_on_tp(self, eng_tp8):
        spec = eng_tp8.pool.kv.sharding.spec
        assert tuple(spec) == (None, None, None, "tp", None, None)
        assert len(eng_tp8.pool.kv.devices()) == 8

    def test_params_sharded(self, eng_tp8):
        layers = eng_tp8.backend.params["model"]["layers"]
        q_spec = layers["self_attn"]["q_proj"]["kernel"].sharding.spec
        assert "tp" in tuple(q_spec), q_spec  # column-parallel heads
        emb = eng_tp8.backend.params["model"]["embed_tokens"]["embedding"]
        assert tuple(emb.sharding.spec)[0] == "tp"  # vocab rows sharded
        norm = layers["input_layernorm"]["scale"]
        assert all(s is None for s in tuple(norm.sharding.spec))  # replicated

    def test_jits_carry_explicit_shardings(self, eng_tp8):
        infer = eng_tp8.infer
        # the sharding trees the jits were compiled with are non-trivial
        assert infer.pool_shardings.kv.spec == P(None, None, None, "tp", None, None)
        import jax
        leaves = jax.tree.leaves(infer.param_shardings)
        assert any("tp" in tuple(ns.spec) for ns in leaves)

    def test_describe_and_stats(self, eng_tp8):
        desc = eng_tp8.stats()["backend"]
        assert desc["kind"] == "sharded"
        assert desc["tp_degree"] == 8 and desc["devices"] == 8
        assert desc["kv_pool_sharded"] is True

    def test_single_device_describe(self, eng_ref):
        desc = eng_ref.stats()["backend"]
        assert desc["kind"] == "single_device" and desc["tp_degree"] == 1


class TestTokenIdentity:
    def test_greedy(self, eng_ref, eng_tp8):
        prompts = [list(range(5, 30)), [40, 41, 42], list(range(50, 67))]
        want = eng_ref.generate(prompts, SamplingParams(max_new_tokens=8))
        got = eng_tp8.generate(prompts, SamplingParams(max_new_tokens=8))
        assert got == want

    def test_seeded_sampling_with_penalties(self, eng_ref, eng_tp8):
        sp = SamplingParams(max_new_tokens=8, do_sample=True, temperature=0.9,
                            top_p=0.8, top_k=12, seed=7, repetition_penalty=1.3,
                            presence_penalty=0.1, frequency_penalty=0.1)
        prompts = [[9, 8, 7, 6, 5], list(range(20, 41)), [60, 61]]
        want = eng_ref.generate(prompts, sp)
        got = eng_tp8.generate(prompts, sp)
        assert got == want

    def test_chunked_prefill_and_prefix_cache(self, eng_ref, eng_tp8_chunked):
        # two passes: the second hits the prefix cache (shared blocks + COW on
        # the exact repeat) while chunks interleave with decode — the full
        # feature matrix on the sharded pool
        prompts = [list(range(30, 55)), [70, 71, 72], list(range(10, 27))]
        want = eng_ref.generate(prompts, SamplingParams(max_new_tokens=8))
        got_cold = eng_tp8_chunked.generate(prompts, SamplingParams(max_new_tokens=8))
        assert got_cold == want
        hits0 = eng_tp8_chunked.mgr.cache_hits
        got_warm = eng_tp8_chunked.generate(prompts, SamplingParams(max_new_tokens=8))
        assert got_warm == want
        assert eng_tp8_chunked.mgr.cache_hits > hits0  # cache actually engaged
        # the jitted steps' out_shardings hold: after real prefill/mixed/decode
        # traffic (and COW copies) the pool is still laid out on tp
        assert tuple(eng_tp8_chunked.pool.kv.sharding.spec) == (
            None, None, None, "tp", None, None)

    def test_seeded_sampling_chunked(self, eng_ref, eng_tp8_chunked):
        sp = SamplingParams(max_new_tokens=6, do_sample=True, temperature=1.1,
                            top_p=0.9, seed=13)
        prompts = [list(range(33, 52)), [80, 81, 82, 83]]
        assert eng_tp8_chunked.generate(prompts, sp) == eng_ref.generate(prompts, sp)

    def test_dp_tp_mesh(self, model, eng_ref):
        eng = InferenceEngine(model, mesh_shape=(2, 4), **KW)
        assert eng.stats()["backend"]["mesh"]["dp"] == 2
        prompts = [[11, 12, 13, 14], list(range(44, 60))]
        want = eng_ref.generate(prompts, SamplingParams(max_new_tokens=6))
        assert eng.generate(prompts, SamplingParams(max_new_tokens=6)) == want

    def test_weight_update_resync(self, model, eng_ref, eng_tp8):
        """Rebinding model.params re-places them on the mesh (id check), and
        the updated sharded engine still matches the updated single-device
        one."""
        import jax

        old = model.params
        try:
            model.params = jax.tree.map(lambda x: x * 1.01, old)
            prompts = [[21, 22, 23]]
            want = eng_ref.generate(prompts, SamplingParams(max_new_tokens=6))
            got = eng_tp8.generate(prompts, SamplingParams(max_new_tokens=6))
            assert got == want
        finally:
            model.params = old


class TestRobustness:
    def test_preempt_and_abort_leak_free(self, model):
        """KV-pressure preemption and mid-flight aborts on the SHARDED pool
        release every block (the sharded pool tensor must never strand host
        allocator state)."""
        eng = InferenceEngine(model, mesh_shape=(1, 8), max_batch_size=2,
                              block_size=4, num_blocks=12, max_blocks_per_seq=16,
                              decode_steps=4, enable_prefix_cache=False)
        ids = [eng.add_request(list(range(5, 13)), SamplingParams(max_new_tokens=16))
               for _ in range(3)]
        for _ in range(3):
            eng.step()
        eng.abort(ids[1])
        while eng.has_work():
            eng.step()
        assert eng.mgr.num_free == eng.mgr.total_usable_blocks
        assert eng.num_preemptions >= 1  # pressure actually hit

    def test_reset_keeps_sharded_pool(self, model):
        eng = InferenceEngine(model, mesh_shape=(1, 8), **KW)
        pool_before = eng.pool.kv
        eng.add_request([5, 6, 7], SamplingParams(max_new_tokens=4))
        eng.step()
        eng.reset()
        # reset drops host state but keeps the device pool tensor (and its
        # sharding) — the supervisor's in-place recovery contract
        assert eng.pool.kv.sharding.spec == pool_before.sharding.spec
        out = eng.generate([[8, 9, 10]], SamplingParams(max_new_tokens=4))
        assert len(out[0]) == 4

    def test_insufficient_devices_raises(self, model):
        with pytest.raises(ValueError, match="devices"):
            InferenceEngine(model, mesh_shape=(4, 4), **KW)

    def test_gqa_indivisible_falls_back(self, eight_devices, eng_ref):
        """num_key_value_heads % tp != 0: pool replicates, outputs still
        token-identical (rules degrade, never crash)."""
        cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=256,
                          eos_token_id=None, pad_token_id=0, use_scan_layers=True)
        m = LlamaForCausalLM.from_config(cfg, seed=0)
        ref = InferenceEngine(m, **KW)
        eng = InferenceEngine(m, mesh_shape=(1, 8), **KW)
        assert eng.stats()["backend"]["kv_pool_sharded"] is False
        prompts = [[5, 6, 7, 8]]
        want = ref.generate(prompts, SamplingParams(max_new_tokens=6))
        assert eng.generate(prompts, SamplingParams(max_new_tokens=6)) == want
