"""Hierarchical KV cache: the host-RAM spill tier under the BlockManager.

The invariants pinned here (see ``kv_host_tier.py``'s module docstring):

(a) **token identity**: a prompt whose prefix was LRU-evicted to the host
    tier and promoted back streams bitwise-identical tokens to a never-
    evicted run — greedy and seeded sampling, monolithic and chunked
    prefill, tp=1 and tp=2;
(b) **resident-XOR + conservation**: under mixed finish/abort/churn a chain
    hash lives in the device index XOR the host tier, the BlockManager's
    free/cached/owned partition stays exact, and the tier's batch refcounts
    match its entry count — no leak in either tier, in either direction;
(c) **chaos degrades to the pre-tier behavior**: a fault on the spill path
    drops the batch (cold re-prefill later, nothing lost); a fault on the
    promote path falls back to cold prefill token-exactly with zero stream
    loss and no tier/device leak;
(d) **conversation lifetime**: a finished request's GENERATED blocks are
    registered alongside its prompt blocks, so a turn-2 prompt that threads
    turn 1's completion back re-prefills only the new suffix;
(e) **epoch invalidation**: ``clear_prefix_cache()`` empties the host tier
    with the device index (the weight-swap HTTP path is covered in
    tests/serving/test_weight_swap.py).
"""

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.experimental.kv_host_tier import HostKVTier
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS, InjectedFault

BS = 4
PREFIX = list(range(5, 21))  # 4 full blocks
GREEDY = SamplingParams(max_new_tokens=8)
SAMPLED = SamplingParams(max_new_tokens=8, do_sample=True, top_p=0.9, seed=7)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def _engine(model, **kw):
    """A SMALL device pool (so churn forces LRU eviction) over a roomy host
    tier — the configuration every spill/promote test needs."""
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("block_size", BS)
    kw.setdefault("num_blocks", 15)
    kw.setdefault("max_blocks_per_seq", 16)
    kw.setdefault("enable_prefix_cache", True)
    kw.setdefault("host_kv_blocks", 64)
    return InferenceEngine(model, **kw)


@pytest.fixture(scope="module")
def eng_host(model):
    return _engine(model)


@pytest.fixture(scope="module")
def eng_host_chunked(model):
    return _engine(model, prefill_chunk_tokens=8)


@pytest.fixture(scope="module")
def eng_host_tp2(model):
    return _engine(model, mesh_shape=(1, 2))


@pytest.fixture(scope="module")
def eng_off(model):
    """Ground truth: no cache, pool big enough that nothing is ever evicted."""
    return InferenceEngine(model, max_batch_size=2, block_size=BS,
                           num_blocks=64, max_blocks_per_seq=16,
                           enable_prefix_cache=False)


CHURN = [22 + i for i in range(44)]  # 11 blocks + decode: floods the pool


def tier_conserved(eng):
    """(b) resident-XOR between tiers + tier-internal batch refcounts +
    device-side block conservation."""
    mgr, tier = eng.mgr, eng._host_tier
    dev = set(mgr._index)
    host = set(tier._entries)
    assert not (dev & host), "chain hash resident in BOTH tiers"
    assert tier.num_blocks <= tier.max_blocks
    batches = {id(b): b for b, _row in tier._entries.values()}
    assert sum(b.live for b in batches.values()) == len(tier._entries)
    owned = {b for blocks in mgr.tables.values() for b in blocks}
    assert len(mgr.free) + len(mgr._lru) + len(owned) == mgr.total_usable_blocks


def spill_then_promote(eng, samp, warm_tail, target_tail):
    """Warm PREFIX into the device cache, churn it out to the host tier,
    then run a PREFIX-sharing prompt that must promote. Returns the target
    output and asserts the tier actually did the work."""
    eng.generate([PREFIX + warm_tail], samp)
    spills0 = eng._host_tier.stats["spills"]
    eng.generate([CHURN], SamplingParams(max_new_tokens=4))
    assert eng._host_tier.stats["spills"] > spills0, "churn never spilled"
    promotes0 = eng._host_tier.stats["promoted_blocks"]
    out = eng.generate([PREFIX + target_tail], samp)[0]
    assert eng._host_tier.stats["promoted_blocks"] >= promotes0 + 4, \
        "target prompt did not promote its evicted prefix"
    tier_conserved(eng)
    return out


class TestPromotedTokenIdentity:
    """(a) across engine geometries. Each case uses disjoint tail tokens so
    the shared module-scoped reference engine stays collision-free; the
    content-addressed caches make prefix overlap across cases harmless."""

    def test_greedy_and_sampled_monolithic(self, eng_host, eng_off):
        got = spill_then_promote(eng_host, GREEDY, [60, 61], [62, 63])
        eng_off.generate([PREFIX + [60, 61]], GREEDY)
        want = eng_off.generate([PREFIX + [62, 63]], GREEDY)[0]
        np.testing.assert_array_equal(got, want)
        got_s = spill_then_promote(eng_host, SAMPLED, [64, 65], [66, 67])
        eng_off.generate([PREFIX + [64, 65]], SAMPLED)
        want_s = eng_off.generate([PREFIX + [66, 67]], SAMPLED)[0]
        np.testing.assert_array_equal(got_s, want_s)

    def test_chunked_prefill(self, eng_host_chunked, eng_off):
        got = spill_then_promote(eng_host_chunked, GREEDY, [68, 69], [70, 71])
        eng_off.generate([PREFIX + [68, 69]], GREEDY)
        want = eng_off.generate([PREFIX + [70, 71]], GREEDY)[0]
        np.testing.assert_array_equal(got, want)

    def test_tp2(self, eng_host_tp2, eng_off):
        got = spill_then_promote(eng_host_tp2, GREEDY, [72, 73], [74, 75])
        eng_off.generate([PREFIX + [72, 73]], GREEDY)
        want = eng_off.generate([PREFIX + [74, 75]], GREEDY)[0]
        np.testing.assert_array_equal(got, want)

    def test_chunked_tp2(self, model, eng_off):
        eng = _engine(model, mesh_shape=(1, 2), prefill_chunk_tokens=8)
        got = spill_then_promote(eng, GREEDY, [88, 89], [90, 91])
        eng_off.generate([PREFIX + [88, 89]], GREEDY)
        want = eng_off.generate([PREFIX + [90, 91]], GREEDY)[0]
        np.testing.assert_array_equal(got, want)


class TestConversationLifetime:
    def test_generated_blocks_registered_and_reused(self, model, eng_off):
        """(d) turn 2 = turn 1's prompt + completion + new user tokens: the
        cached span covers the COMPLETION, not just the prompt."""
        eng = _engine(model, num_blocks=64)  # no eviction: isolates (d)
        p1 = [3] + PREFIX + [4]  # 18 tokens
        out1 = list(eng.generate([p1], GREEDY)[0])
        turn2 = p1 + out1 + [76, 77]
        cached0 = eng.mgr.cached_tokens_total
        out2 = eng.generate([turn2], GREEDY)[0]
        # prompt+completion = 26 tokens = 6 full blocks all served from cache
        assert eng.mgr.cached_tokens_total - cached0 >= \
            (len(p1) + len(out1)) // BS * BS
        eng_off.generate([p1], GREEDY)
        want = eng_off.generate([turn2], GREEDY)[0]
        np.testing.assert_array_equal(out2, want)

    def test_turn2_survives_eviction_via_host_tier(self, model, eng_off):
        """(a)+(d): the whole turn-1 history (prompt AND completion) comes
        back from the HOST tier after churn evicted it from the device."""
        eng = _engine(model)
        p1 = [3] + PREFIX + [4]
        out1 = list(eng.generate([p1], GREEDY)[0])
        # 52 + 4 tokens = ALL 14 usable blocks: every history block evicts
        eng.generate([[22 + i % 60 for i in range(52)]],
                     SamplingParams(max_new_tokens=4))
        promotes0 = eng._host_tier.stats["promoted_blocks"]
        turn2 = p1 + out1 + [78, 79]
        out2 = eng.generate([turn2], GREEDY)[0]
        assert eng._host_tier.stats["promoted_blocks"] >= promotes0 + 6
        eng_off.generate([p1], GREEDY)
        want = eng_off.generate([turn2], GREEDY)[0]
        np.testing.assert_array_equal(out2, want)
        tier_conserved(eng)


class TestChaos:
    """(c) both fault points from utils/faults.py CATALOG."""

    def test_spill_fault_drops_batch_no_leak(self, eng_host, eng_off):
        eng_host.generate([PREFIX + [80, 81]], GREEDY)
        FAULTS.arm("engine.kv_spill", times=1)
        blocks0 = eng_host._host_tier.num_blocks
        got = eng_host.generate([CHURN], SamplingParams(max_new_tokens=4))[0]
        assert FAULTS.fired("engine.kv_spill") == 1
        # the faulted batch is GONE (pre-tier behavior), nothing half-resident
        assert eng_host._host_tier.num_blocks <= blocks0 + len(CHURN) // BS
        want = eng_off.generate([CHURN], SamplingParams(max_new_tokens=4))[0]
        np.testing.assert_array_equal(got, want)
        tier_conserved(eng_host)

    def test_promote_fault_cold_prefill_token_exact(self, eng_host, eng_off):
        eng_host.generate([PREFIX + [82, 83]], GREEDY)
        eng_host.generate([CHURN], SamplingParams(max_new_tokens=4))
        assert eng_host._host_tier.num_blocks >= 4
        FAULTS.arm("engine.kv_promote", times=1)
        promotes0 = eng_host._host_tier.stats["promotes"]
        got = eng_host.generate([PREFIX + [84, 85]], GREEDY)[0]
        assert FAULTS.fired("engine.kv_promote") == 1
        # fallback recomputed the span cold: no promote happened, the fault
        # fired BEFORE take() so the entries stay tier-resident
        assert eng_host._host_tier.stats["promotes"] == promotes0
        eng_off.generate([PREFIX + [82, 83]], GREEDY)
        want = eng_off.generate([PREFIX + [84, 85]], GREEDY)[0]
        np.testing.assert_array_equal(got, want)
        tier_conserved(eng_host)


class TestEpochAndSurface:
    def test_clear_prefix_cache_empties_host_tier(self, model):
        """(e) the engine-level half of the weight-swap invalidation."""
        eng = _engine(model)
        eng.generate([PREFIX + [86, 87]], GREEDY)
        eng.generate([CHURN], SamplingParams(max_new_tokens=4))
        assert eng._host_tier.num_blocks > 0
        eng.clear_prefix_cache()
        assert eng._host_tier.num_blocks == 0
        assert eng.mgr.num_cached_blocks == 0
        # a post-clear repeat must not promote (nothing resident anywhere)
        promotes0 = eng._host_tier.stats["promotes"]
        eng.generate([PREFIX + [86, 87]], GREEDY)
        assert eng._host_tier.stats["promotes"] == promotes0
        tier_conserved(eng)

    def test_stats_surface(self, eng_host, model):
        host = eng_host.stats()["prefix_cache"]["host"]
        assert host["enabled"] and host["capacity"] == 64
        for k in ("blocks", "spills", "spill_batches", "promotes",
                  "promoted_blocks", "promote_bytes", "evictions",
                  "promotes_inflight"):
            assert k in host, k
        # tier off: same shape, zeros + enabled False
        off = InferenceEngine(model, max_batch_size=2, block_size=BS,
                              num_blocks=15, max_blocks_per_seq=16,
                              enable_prefix_cache=True)
        host_off = off.stats()["prefix_cache"]["host"]
        assert host_off["enabled"] is False and host_off["blocks"] == 0

    def test_host_tier_requires_prefix_cache(self, model):
        with pytest.raises(ValueError, match="enable_prefix_cache"):
            InferenceEngine(model, max_batch_size=2, block_size=BS,
                            num_blocks=15, max_blocks_per_seq=16,
                            enable_prefix_cache=False, host_kv_blocks=8)


class TestHostTierUnit:
    """Pure HostKVTier semantics, no engine: LRU under capacity pressure,
    re-spill dedup, take pops (resident-XOR half), clear, byte fidelity."""

    def _batch(self, n, seed):
        rng = np.random.default_rng(seed)
        return rng.standard_normal((2, 2, n, 2, BS, 8)).astype(np.float32)

    def test_put_take_roundtrip_bitwise(self):
        tier = HostKVTier(8, block_bytes=2 * 2 * 2 * BS * 8 * 4)
        kv = self._batch(3, 0)
        tier.put([b"a", b"b", b"c"], kv)
        got, scale, nbytes = tier.take([b"b", b"c"])
        np.testing.assert_array_equal(got, kv[:, :, 1:3])
        assert scale is None and nbytes == 2 * tier.block_bytes
        assert tier.num_blocks == 1 and not tier.contains(b"b")
        assert tier.stats["promotes"] == 1
        assert tier.stats["promoted_blocks"] == 2

    def test_lru_eviction_and_respill(self):
        tier = HostKVTier(3)
        tier.put([b"a", b"b"], self._batch(2, 1))
        tier.put([b"c", b"a"], self._batch(2, 2))  # re-spill of a: newest wins
        assert tier.num_blocks == 3 and tier.stats["evictions"] == 0
        tier.put([b"d"], self._batch(1, 3))  # capacity 3: oldest (b) evicted
        assert tier.stats["evictions"] == 1
        assert not tier.contains(b"b") and tier.contains(b"a")
        tier.clear()
        assert tier.num_blocks == 0

    def test_disabled_tier_accepts_nothing(self):
        tier = HostKVTier(0)
        assert not tier.accepting
        tier.put([b"a"], self._batch(1, 4))
        assert tier.num_blocks == 0
