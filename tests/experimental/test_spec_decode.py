"""Speculative decoding (prompt-lookup drafts + batched verify).

The reference accelerates decode with a speculative write path in its paged
attention ops (csrc/gpu/append_attn/ speculative decoding); here the drafts
come from an n-gram prompt-lookup proposer and are verified in ONE [B, K+1]
forward over the paged cache. Greedy outputs must be bit-identical with
speculation on/off, and repetitive prompts must accept enough drafts to beat
1.5 tokens per model forward.
"""

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=512,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def _engine(model, spec: bool, **kw):
    return InferenceEngine(model, max_batch_size=2, block_size=4, num_blocks=128,
                           max_blocks_per_seq=32, use_speculative=spec, **kw)


class TestSpeculative:
    def test_greedy_bit_identical(self, model):
        """Speculation must never change greedy outputs."""
        prompts = [[5, 6, 7, 8, 9, 5, 6, 7], [40, 41, 42, 43]]
        base = _engine(model, spec=False).generate(prompts, SamplingParams(max_new_tokens=24))
        spec = _engine(model, spec=True).generate(prompts, SamplingParams(max_new_tokens=24))
        for b, s in zip(base, spec):
            np.testing.assert_array_equal(b, s)

    def test_repetitive_prompt_speedup(self, model):
        """On a prompt whose continuation the model repeats, the n-gram
        proposer must push acceptance to >=1.5 tokens per verify forward."""
        eng = _engine(model, spec=True, spec_draft_len=8)
        # this seed's greedy continuation of [30]*12 is a constant stream —
        # once two generated n-grams repeat, prompt-lookup proposes the whole
        # draft window and verification accepts it in full
        prompt = [30] * 12
        out = eng.generate([prompt], SamplingParams(max_new_tokens=40))[0]
        assert len(out) == 40
        stats = eng.spec_stats
        assert stats["verify_steps"] > 0
        tokens_per_forward = stats["tokens_emitted"] / stats["verify_steps"]
        assert tokens_per_forward >= 1.5, stats
        # and the output still matches plain greedy
        ref = _engine(model, spec=False).generate([prompt], SamplingParams(max_new_tokens=40))[0]
        np.testing.assert_array_equal(ref, out)

    def test_sampling_requests_fall_back(self, model):
        """do_sample / penalty requests are ineligible: the engine silently
        uses the normal multi-step decode and must still match it exactly."""
        prompts = [[5, 6, 7, 8]]
        sp = SamplingParams(max_new_tokens=12, do_sample=True, seed=3, top_k=8)
        base = _engine(model, spec=False).generate(prompts, sp)
        eng = _engine(model, spec=True)
        spec = eng.generate(prompts, sp)
        np.testing.assert_array_equal(base[0], spec[0])
        assert eng.spec_stats["verify_steps"] == 0

    def test_preemption_under_pressure(self, model):
        """Speculative extension must preempt-and-recover exactly like decode
        when blocks run out (tiny pool forces it)."""
        eng = InferenceEngine(model, max_batch_size=2, block_size=4, num_blocks=14,
                              max_blocks_per_seq=16, use_speculative=True)
        prompts = [[5, 6, 7, 8, 5, 6, 7, 8], [40, 41, 42, 43, 40, 41, 42, 43]]
        outs = eng.generate(prompts, SamplingParams(max_new_tokens=12))
        ref = _engine(model, spec=False).generate(prompts, SamplingParams(max_new_tokens=12))
        for a, b in zip(outs, ref):
            np.testing.assert_array_equal(a, b)


@pytest.fixture(scope="module")
def draft_model():
    """A DIFFERENT (smaller) model than the target — drafts won't always match."""
    from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=96, hidden_size=32, intermediate_size=64, num_hidden_layers=1,
                      num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=512,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=7)


class TestDraftModelSpeculative:
    def test_greedy_bit_identical_with_draft_model(self, model, draft_model):
        """Draft-model proposals + greedy verify must never change outputs."""
        prompts = [[5, 6, 7, 8, 9, 5, 6, 7], [40, 41, 42, 43]]
        base = _engine(model, spec=False).generate(prompts, SamplingParams(max_new_tokens=16))
        eng = _engine(model, spec=False, draft_model=draft_model, spec_draft_len=4)
        spec = eng.generate(prompts, SamplingParams(max_new_tokens=16))
        for b, s in zip(base, spec):
            np.testing.assert_array_equal(b, s)
        assert eng.spec_stats["verify_steps"] > 0

    def test_self_draft_accepts_everything_greedy(self, model):
        """Target drafting for itself: greedy drafts always match the verify
        argmax, so acceptance must be 100%."""
        eng = _engine(model, spec=False, draft_model=model, spec_draft_len=4)
        out = eng.generate([[5, 6, 7, 8]], SamplingParams(max_new_tokens=12))[0]
        assert len(out) == 12
        s = eng.spec_stats
        # >= 0.95 rather than bit-exact: the drafts come from a separate
        # (unbatched, unpadded) forward of the same weights, so a near-tie in
        # the logits can argmax differently than the batched verify pass
        assert s["drafted"] > 0 and s["accepted"] / s["drafted"] >= 0.95, s

    def test_rejection_sampling_self_draft_full_acceptance(self, model):
        """Sampling mode with draft == target: p == q at every position, so the
        accept probability min(1, p/q) is 1 — every draft must be accepted and
        the emitted stream is an exact target-distribution sample."""
        eng = _engine(model, spec=False, draft_model=model, spec_draft_len=4)
        out = eng.generate([[5, 6, 7, 8]],
                           SamplingParams(max_new_tokens=12, do_sample=True, temperature=0.9,
                                          top_k=0, top_p=1.0))[0]
        assert len(out) == 12
        s = eng.spec_stats
        # p and q come from separate forwards of the same weights; accept
        # probability min(1, p/q) is 1 only up to float round-off, so bound
        # the acceptance ratio instead of demanding bit-exact equality
        assert s["drafted"] > 0 and s["accepted"] / s["drafted"] >= 0.95, s

    def test_rejection_sampling_different_draft_runs(self, model, draft_model):
        """Different draft: some rejections expected; stream must still complete
        and stats must record partial acceptance."""
        eng = _engine(model, spec=False, draft_model=draft_model, spec_draft_len=4)
        out = eng.generate([[5, 6, 7, 8], [40, 41, 42, 43]],
                           SamplingParams(max_new_tokens=16, do_sample=True, temperature=0.9,
                                          top_k=0, top_p=1.0))
        assert all(len(o) == 16 for o in out)
        s = eng.spec_stats
        assert s["verify_steps"] > 0 and s["drafted"] >= s["accepted"], s

    def test_topk_sampling_falls_back(self, model, draft_model):
        """top-k sampling is outside the rejection path — engine must fall back
        to normal decode (no verify steps) and still produce full streams."""
        eng = _engine(model, spec=False, draft_model=draft_model, spec_draft_len=4)
        out = eng.generate([[5, 6, 7, 8]],
                           SamplingParams(max_new_tokens=8, do_sample=True, top_k=5))[0]
        assert len(out) == 8
        assert eng.spec_stats["verify_steps"] == 0
