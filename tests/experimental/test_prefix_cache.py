"""Prefix KV-block caching: chained-hash full-block matching, refcounted
sharing, copy-on-write, LRU eviction under allocation pressure — and the
engine-level contract that cache-on output is token-identical to cache-off
while skipping the shared span's prefill.

Acceptance criteria covered here:
(a) cache-on vs cache-off outputs token-identical on a shared-prefix batch;
(b) a second request with a shared prefix skips >= the shared full-block token
    count of prefill (asserted via prefix_cache_cached_tokens_total);
(c) no KV-block leak after mixed finish/abort/preempt + eviction churn
    (free + idle-cached returns to total);
(d) eviction keeps admission behavior identical to the uncached allocator
    under pressure.
"""

import numpy as np
import pytest

from paddlenlp_tpu.experimental import BlockManager, InferenceEngine, SamplingParams
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM

BS = 4  # block size used throughout


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def mgr_conserved(mgr):
    """free + idle-cached + distinct-owned == total; no block in two states."""
    owned = {b for blocks in mgr.tables.values() for b in blocks}
    idle_cached = set(mgr._lru)
    assert 0 not in owned and 0 not in mgr.free and 0 not in idle_cached
    assert not (owned & set(mgr.free))
    assert not (idle_cached & set(mgr.free))
    assert not (owned & idle_cached)
    assert len(mgr.free) + len(idle_cached) + len(owned) == mgr.total_usable_blocks
    # every owned block carries a positive refcount; idle cached blocks none
    assert all(mgr.ref.get(b, 0) >= 1 for b in owned)
    assert all(b not in mgr.ref for b in idle_cached)


def _mgr(num_blocks=33, max_per_seq=16):
    return BlockManager(num_blocks=num_blocks, block_size=BS,
                        max_blocks_per_seq=max_per_seq, enable_prefix_cache=True)


class TestBlockManagerPrefixCache:
    def test_register_then_share_with_refcounts(self):
        mgr = _mgr()
        tokens = list(range(10, 22))  # 12 tokens = 3 full blocks
        shared, n_cached, new = mgr.allocate(1, 12, token_ids=tokens)
        assert (shared, n_cached) == ([], 0) and len(new) == 3  # cold cache
        seq1_blocks = list(mgr.tables[1])
        mgr.finish_seq_cached(1, tokens)
        assert mgr.num_cached_blocks == 3
        assert mgr.num_free == mgr.total_usable_blocks  # idle cached == capacity
        mgr_conserved(mgr)

        # identical prompt: full cover -> share all but the tail, COW the tail
        shared, n_cached, new = mgr.allocate(2, 12, token_ids=tokens)
        assert shared == seq1_blocks[:2]
        assert n_cached == 11  # one token left to prefill
        assert len(new) == 1
        pairs = mgr.drain_cow_pairs()
        assert pairs == [(seq1_blocks[2], new[0])]
        assert mgr.ref[seq1_blocks[0]] == 1 and mgr.ref[seq1_blocks[1]] == 1
        assert mgr.cache_hits == 1 and mgr.cached_tokens_total == 11
        mgr_conserved(mgr)

    def test_partial_and_divergent_match(self):
        mgr = _mgr()
        tokens = list(range(10, 22))
        mgr.allocate(1, 12, token_ids=tokens)
        mgr.finish_seq_cached(1, tokens)

        longer = tokens[:8] + [90, 91, 92, 93, 94]  # first 2 blocks shared
        shared, n_cached, new = mgr.allocate(2, len(longer), token_ids=longer)
        assert len(shared) == 2 and n_cached == 8
        assert not mgr.drain_cow_pairs()  # suffix starts in a fresh block

        divergent = [50] + tokens[1:]  # first block differs -> chain dead at 0
        shared, n_cached, _ = mgr.allocate(3, 12, token_ids=divergent)
        assert shared == [] and n_cached == 0
        mgr_conserved(mgr)

    def test_shrink_and_free_are_refcount_correct(self):
        mgr = _mgr()
        tokens = list(range(10, 22))
        mgr.allocate(1, 12, token_ids=tokens)
        mgr.finish_seq_cached(1, tokens)
        shared, _, new = mgr.allocate(2, 12, token_ids=tokens)
        mgr.drain_cow_pairs()
        # drop the private COW block: it must land on the free list
        mgr.shrink(2, 5)
        assert new[0] in mgr.free
        # drop a SHARED cached block: back to the idle (evictable) list
        mgr.shrink(2, 3)
        assert shared[1] in mgr._lru and shared[1] not in mgr.free
        mgr.free_seq(2)  # abort-style release: nothing unregistered
        assert mgr.num_cached_blocks == 3
        assert mgr.num_free == mgr.total_usable_blocks
        mgr_conserved(mgr)

    def test_lru_eviction_only_under_pressure(self):
        mgr = _mgr(num_blocks=7, max_per_seq=8)  # 6 usable
        a, b = list(range(10, 18)), list(range(30, 38))  # 2 blocks each
        mgr.allocate(1, 8, token_ids=a)
        mgr.finish_seq_cached(1, a)
        mgr.allocate(2, 8, token_ids=b)
        mgr.finish_seq_cached(2, b)
        assert mgr.num_cached_blocks == 4 and mgr.evictions == 0
        # idle cached blocks ARE capacity: a 24-token request still fits
        assert mgr.can_allocate(24)
        mgr.allocate(3, 24, token_ids=list(range(60, 84)))
        assert mgr.evictions == 4  # both cached prefixes recycled, LRU first
        assert mgr.num_cached_blocks == 0
        mgr_conserved(mgr)

    def test_admission_parity_with_uncached_allocator(self):
        """(d) a full cache never rejects an allocation the uncached allocator
        would have accepted."""
        cached = _mgr(num_blocks=9, max_per_seq=8)  # 8 usable
        plain = BlockManager(num_blocks=9, block_size=BS, max_blocks_per_seq=8)
        # fill the cache with two finished prompts (all 8 blocks cached, idle)
        for sid, lo in ((1, 10), (2, 40)):
            cached.allocate(sid, 16, token_ids=list(range(lo, lo + 16)))
            cached.finish_seq_cached(sid, list(range(lo, lo + 16)))
        assert cached.num_cached_blocks == 8
        for n in range(1, 40):
            assert cached.can_allocate(n) == plain.can_allocate(n), n
        # and the actual allocation succeeds by evicting
        cached.allocate(3, 32, token_ids=list(range(70, 102)))
        plain.allocate(3, 32)
        assert cached.num_free == plain.num_free
        mgr_conserved(cached)

    def test_idle_matched_blocks_not_double_counted(self):
        """A matched idle block can't be both 'no fresh capacity needed' and
        'evictable free capacity': can_admit must refuse exactly what
        allocate cannot satisfy (the uncached allocator would also refuse)."""
        mgr = _mgr(num_blocks=5, max_per_seq=8)  # 4 usable
        mgr.allocate(1, 4)  # one block privately held
        toks = list(range(10, 22))  # 3 full blocks
        mgr.allocate(2, 12, token_ids=toks)
        mgr.finish_seq_cached(2, toks)  # 3 idle cached; free list empty
        long = toks + list(range(90, 95))  # needs 5 blocks, matches the 3 cached
        assert not mgr.can_admit(len(long), token_ids=long)
        with pytest.raises(RuntimeError):
            mgr.allocate(3, len(long), token_ids=long)
        mgr_conserved(mgr)
        # uncached twin agrees: 4 usable - 1 held < 5 needed
        plain = BlockManager(num_blocks=5, block_size=BS, max_blocks_per_seq=8)
        plain.allocate(1, 4)
        assert not plain.can_allocate(len(long))

    def test_clear_prefix_cache_blocks_stale_registration(self):
        """A sequence allocated BEFORE clear_prefix_cache() holds KV computed
        under superseded params: it must release without re-registering, or
        the next match would serve stale KV the clear was meant to drop."""
        mgr = _mgr()
        tokens = list(range(10, 22))
        mgr.allocate(1, 12, token_ids=tokens)  # in flight across the clear
        mgr.clear_prefix_cache()
        mgr.finish_seq_cached(1, tokens)
        assert mgr.num_cached_blocks == 0
        assert mgr.match_prefix(tokens, 12) == ([], 0, None)
        assert mgr.num_free == mgr.total_usable_blocks
        mgr_conserved(mgr)
        # a post-clear sequence registers normally into the fresh index
        mgr.allocate(2, 12, token_ids=tokens)
        mgr.finish_seq_cached(2, tokens)
        assert mgr.num_cached_blocks == 3
        mgr_conserved(mgr)

    def test_copy_blocks_pads_without_corruption(self):
        """copy_blocks pads the pair list to a power of two with (0, 0)
        sentinel self-copies (bounded retraces): real copies land, block 0
        stays zero, untouched blocks stay put."""
        import jax.numpy as jnp

        from paddlenlp_tpu.experimental.paged_cache import PagedKVPool, copy_blocks

        kv = jnp.arange(2 * 2 * 6 * 1 * BS * 2, dtype=jnp.float32).reshape(2, 2, 6, 1, BS, 2)
        kv = kv.at[:, :, 0].set(0.0)  # zero sentinel
        before = np.asarray(kv)
        pool = copy_blocks(PagedKVPool(kv=kv), [(1, 4), (2, 5), (3, 1)])  # 3 -> pads to 4
        after = np.asarray(pool.kv)
        np.testing.assert_array_equal(after[:, :, 4], before[:, :, 1])
        np.testing.assert_array_equal(after[:, :, 5], before[:, :, 2])
        np.testing.assert_array_equal(after[:, :, 1], before[:, :, 3])
        np.testing.assert_array_equal(after[:, :, 0], 0.0)
        np.testing.assert_array_equal(after[:, :, 2], before[:, :, 2])
        np.testing.assert_array_equal(after[:, :, 3], before[:, :, 3])

    def test_mixed_churn_no_leak(self):
        """(c) randomized finish-cached / abort / shrink / eviction churn
        conserves every block."""
        rng = np.random.default_rng(0)
        mgr = _mgr(num_blocks=17, max_per_seq=8)
        prompts = [list(range(lo, lo + 12)) for lo in (10, 10, 30, 50)]  # dup on purpose
        live = {}
        next_id = 0
        for _ in range(400):
            op = rng.choice(["alloc", "finish", "abort", "shrink", "extend"])
            if op == "alloc":
                toks = prompts[int(rng.integers(len(prompts)))]
                if mgr.can_admit(len(toks), token_ids=toks):
                    mgr.allocate(next_id, len(toks), token_ids=toks)
                    mgr.drain_cow_pairs()
                    live[next_id] = toks
                    next_id += 1
            elif op == "finish" and live:
                sid = int(rng.choice(list(live)))
                mgr.finish_seq_cached(sid, live.pop(sid))
            elif op == "abort" and live:
                sid = int(rng.choice(list(live)))
                mgr.free_seq(sid)
                del live[sid]
            elif op == "shrink" and live:
                sid = int(rng.choice(list(live)))
                mgr.shrink(sid, int(rng.integers(1, mgr.lengths[sid] + 1)))
            elif op == "extend" and live:
                sid = int(rng.choice(list(live)))
                mgr.extend(sid, int(rng.integers(1, 6)))
            mgr_conserved(mgr)
        for sid in list(live):
            mgr.free_seq(sid)
        # free + cached count returns to total
        assert len(mgr.free) + len(mgr._lru) == mgr.total_usable_blocks


def _engine(model, cache: bool, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("block_size", BS)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_blocks_per_seq", 16)
    return InferenceEngine(model, enable_prefix_cache=cache, **kw)


# jit compiles dominate this suite's wall clock, so the standard-pool engines
# are module-scoped and shared; each test works in a DISJOINT token range, and
# the content-addressed cache keeps the ranges from ever colliding
@pytest.fixture(scope="module")
def eng_on(model):
    return _engine(model, cache=True)


@pytest.fixture(scope="module")
def eng_off(model):
    return _engine(model, cache=False)


PREFIX = [5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18, 19, 20]  # 4 full blocks


class TestEnginePrefixCache:
    def test_cache_on_off_token_identical_shared_prefix_batch(self, eng_on, eng_off):
        """(a) greedy + seeded sampling, warm cache vs no cache: identical."""
        first = [PREFIX + [60, 61]]
        batch = [PREFIX + [70, 71, 72],        # 4 cached blocks after warmup
                 PREFIX[:8] + [80, 81],        # 2 cached blocks
                 list(PREFIX)]                 # exact repeat -> COW tail
        samp = SamplingParams(max_new_tokens=8)
        samp_s = SamplingParams(max_new_tokens=8, do_sample=True, top_p=0.9, seed=7)

        warm_on = eng_on.generate(first, samp)
        got = eng_on.generate(batch, samp)
        got_s = eng_on.generate([PREFIX + [33]], samp_s)
        assert eng_on.mgr.cache_hits >= 3
        assert eng_on.mgr.cached_tokens_total >= 16 + 8 + 15

        warm_off = eng_off.generate(first, samp)
        want = eng_off.generate(batch, samp)
        want_s = eng_off.generate([PREFIX + [33]], samp_s)
        assert eng_off.mgr.cached_tokens_total == 0
        np.testing.assert_array_equal(warm_on[0], warm_off[0])
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)
        np.testing.assert_array_equal(got_s[0], want_s[0])

    def test_prefill_skip_counted_via_metric(self, eng_on):
        """(b) the shared full-block span lands in
        paddlenlp_serving_prefix_cache_cached_tokens_total."""
        from paddlenlp_tpu.serving.engine_loop import ServingMetrics
        from paddlenlp_tpu.serving.metrics import MetricsRegistry

        p2 = list(range(21, 37))  # 4 full blocks, disjoint from PREFIX
        registry = MetricsRegistry()
        metrics = ServingMetrics(eng_on, registry=registry)

        def run(prompts):
            for p in prompts:
                eng_on.add_request(p, SamplingParams(max_new_tokens=4))
            while eng_on.has_work():
                eng_on.step()
                metrics.on_step(eng_on.stats(), 0)  # what EngineLoop does per step

        run([p2 + [60, 61]])
        hits0 = metrics.prefix_hits.value()
        cached0 = metrics.prefix_cached_tokens.value()
        run([p2 + [70, 71]])  # shares 4 full blocks = 16 tokens
        assert metrics.prefix_cached_tokens.value() - cached0 >= 16
        assert metrics.prefix_hits.value() == hits0 + 1
        assert registry.get("paddlenlp_serving_kv_cached_blocks").value() \
            == eng_on.mgr.num_cached_blocks > 0

    def test_exact_repeat_cow_identical(self, eng_on, eng_off):
        p = list(range(40, 56))  # multiple of block size: full-cover COW path
        samp = SamplingParams(max_new_tokens=6)
        cached0 = eng_on.mgr.cached_tokens_total
        a = eng_on.generate([p], samp)
        b = eng_on.generate([p], samp)
        # the repeat skips all but the re-fed tail token
        assert eng_on.mgr.cached_tokens_total - cached0 == len(p) - 1
        wa = eng_off.generate([p], samp)
        wb = eng_off.generate([p], samp)
        np.testing.assert_array_equal(a[0], wa[0])
        np.testing.assert_array_equal(b[0], wb[0])

    def test_penalty_counts_cover_cached_span(self, eng_on, eng_off):
        """Repetition/presence penalties count the FULL prompt even when the
        cached span is never fed to prefill (suffix counted on device, cached
        span host-side): warm-cache output == cache-off output."""
        p = [88, 88, 88, 89, 89, 89, 89, 90]  # 2 full blocks, repetition-heavy
        samp = SamplingParams(max_new_tokens=8, repetition_penalty=5.0,
                              presence_penalty=1.0)
        eng_on.generate([p + [91, 92]], samp)  # warm the cache
        cached0 = eng_on.mgr.cached_tokens_total
        got = eng_on.generate([p + [93, 94]], samp)  # shares 2 full blocks
        assert eng_on.mgr.cached_tokens_total - cached0 == 8
        eng_off.generate([p + [91, 92]], samp)
        want = eng_off.generate([p + [93, 94]], samp)
        np.testing.assert_array_equal(got[0], want[0])

    def test_out_of_vocab_prompt_does_not_crash_step(self, eng_on):
        """Direct callers can feed ids outside the vocab; the penalty-count
        bincount must degrade (clip) rather than crash the engine step."""
        out = eng_on.generate([[200, 3, 7, 2, 6]], SamplingParams(max_new_tokens=2))
        assert len(out[0]) == 2

    def test_stats_surface_and_disable_flag(self, eng_on, eng_off):
        st = eng_on.stats()["prefix_cache"]  # warmed by the tests above
        assert st["enabled"] and st["hits"] >= 3
        assert st["cached_tokens"] >= 16 and st["cached_blocks"] >= 4
        st_off = eng_off.stats()["prefix_cache"]
        # the host-tier sub-dict is ALWAYS present (zeros when no tier is
        # attached) so the metrics plane reads one shape
        host_off = st_off.pop("host")
        assert host_off["enabled"] is False and host_off["blocks"] == 0
        assert st_off == {"enabled": False, "hits": 0, "cached_tokens": 0,
                          "evictions": 0, "cached_blocks": 0}

    def test_mixed_finish_abort_preempt_churn_no_leak(self, model):
        """(c) engine-level: finish + abort + forced preemption + eviction,
        then free + cached == total and no tables remain."""
        eng = _engine(model, cache=True, max_batch_size=2, num_blocks=14)
        samp = SamplingParams(max_new_tokens=8)
        # round 1: two shared-prefix requests under block pressure
        eng.generate([PREFIX[:8] + [60], PREFIX[:8] + [70]], samp)
        # round 2: abort one mid-flight
        rid = eng.add_request(PREFIX[:8] + [80], samp)
        eng.add_request(PREFIX[:8] + [90], samp)
        eng.step()
        eng.abort(rid)
        while eng.has_work():
            eng.step()
        # round 3: force eviction of the cached prefix with a long request
        eng.generate([[40 + i for i in range(44)]], SamplingParams(max_new_tokens=4))
        mgr = eng.mgr
        assert not mgr.tables
        assert len(mgr.free) + len(mgr._lru) == mgr.total_usable_blocks
        mgr_conserved(mgr)

    def test_eviction_pressure_output_parity(self, model):
        """(d) under a pool small enough to force eviction + preemption, the
        cached engine completes the same work with identical tokens."""
        samp = SamplingParams(max_new_tokens=8)
        rounds = [[PREFIX[:8] + [60], PREFIX[:8] + [61]],
                  [PREFIX[:8] + [62], [33, 34, 35, 36, 37, 38, 39, 40, 41]]]
        on = _engine(model, cache=True, max_batch_size=2, num_blocks=12)
        off = _engine(model, cache=False, max_batch_size=2, num_blocks=12)
        for prompts in rounds:
            got = on.generate(prompts, samp)
            want = off.generate(prompts, samp)
            for g, w in zip(got, want):
                np.testing.assert_array_equal(g, w)
        assert len(on.mgr.free) + len(on.mgr._lru) == on.mgr.total_usable_blocks
