"""Disaggregated prefill/decode backend: token identity + migration behavior.

``InferenceEngine(disagg_stages=(P, D))`` runs prompt work on a P-device
prefill stage and decode on a D-device decode stage with paged KV blocks
migrating between the stage pools. Each stage is a ShardedBackend (all-gather
layout), so the disagg engine must be BITWISE token-identical to the
single-device one — greedy, seeded sampling with penalties, and the chunked
× prefix-cache matrix. The conftest forces 8 virtual CPU devices.

Engines are module-scoped and reused aggressively (every fresh engine
compiles BOTH stages' jit sets): the identity engines run distinct prompts
per test, and the scheduling/robustness tests share one (1,1) engine whose
gating knobs are plain attributes saved/restored by the ``eng_11`` fixture —
each test drains fully, and any cross-test prefix-cache hit must leave
behavior identical anyway (the cached-block invariant under test elsewhere).
The module fixture is deliberately ASYMMETRIC (2 prefill devices, 1 decode)
so every identity test also exercises the in-flight tp-resharding migration
path."""

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model(eight_devices):
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=8,
                      max_position_embeddings=256, eos_token_id=None, pad_token_id=0,
                      use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


KW = dict(max_batch_size=4, block_size=4, num_blocks=128, max_blocks_per_seq=32,
          decode_steps=4)


@pytest.fixture(scope="module")
def eng_ref(model):
    return InferenceEngine(model, **KW)


@pytest.fixture(scope="module")
def eng_disagg(model):
    # asymmetric on purpose: prefill-heavy 2:1 — migration reshards across
    # different tp degrees in flight on every handoff
    return InferenceEngine(model, disagg_stages=(2, 1), **KW)


@pytest.fixture(scope="module")
def eng_disagg_chunked(model):
    return InferenceEngine(model, disagg_stages=(1, 1), prefill_chunk_tokens=8, **KW)


@pytest.fixture(scope="module")
def _eng_11(model):
    return InferenceEngine(model, disagg_stages=(1, 1), **KW)


@pytest.fixture
def eng_11(_eng_11):
    """The shared scheduling/robustness engine, with gating knobs restored
    after each test (they are plain attributes — the backend is untouched)."""
    saved = (_eng_11.migration_inflight_limit, _eng_11.decode_pressure_gate,
             _eng_11.prefill_pressure_gate)
    yield _eng_11
    (_eng_11.migration_inflight_limit, _eng_11.decode_pressure_gate,
     _eng_11.prefill_pressure_gate) = saved


class TestLayout:
    def test_describe_two_stages(self, eng_disagg):
        desc = eng_disagg.stats()["backend"]
        assert desc["kind"] == "disagg" and desc["devices"] == 3
        assert desc["stages"]["prefill"]["stage"] == "prefill"
        assert desc["stages"]["decode"]["stage"] == "decode"
        assert desc["mesh"] == {"prefill_tp": 2, "decode_tp": 1}

    def test_disjoint_device_groups_and_pools(self, eng_disagg):
        b = eng_disagg.backend
        p_devs = set(b.prefill_stage.pool.kv.devices())
        d_devs = set(b.decode_stage.pool.kv.devices())
        assert p_devs and d_devs and not (p_devs & d_devs)
        # one shared block-id space: both pools are full-size
        assert b.prefill_stage.pool.kv.shape == b.decode_stage.pool.kv.shape
        # each stage's pool is laid out on its own tp axis
        assert tuple(b.prefill_stage.pool.kv.sharding.spec) == (
            None, None, None, "tp", None, None)

    def test_insufficient_devices_raises(self, model):
        with pytest.raises(ValueError, match="devices"):
            InferenceEngine(model, disagg_stages=(8, 8), **KW)

    def test_bad_stage_spec_raises(self, model):
        with pytest.raises(ValueError, match="stages"):
            InferenceEngine(model, disagg_stages=(0, 2), **KW)

    def test_mesh_shape_and_disagg_mutually_exclusive(self, model):
        with pytest.raises(ValueError, match="mutually exclusive"):
            InferenceEngine(model, disagg_stages=(1, 1), mesh_shape=(1, 2), **KW)

    def test_stats_disagg_section(self, eng_disagg):
        dg = eng_disagg.stats()["disagg"]
        assert set(dg) >= {"prefill_stage", "decode_stage", "migrations",
                           "migrations_inflight", "migrations_pending"}
        for stage in ("prefill_stage", "decode_stage"):
            assert set(dg[stage]) == {"kv_blocks", "kv_utilization", "queue_depth"}


class TestTokenIdentity:
    def test_greedy(self, eng_ref, eng_disagg):
        prompts = [list(range(5, 30)), [40, 41, 42], list(range(50, 67))]
        want = eng_ref.generate(prompts, SamplingParams(max_new_tokens=8))
        got = eng_disagg.generate(prompts, SamplingParams(max_new_tokens=8))
        assert got == want
        # the handoff actually happened: one migration per sequence
        assert eng_disagg.backend.migration_stats["migrations"] >= 3

    def test_seeded_sampling_with_penalties(self, eng_ref, eng_disagg):
        sp = SamplingParams(max_new_tokens=8, do_sample=True, temperature=0.9,
                            top_p=0.8, top_k=12, seed=7, repetition_penalty=1.3,
                            presence_penalty=0.1, frequency_penalty=0.1)
        prompts = [[9, 8, 7, 6, 5], list(range(20, 41)), [60, 61]]
        want = eng_ref.generate(prompts, sp)
        got = eng_disagg.generate(prompts, sp)
        assert got == want

    def test_chunked_prefill_and_prefix_cache(self, eng_ref, eng_disagg_chunked):
        # chunk rows run on the prefill stage while decode rows flow on the
        # decode stage; the second pass hits the prefix cache (shared blocks
        # + COW on the exact repeat) whose blocks live in the PREFILL pool
        prompts = [list(range(30, 55)), [70, 71, 72], list(range(10, 27))]
        want = eng_ref.generate(prompts, SamplingParams(max_new_tokens=8))
        got_cold = eng_disagg_chunked.generate(prompts, SamplingParams(max_new_tokens=8))
        assert got_cold == want
        hits0 = eng_disagg_chunked.mgr.cache_hits
        got_warm = eng_disagg_chunked.generate(prompts, SamplingParams(max_new_tokens=8))
        assert got_warm == want
        assert eng_disagg_chunked.mgr.cache_hits > hits0  # cache actually engaged

    def test_seeded_sampling_chunked(self, eng_ref, eng_disagg_chunked):
        sp = SamplingParams(max_new_tokens=6, do_sample=True, temperature=1.1,
                            top_p=0.9, seed=13)
        prompts = [list(range(33, 52)), [80, 81, 82, 83]]
        assert eng_disagg_chunked.generate(prompts, sp) == eng_ref.generate(prompts, sp)


class TestMigrationScheduling:
    def test_decode_eligibility_gated_on_landing(self, eng_11):
        """After prefill the sequence is 'migrating' (no decode row) and only
        a later step's poll flips it to 'decode'."""
        eng = eng_11
        m0 = eng.backend.migration_stats["migrations"]
        eng.add_request([75, 76, 77, 78, 79], SamplingParams(max_new_tokens=6))
        eng.step()  # admit + prefill: first token sampled on the prefill stage
        req = next(r for r in eng.slots if r is not None)
        assert len(req.output_ids) == 1
        assert req.kv_stage == "migrating"
        assert eng._migrate_pending or eng._migrating
        while eng.has_work():
            eng.step()
        assert req.kv_stage == "decode"
        assert len(req.output_ids) == 6
        assert eng.backend.migration_stats["migrations"] == m0 + 1
        assert eng.mgr.num_free == eng.mgr.total_usable_blocks

    def test_migration_inflight_limit(self, eng_11):
        eng = eng_11
        eng.migration_inflight_limit = 1
        m0 = eng.backend.migration_stats["migrations"]
        for i in range(3):
            eng.add_request([61 + i, 2, 3, 4], SamplingParams(max_new_tokens=4))
        saw_pending = False
        while eng.has_work():
            eng.step()
            assert len(eng._migrating) <= 1
            saw_pending = saw_pending or len(eng._migrate_pending) > 0
        assert saw_pending  # the bound actually deferred a handoff
        assert eng.backend.migration_stats["migrations"] == m0 + 3

    def test_decode_pressure_defers_migration(self, eng_11):
        """decode_pressure_gate=0: while ANY decode-stage sequence holds
        blocks, new handoffs defer — and resume once it finishes."""
        eng = eng_11
        eng.decode_pressure_gate = 0.0
        m0 = eng.backend.migration_stats["migrations"]
        # A long enough to keep decoding for several steps (decode_steps=4),
        # so B's deferral window is observable — a short request could land
        # its migration AND finish inside one step
        a = eng.add_request([91, 92, 93], SamplingParams(max_new_tokens=13))
        while eng.has_work() and not any(
                r is not None and r.kv_stage == "decode" for r in eng.slots):
            eng.step()
        assert any(r is not None and r.req_id == a for r in eng.slots)
        b = eng.add_request([86, 87, 88, 89], SamplingParams(max_new_tokens=3))
        deferred = False
        while eng.has_work():
            eng.step()
            b_req = next((r for r in eng.slots
                          if r is not None and r.req_id == b), None)
            if (b_req is not None and b_req.kv_stage == "migrating"
                    and any(r is not None and r.req_id == a for r in eng.slots)):
                deferred = True  # B held back while A still decodes
        assert deferred
        assert eng.backend.migration_stats["migrations"] == m0 + 2
        assert eng.mgr.num_free == eng.mgr.total_usable_blocks

    def test_lone_request_admits_despite_gate(self, eng_11):
        """An IDLE prefill stage always admits: a single request whose
        reservation exceeds the gate fraction must run, not head-of-line
        block the queue forever (the gate throttles contention, it is not an
        absolute cap)."""
        eng = eng_11
        eng.prefill_pressure_gate = 0.01  # ~1 block: any prompt exceeds it
        out = eng.generate([list(range(11, 31))], SamplingParams(max_new_tokens=3))
        assert len(out[0]) == 3

    def test_prefill_pressure_gates_admission(self, eng_11):
        """Stage-aware admission: with a tight prefill gate only part of the
        queue admits per wave; everything still completes."""
        eng = eng_11
        eng.prefill_pressure_gate = 0.04  # ~5 of 127 blocks
        ids = [eng.add_request([55 + i, 6, 7, 8, 9, 10, 11, 12],
                               SamplingParams(max_new_tokens=3))
               for i in range(3)]
        eng.step()
        admitted = sum(1 for r in eng.slots if r is not None)
        assert admitted < 3  # the gate held some of the queue back
        out = {}
        while eng.has_work():
            for req in eng.step():
                out[req.req_id] = req
        assert sorted(out) == sorted(ids)
        assert all(len(out[i].output_ids) == 3 for i in ids)


class TestRobustness:
    def test_abort_mid_migration_leak_free(self, eng_11):
        eng = eng_11
        rid = eng.add_request([15, 16, 17, 18, 19], SamplingParams(max_new_tokens=8))
        eng.step()  # prefill done, request now migrating-pending
        req = next(r for r in eng.slots if r is not None)
        assert req.kv_stage == "migrating"
        assert eng.abort(rid) is not None
        assert not eng._migrating and not eng._migrate_pending
        assert eng.mgr.num_free == eng.mgr.total_usable_blocks

    def test_release_request_drops_migration(self, eng_11):
        eng = eng_11
        rid = eng.add_request([25, 26, 27, 28], SamplingParams(max_new_tokens=8))
        eng.step()
        assert eng.release_request(rid) is True
        assert not eng._migrating and not eng._migrate_pending
        assert eng.mgr.num_free == eng.mgr.total_usable_blocks

    def test_preempt_and_abort_leak_free(self, model):
        """KV-pressure preemption with the stage handoff in the loop releases
        every block (a preempted mid-migration request re-prefills and
        re-migrates on re-admission). Small pool: needs its own engine."""
        eng = InferenceEngine(model, disagg_stages=(1, 1), max_batch_size=2,
                              block_size=4, num_blocks=12, max_blocks_per_seq=16,
                              decode_steps=4, enable_prefix_cache=False)
        ids = [eng.add_request(list(range(5, 13)), SamplingParams(max_new_tokens=16))
               for _ in range(3)]
        # enough steps to ride past the 2-step migration latency so two
        # sequences actually decode concurrently and exhaust the pool
        for _ in range(5):
            eng.step()
        eng.abort(ids[1])
        while eng.has_work():
            eng.step()
        assert eng.mgr.num_free == eng.mgr.total_usable_blocks
        assert eng.num_preemptions >= 1  # pressure actually hit

    def test_single_device_engine_has_no_staging(self, eng_ref):
        assert eng_ref.staged is False
        assert "disagg" not in eng_ref.stats()
        out = eng_ref.generate([[77, 78]], SamplingParams(max_new_tokens=3))
        assert len(out[0]) == 3

    def test_reset_clears_migration_state(self, eng_11):
        # LAST on the shared engine on purpose: reset drops scheduler state
        eng = eng_11
        eng.add_request([35, 36, 37], SamplingParams(max_new_tokens=4))
        eng.step()
        eng.reset()
        assert not eng._migrating and not eng._migrate_pending
        out = eng.generate([[44, 45, 46]], SamplingParams(max_new_tokens=4))
        assert len(out[0]) == 4


class TestSyncParams:
    def test_two_stage_resync_keeps_layouts_and_counts(self, model, eng_disagg):
        """``DisaggBackend.sync_params`` (the weight-swap install seam):

        - both stage placements keep their EXISTING mesh/NamedSharding layout
          (no resharding, device groups stay disjoint);
        - both bindings move together — after the resync every launch runs on
          the new tree, and a penalty-sampling generation (whose logits READ
          the device-side counts through ``resync_counts``-seeded state) is
          token-exact against a fresh single-device engine built on the new
          weights, across the prefill->migrate->decode handoff."""
        import jax

        b = eng_disagg.backend
        old_params = model.params
        before = {}
        for name, stage in (("prefill", b.prefill_stage), ("decode", b.decode_stage)):
            leaves = jax.tree_util.tree_leaves(stage.params)
            before[name] = [leaf.sharding for leaf in leaves]

        new_model = type(model).from_config(model.config, seed=1)
        b.sync_params(new_model.params)
        try:
            for name, stage in (("prefill", b.prefill_stage),
                                ("decode", b.decode_stage)):
                leaves = jax.tree_util.tree_leaves(stage.params)
                assert len(leaves) == len(before[name])
                for leaf, old_sharding in zip(leaves, before[name]):
                    assert leaf.sharding == old_sharding, \
                        f"{name} stage resharded during sync_params"
            p_devs = set(b.prefill_stage.params and jax.tree_util.tree_leaves(
                b.prefill_stage.params)[0].devices())
            d_devs = set(jax.tree_util.tree_leaves(
                b.decode_stage.params)[0].devices())
            assert p_devs and d_devs and not (p_devs & d_devs)
            # the engine-level resync_counts contract survives the swap: a
            # no-op here (no live slots), then penalty decoding must match a
            # fresh engine on the new weights bit-for-bit
            eng_disagg.resync_counts()
            sp = SamplingParams(max_new_tokens=8, frequency_penalty=0.6)
            prompts = [[81, 82, 83, 84, 85]]
            ref = InferenceEngine(new_model, **KW)
            assert eng_disagg.generate(prompts, sp) == ref.generate(prompts, sp)
        finally:
            # the module model/engines are shared: restore the old binding
            b.sync_params(old_params)
        out = eng_disagg.generate([[86, 87, 88]], SamplingParams(max_new_tokens=4))
        assert len(out[0]) == 4
