"""Goodput-ledger conservation parity across every engine step path.

The invariant under test: ``fed == useful + padding + spec_rejected + rework``
holds EXACTLY on monolithic, chunked, token-flattened, padded-mixed, sharded
and disaggregated steps — and ``useful`` is identical across all of them for
the same greedy workload (token identity implies work identity; only the
padding/rework decomposition may differ per layout). Plus the rework
accounting: preemption recompute, supervisor-requeue hints, prefix-cache COW
tails and disagg migration re-seeds all land in their named buckets.

Engines are module-scoped and reused (compiles are the cost); tests use
distinct prompt streams so runs stay independent."""

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model(eight_devices):
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=8,
                      num_key_value_heads=8, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


KW = dict(max_batch_size=4, block_size=4, num_blocks=128, max_blocks_per_seq=32,
          decode_steps=4)


@pytest.fixture(scope="module")
def engines(model):
    return {
        "mono": InferenceEngine(model, **KW),
        "chunked": InferenceEngine(model, prefill_chunk_tokens=4, **KW),
        "flat": InferenceEngine(model, prefill_chunk_tokens=4,
                                token_flatten=True, **KW),
        "padded": InferenceEngine(model, prefill_chunk_tokens=4,
                                  token_flatten=False, **KW),
        "sharded": InferenceEngine(model, mesh_shape=(1, 2), **KW),
        "disagg": InferenceEngine(model, disagg_stages=(1, 1),
                                  prefill_chunk_tokens=4, **KW),
    }


def run(eng, prompts, max_new=6):
    led0 = dict(eng.ledger.totals)
    outs = eng.generate(prompts, SamplingParams(max_new_tokens=max_new))
    delta = {k: eng.ledger.totals[k] - led0[k] for k in led0}
    assert eng.ledger.verify_conservation()
    assert delta["fed"] == delta["useful"] + delta["padding"] \
        + delta["spec_rejected"] + delta["rework"]
    return outs, delta


class TestConservationParity:
    def test_useful_identical_across_all_step_paths(self, engines):
        # distinct leading block per engine family is NOT needed here: each
        # engine owns its BlockManager, so caches never cross engines
        prompts = [[11, 12, 13, 14, 15], [21, 22, 23], [31, 32, 33, 34, 35, 36, 37]]
        results = {name: run(eng, [list(p) for p in prompts])
                   for name, eng in engines.items()}
        outs0, delta0 = results["mono"]
        # greedy token identity across every backend/layout
        for name, (outs, _d) in results.items():
            assert outs == outs0, name
        # useful = prompt tokens + (emitted - 1) per request, exactly
        expect_useful = sum(len(p) for p in prompts) \
            + sum(len(o) - 1 for o in outs0)
        for name, (_outs, d) in results.items():
            if name == "disagg":
                # the migration re-seed re-processes prompt + first token per
                # sequence: pure rework on top of the same useful work
                assert d["useful"] == expect_useful, name
                assert d["rework"] == sum(len(p) + 1 for p in prompts)
            else:
                assert d["useful"] == expect_useful, name
                assert d["rework"] == 0, name
            assert d["spec_rejected"] == 0, name
            assert d["fed"] >= d["useful"], name

    def test_disagg_rework_is_migration_reseed(self, engines):
        eng = engines["disagg"]
        before = dict(eng.ledger.rework_by)
        run(eng, [[41, 42, 43, 44]])
        assert eng.ledger.rework_by["migration_reseed"] - before.get(
            "migration_reseed", 0) == 5  # 4 prompt + 1 emitted at handoff
        assert eng.ledger.rework_by.get("preempt_refill", 0) == before.get(
            "preempt_refill", 0)

    def test_shape_buckets_and_stats_surface(self, engines):
        eng = engines["mono"]
        run(eng, [[51, 52, 53]])
        snap = eng.stats()["goodput"]
        assert snap["shape_buckets"] >= 1
        assert snap["totals"] == dict(eng.ledger.totals)
        eff = eng.efficiency()
        assert eff["goodput_ratio"] == pytest.approx(eng.ledger.ratio())
        assert eff["mfu"] is None  # CPU: NaN -> null, never a fake number
        assert "step_anatomy" in eff and eff["step_anatomy"]["window_steps"] >= 1


class TestReworkAccounting:
    def test_preemption_books_preempt_refill(self, model):
        # tiny pool: decode growth forces preemption; the recompute re-prefill
        # of already-fed positions must land in rework, token-identically
        # (identity is asserted on the STREAMED tokens — a preempted request's
        # engine-side output_ids restart at the fold, the stream does not)
        def streamed_run(eng, prompts, max_new=8):
            streams = {}
            for p in prompts:
                toks = []
                rid = eng.add_request(list(p), SamplingParams(max_new_tokens=max_new),
                                      stream_cb=lambda t, d, _l=toks: _l.append(t))
                streams[rid] = toks
            while eng.has_work():
                eng.step()
            return [streams[r] for r in sorted(streams)]

        ref = InferenceEngine(model, **KW)
        tiny = InferenceEngine(model, max_batch_size=4, block_size=4,
                               num_blocks=8, max_blocks_per_seq=32,
                               decode_steps=4)
        prompts = [[61, 62, 63, 64], [71, 72, 73, 74], [81, 82, 83, 84]]
        outs_ref = streamed_run(ref, prompts)
        led0 = dict(tiny.ledger.totals)
        outs = streamed_run(tiny, prompts)
        delta = {k: tiny.ledger.totals[k] - led0[k] for k in led0}
        assert tiny.num_preemptions > 0
        # recompute identity: pre-preemption stream + resampled continuation
        # must equal the unconstrained run token for token
        assert outs == outs_ref
        assert tiny.ledger.verify_conservation()
        assert delta["rework"] > 0
        assert tiny.ledger.rework_by["preempt_refill"] == delta["rework"]
        # useful counts true work ONCE: the recompute's re-prefill of
        # already-fed positions is all rework, so useful equals the
        # no-preemption run's exactly (prompts + emits - 1 per request)
        base_useful = sum(len(p) for p in prompts) + sum(len(o) - 1 for o in outs)
        assert delta["useful"] == base_useful

    def test_requeue_hint_books_requeue_refill(self, model):
        eng = InferenceEngine(model, **KW)
        rid = eng.add_request([91, 92, 93, 94, 95],
                              SamplingParams(max_new_tokens=3), rework_hwm=4)
        while eng.has_work():
            eng.step()
        assert eng.ledger.rework_by["requeue_refill"] == 4
        assert eng.ledger.verify_conservation()
        assert rid >= 0

    def test_full_cover_cow_books_cow_token(self, model):
        eng = InferenceEngine(model, **KW)
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]  # exactly 2 full blocks at bs=4
        run(eng, [list(prompt)])  # registers the prompt's full blocks
        before = eng.ledger.rework_by.get("cow_token", 0)
        _outs, delta = run(eng, [list(prompt)])  # full-cover hit -> COW tail
        assert eng.ledger.rework_by.get("cow_token", 0) - before == 1
        assert delta["rework"] == 1
        assert delta["useful"] == 0 + (len(_outs[0]) - 1)  # suffix was all COW


class TestSpeculative:
    def test_spec_rejected_matches_engine_stats(self, model):
        eng = InferenceEngine(model, use_speculative=True, spec_draft_len=3,
                              spec_ngram=2, **KW)
        # constant prompt: the model repeats, the n-gram proposer drafts,
        # greedy verify accepts some and rejects the rest — the ledger's
        # spec_rejected bucket must equal the engine's drafted - accepted
        prompt = [30] * 12
        _outs, delta = run(eng, [prompt], max_new=24)
        st = eng.spec_stats
        assert st["drafted"] > 0
        assert delta["spec_rejected"] == st["drafted"] - st["accepted"]
        assert eng.ledger.verify_conservation()


class TestChaosConservation:
    def test_conservation_across_engine_step_fault_and_reset(self, model):
        # a mid-run step fault + in-place reset must leave the ledger's
        # monotone totals conserved (reset keeps them, like chunk_stats)
        from paddlenlp_tpu.utils.faults import FAULTS

        eng = InferenceEngine(model, **KW)
        eng.add_request([15, 16, 17], SamplingParams(max_new_tokens=6))
        eng.step()  # prefill lands
        FAULTS.arm("engine.step", nth=1)
        try:
            with pytest.raises(Exception):
                while eng.has_work():
                    eng.step()
        finally:
            FAULTS.disarm("engine.step")
        totals_mid = dict(eng.ledger.totals)
        assert eng.ledger.verify_conservation()
        eng.reset()
        assert eng.ledger.totals == totals_mid  # reset never rewinds totals
        # the anatomy anchors must reset too, or the first post-recovery step
        # books the whole outage (triage + reset) as a "step gap"
        assert eng._last_step_end is None and eng._prev_step_busy is False
        _outs, delta = run(eng, [[25, 26, 27]])
        assert delta["useful"] > 0
        assert eng.ledger.verify_conservation()
