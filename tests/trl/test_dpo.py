"""DPO / RM tests: criterion math, trainer learns a preference, entry point runs."""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlenlp_tpu.trainer import TrainingArguments
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM, LlamaForSequenceClassification
from paddlenlp_tpu.trl import DPOCriterion, DPOTrainer, RewardTrainer, sequence_logps


def tiny_model(seed=0, cls=LlamaForCausalLM, **kw):
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=64, **kw)
    return cls.from_config(cfg, seed=seed)


class TestCriterion:
    def test_sequence_logps_masks_prompt(self):
        logits = jnp.zeros((1, 4, 8))  # uniform -> logp = -log(8) per token
        labels = jnp.asarray([[-100, 1, 2, -100]])
        lp = sequence_logps(logits, labels)
        np.testing.assert_allclose(float(lp[0]), -2 * np.log(8), rtol=1e-5)

    def test_sigmoid_loss_prefers_chosen(self):
        crit = DPOCriterion(beta=0.1, loss_type="sigmoid")
        good = crit(jnp.asarray([-5.0]), jnp.asarray([-10.0]), jnp.asarray([-7.0]), jnp.asarray([-7.0]))[0]
        bad = crit(jnp.asarray([-10.0]), jnp.asarray([-5.0]), jnp.asarray([-7.0]), jnp.asarray([-7.0]))[0]
        assert float(good) < float(bad)

    @pytest.mark.parametrize("loss_type", ["sigmoid", "hinge", "ipo", "kto_pair"])
    def test_ref_losses_finite(self, loss_type):
        crit = DPOCriterion(loss_type=loss_type)
        loss, metrics = crit(jnp.asarray([-4.0, -6.0]), jnp.asarray([-5.0, -5.5]),
                             jnp.asarray([-5.0, -6.0]), jnp.asarray([-5.0, -6.0]))
        assert np.isfinite(float(loss))
        assert 0.0 <= float(metrics["rewards_accuracy"]) <= 1.0

    def test_kto_pair_kl_direction(self):
        """KL baselines are clip(mean(policy - reference), 0): with the policy
        drifted up on chosen only, chosen_kl > 0 must pull the rejected term's
        sigmoid argument positive, so mean loss dips below the 0.5 fixed point
        (the old sign-flipped form left it exactly at 0.5)."""
        crit = DPOCriterion(beta=1.0, loss_type="kto_pair")
        at_ref, _ = crit(jnp.asarray([-5.0]), jnp.asarray([-5.0]), jnp.asarray([-5.0]), jnp.asarray([-5.0]))
        np.testing.assert_allclose(float(at_ref), 0.5, rtol=1e-6)
        drifted, _ = crit(jnp.asarray([-3.0]), jnp.asarray([-5.0]), jnp.asarray([-5.0]), jnp.asarray([-5.0]))
        assert float(drifted) < 0.5 - 1e-3, float(drifted)

    @pytest.mark.parametrize("loss_type", ["simpo", "orpo"])
    def test_ref_free_losses(self, loss_type):
        crit = DPOCriterion(loss_type=loss_type)
        assert not crit.needs_reference
        loss, _ = crit(jnp.asarray([-4.0]), jnp.asarray([-6.0]), None, None,
                       jnp.asarray([10]), jnp.asarray([12]))
        assert np.isfinite(float(loss))


def make_pref_dataset(n=32, seq=12):
    """chosen continuations use token 7, rejected use token 9 — learnable."""
    rng = np.random.default_rng(0)

    class DS:
        def __len__(self):
            return n

        def __getitem__(self, i):
            prompt = rng.integers(20, 40, size=4).astype(np.int32)

            def row(tok):
                resp = np.full(seq - 4, tok, dtype=np.int32)
                ids = np.concatenate([prompt, resp])
                labels = np.concatenate([np.full(4, -100, np.int32), resp])
                return ids, labels

            ci, cl = row(7)
            ri, rl = row(9)
            return {"chosen_input_ids": ci, "chosen_labels": cl,
                    "rejected_input_ids": ri, "rejected_labels": rl}

    return DS()


class TestDPOTrainer:
    def test_dpo_learns_preference(self, tmp_path):
        model = tiny_model()
        args = TrainingArguments(output_dir=str(tmp_path), max_steps=8, per_device_train_batch_size=2,
                                 learning_rate=5e-4, logging_steps=4, save_strategy="no")
        trainer = DPOTrainer(model=model, args=args, train_dataset=make_pref_dataset(), beta=0.5)
        out = trainer.train()
        assert np.isfinite(out.training_loss)
        # after training, p(chosen token) should beat p(rejected token)
        ids = jnp.asarray([[25, 30, 22, 35]], jnp.int32)
        logits = trainer.model.apply(trainer.train_state.params, input_ids=ids).logits
        last = np.asarray(logits[0, -1])
        assert last[7] > last[9], (last[7], last[9])

    def test_simpo_no_reference(self, tmp_path):
        model = tiny_model()
        args = TrainingArguments(output_dir=str(tmp_path), max_steps=3, per_device_train_batch_size=2,
                                 learning_rate=5e-4, save_strategy="no")
        trainer = DPOTrainer(model=model, args=args, train_dataset=make_pref_dataset(),
                             loss_type="simpo")
        assert trainer.ref_params is None
        out = trainer.train()
        assert np.isfinite(out.training_loss)


class TestRewardTrainer:
    def test_rm_learns_ranking(self, tmp_path):
        model = tiny_model(cls=LlamaForSequenceClassification, num_labels=1)
        rng = np.random.default_rng(0)

        class DS:
            def __len__(self):
                return 32

            def __getitem__(self, i):
                base = rng.integers(20, 40, size=8).astype(np.int32)
                chosen = np.concatenate([base, [7, 7]]).astype(np.int32)
                rejected = np.concatenate([base, [9, 9]]).astype(np.int32)
                return {"chosen_input_ids": chosen, "rejected_input_ids": rejected}

        args = TrainingArguments(output_dir=str(tmp_path), max_steps=8, per_device_train_batch_size=2,
                                 learning_rate=1e-3, logging_steps=4, save_strategy="no")
        trainer = RewardTrainer(model=model, args=args, train_dataset=DS())
        out = trainer.train()
        assert np.isfinite(out.training_loss)
        chosen = jnp.asarray([np.concatenate([np.arange(20, 28), [7, 7]])], jnp.int32)
        rejected = jnp.asarray([np.concatenate([np.arange(20, 28), [9, 9]])], jnp.int32)
        rc = float(trainer.model.apply(trainer.train_state.params, input_ids=chosen).logits[0, 0])
        rr = float(trainer.model.apply(trainer.train_state.params, input_ids=rejected).logits[0, 0])
        assert rc > rr, (rc, rr)


class TestRunDPO:
    def test_entry_point(self, tmp_path, monkeypatch):
        repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        sys.path.insert(0, os.path.join(repo, "llm", "alignment", "dpo"))
        import run_dpo

        from tokenizers import Tokenizer
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        from paddlenlp_tpu.transformers import PretrainedTokenizer

        model_dir = tmp_path / "model"
        tiny_model().save_pretrained(str(model_dir))
        vocab = {"<pad>": 0, "<s>": 1, "</s>": 2, "<unk>": 3}
        for i, w in enumerate("yes no maybe good bad fine great awful ok sure".split()):
            vocab[w] = i + 4
        t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
        t.pre_tokenizer = Whitespace()
        PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", eos_token="</s>",
                            unk_token="<unk>").save_pretrained(str(model_dir))
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        with open(data_dir / "train.json", "w") as f:
            for _ in range(16):
                f.write(json.dumps({"src": "maybe ok", "chosen": "good great", "rejected": "bad awful"}) + "\n")
        cfg = {
            "model_name_or_path": str(model_dir),
            "dataset_name_or_path": str(data_dir),
            "output_dir": str(tmp_path / "out"),
            "max_length": 16,
            "max_prompt_length": 8,
            "per_device_train_batch_size": 1,
            "max_steps": 2,
            "save_strategy": "no",
            "do_train": True,
            "dtype": "float32",
        }
        p = tmp_path / "dpo.json"
        p.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_dpo.py", str(p)])
        trainer = run_dpo.main()
        assert trainer.state.global_step == 2


def _tiny_tokenizer_dir(tmp_path, model):
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    from paddlenlp_tpu.transformers import PretrainedTokenizer

    model_dir = tmp_path / "model"
    model.save_pretrained(str(model_dir))
    vocab = {"<pad>": 0, "<s>": 1, "</s>": 2, "<unk>": 3}
    for i, w in enumerate("yes no maybe good bad fine great awful ok sure".split()):
        vocab[w] = i + 4
    t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    t.pre_tokenizer = Whitespace()
    PretrainedTokenizer(tokenizer_object=t, pad_token="<pad>", eos_token="</s>",
                        unk_token="<unk>").save_pretrained(str(model_dir))
    return model_dir


class TestRunRMAndPPO:
    def test_rm_then_ppo_entry_points(self, tmp_path, monkeypatch):
        """run_rm.py trains a reward model; run_ppo.py consumes it — the
        reference's rm -> ppo pipeline (llm/alignment/{rm,ppo}/run_*.py)."""
        repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        sys.path.insert(0, os.path.join(repo, "llm", "alignment", "rm"))
        sys.path.insert(0, os.path.join(repo, "llm", "alignment", "ppo"))
        import run_ppo
        import run_rm

        model_dir = _tiny_tokenizer_dir(tmp_path, tiny_model(use_scan_layers=True))
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        with open(data_dir / "train.json", "w") as f:
            for _ in range(16):
                f.write(json.dumps({"src": "maybe ok", "chosen": "good great", "rejected": "bad awful"}) + "\n")
        rm_out = tmp_path / "rm_out"
        cfg = {
            "model_name_or_path": str(model_dir),
            "dataset_name_or_path": str(data_dir),
            "output_dir": str(rm_out),
            "max_length": 16,
            "max_prompt_length": 8,
            "per_device_train_batch_size": 1,
            "gradient_accumulation_steps": 1,
            "max_steps": 2,
            "save_strategy": "no",
            "do_train": True,
            "dtype": "float32",
        }
        p = tmp_path / "rm.json"
        p.write_text(json.dumps(cfg))
        monkeypatch.setattr(sys, "argv", ["run_rm.py", str(p)])
        rm_trainer = run_rm.main()
        assert rm_trainer.state.global_step == 2

        ppo_cfg = {
            "model_name_or_path": str(model_dir),
            "reward_model_name_or_path": str(rm_out),
            "dataset_name_or_path": str(data_dir),
            "output_dir": str(tmp_path / "ppo_out"),
            "max_prompt_length": 8,
            "max_new_tokens": 4,
            "num_rollouts_per_prompt": 2,
            "per_device_train_batch_size": 1,
            "max_steps": 2,
            "save_strategy": "no",
            "do_train": True,
            "dtype": "float32",
            "use_value_model": True,
        }
        p2 = tmp_path / "ppo.json"
        p2.write_text(json.dumps(ppo_cfg))
        monkeypatch.setattr(sys, "argv", ["run_ppo.py", str(p2)])
        ppo_trainer = run_ppo.main()
        assert ppo_trainer.state.global_step == 2


class TestPPOTrainer:
    def test_ppo_increases_reward(self, tmp_path):
        """Reward = fraction of generated tokens equal to 7 -> policy must shift
        toward emitting 7 (group-relative baseline, rollout via the paged engine)."""
        from paddlenlp_tpu.trl import PPOConfig, PPOTrainer

        model = tiny_model(use_scan_layers=True, eos_token_id=None)

        class Prompts:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return {"input_ids": np.asarray([20 + i, 30 + i, 40 + i], np.int32)}

        def reward_fn(ids, labels):
            gen = ids[labels != -100] if (labels != -100).any() else ids
            # dense signal: closer-to-7 tokens score higher (sparse ==7 rewards are
            # ~all-zero on a random tiny model, leaving no group advantage)
            return float(-np.abs(gen.astype(np.float32) - 7).mean() / 64.0)

        args = TrainingArguments(output_dir=str(tmp_path), max_steps=8, per_device_train_batch_size=2,
                                 learning_rate=5e-3, save_strategy="no", max_grad_norm=1.0)
        trainer = PPOTrainer(
            model=model,
            reward_fn=reward_fn,
            args=args,
            train_dataset=Prompts(),
            ppo_config=PPOConfig(num_rollouts_per_prompt=4, max_new_tokens=8, kl_coef=0.01),
        )
        # baseline: expected |token - 7| under the policy at this prompt
        ids = jnp.asarray([[20, 30, 40]], jnp.int32)
        dist = jnp.abs(jnp.arange(64) - 7)

        def expected_dist(params):
            p = jax.nn.softmax(trainer.model.apply(params, input_ids=ids).logits[0, -1])
            return float((p * dist).sum())

        before = expected_dist(model.params)
        out = trainer.train()
        after = expected_dist(trainer.train_state.params)
        assert np.isfinite(out.training_loss)
        assert after < before, (before, after)  # policy shifted toward token 7

    def test_gae_matches_numpy_reference(self):
        """gae_advantages against a hand-rolled reversed-loop reference,
        including right-padding and a masked prompt prefix."""
        from paddlenlp_tpu.trl.ppo_trainer import gae_advantages

        gamma, lam = 0.9, 0.8
        rng = np.random.default_rng(0)
        B, T = 2, 7
        rewards = rng.normal(size=(B, T)).astype(np.float32)
        values = rng.normal(size=(B, T)).astype(np.float32)
        mask = np.asarray([[0, 0, 1, 1, 1, 0, 0],   # prompt=2, resp=3, pad=2
                           [0, 1, 1, 1, 1, 1, 0]], np.float32)
        rewards *= mask
        values *= mask
        adv_ref = np.zeros((B, T), np.float32)
        for b in range(B):
            nxt_adv, nxt_v = 0.0, 0.0
            for t in range(T - 1, -1, -1):
                if not mask[b, t]:
                    continue
                delta = rewards[b, t] + gamma * nxt_v - values[b, t]
                nxt_adv = delta + gamma * lam * nxt_adv
                nxt_v = values[b, t]
                adv_ref[b, t] = nxt_adv
        adv, ret = gae_advantages(jnp.asarray(rewards), jnp.asarray(values),
                                  jnp.asarray(mask), gamma, lam)
        np.testing.assert_allclose(np.asarray(adv), adv_ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(ret), adv_ref + values * mask, rtol=1e-5, atol=1e-6)

    def test_ppo_value_model_mode(self, tmp_path):
        """Reference-fidelity mode: token-level ratios + trained value model +
        GAE (per-token KL rewards, terminal score). The policy must still learn
        and the value loss must fall across the run."""
        from paddlenlp_tpu.trl import PPOConfig, PPOTrainer

        model = tiny_model(use_scan_layers=True, eos_token_id=None)

        class Prompts:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return {"input_ids": np.asarray([20 + i, 30 + i, 40 + i], np.int32)}

        def reward_fn(ids, labels):
            gen = ids[labels != -100] if (labels != -100).any() else ids
            return float(-np.abs(gen.astype(np.float32) - 7).mean() / 64.0)

        args = TrainingArguments(output_dir=str(tmp_path), max_steps=8, per_device_train_batch_size=2,
                                 learning_rate=5e-3, save_strategy="no", max_grad_norm=1.0)
        trainer = PPOTrainer(
            model=model,
            reward_fn=reward_fn,
            args=args,
            train_dataset=Prompts(),
            ppo_config=PPOConfig(num_rollouts_per_prompt=4, max_new_tokens=8, kl_coef=0.01,
                                 use_value_model=True, gae_lambda=0.95, value_lr=1e-3,
                                 entropy_coef=0.001),
        )
        ids = jnp.asarray([[20, 30, 40]], jnp.int32)
        dist = jnp.abs(jnp.arange(64) - 7)

        def expected_dist(params):
            p = jax.nn.softmax(trainer.model.apply(params, input_ids=ids).logits[0, -1])
            return float((p * dist).sum())

        before = expected_dist(model.params)
        out = trainer.train()
        after = expected_dist(trainer.train_state.params)
        assert np.isfinite(out.training_loss)
        assert after < before, (before, after)
        # the value head must have moved off its init
        assert trainer.value_params is not None
