"""Disaggregated-backend stage-loss chaos: migration and stage-init faults.

A disagg replica has two new ways to die that a single-pool one doesn't:

- the prefill→decode KV-block migration dispatch (``engine.kv_migrate``) —
  it hits a request whose FIRST token already streamed, so recovery must
  fold that token into the requeue prompt and continue token-exactly;
- either stage's mesh/layout construction during a supervisor rebuild
  (``engine.shard_init``, fired once per stage) — a failed stage init must
  extend the DEGRADED window, not crash-loop, and the next attempt must
  bring BOTH stages back.

With concurrent SSE streams in flight and both faults armed, the run must
end with zero stream loss, token-exact outputs vs a solo disagg run, and no
KV block leaked in either pool. Runs on the conftest's 8 virtual CPU devices
(1+1 stages keep compiles cheap)."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import (
    MetricsRegistry,
    SchedulerConfig,
    ServingServer,
    SupervisorPolicy,
)
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS


def get_json(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), dict(resp.getheaders())
    finally:
        conn.close()


def post_json(port, path, payload, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), dict(resp.getheaders())
    finally:
        conn.close()


class SSEStream:
    def __init__(self, port, payload, timeout=300):
        self.conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        self.conn.request("POST", "/v1/completions", body=json.dumps(payload),
                          headers={"Content-Type": "application/json"})
        self.resp = self.conn.getresponse()
        self.status = self.resp.status

    def events(self):
        while True:
            line = self.resp.readline()
            if not line:
                return
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                return
            yield json.loads(data)

    def close(self):
        self.conn.close()


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model(eight_devices):
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=8,
                      max_position_embeddings=256, eos_token_id=None, pad_token_id=0,
                      use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine(model):
    return InferenceEngine(model, disagg_stages=(1, 1), max_batch_size=4,
                           block_size=4, num_blocks=128, max_blocks_per_seq=32,
                           decode_steps=4)


GEN_LEN = 12


class TestDisaggStageLoss:
    def test_migrate_fault_then_shard_init_fault_zero_stream_loss(self, model):
        """engine.kv_migrate kills a step whose victims already streamed their
        first token; rebuild attempt 1 dies inside a stage's mesh init
        (engine.shard_init); attempt 2 recovers — every stream finishes
        token-exact, nothing leaks in either pool."""
        n_stream = 4
        registry = MetricsRegistry()
        srv = ServingServer(
            make_engine(model),
            engine_factory=lambda: make_engine(model),
            supervisor_policy=SupervisorPolicy(max_retries=2, backoff_base_s=0.5,
                                               backoff_max_s=1.5),
            scheduler_config=SchedulerConfig(max_inflight=16, default_timeout_s=600.0),
            registry=registry,
        )
        port = srv.start_in_thread()
        try:
            # armed AFTER the first engine exists: the first migration attempt
            # dies (the victims have exactly their prefill-sampled token
            # streamed), then the rebuild's FIRST stage construction dies too
            FAULTS.arm("engine.kv_migrate", nth=1)
            FAULTS.arm("engine.shard_init", nth=1)

            results = {}

            def stream_worker(i):
                s = SSEStream(port, {"prompt": [5 + i, 6 + i, 7 + i],
                                     "max_tokens": GEN_LEN, "stream": True})
                assert s.status == 200
                toks, finish = [], None
                for ev in s.events():
                    c = ev["choices"][0]
                    if c.get("finish_reason"):
                        finish = c["finish_reason"]
                    elif "token" in c:
                        toks.append(c["token"])
                results[i] = (toks, finish)
                s.close()

            threads = [threading.Thread(target=stream_worker, args=(i,))
                       for i in range(n_stream)]
            for t in threads:
                t.start()

            deadline = time.time() + 120
            while time.time() < deadline and not srv.loop.degraded:
                time.sleep(0.01)
            assert srv.loop.degraded, "engine.kv_migrate fault never tripped the supervisor"
            status, health, _ = get_json(port, "/health")
            assert status == 503 and health["status"] == "degraded"
            status, body, headers = post_json(
                port, "/v1/completions", {"prompt": [1, 2, 3], "max_tokens": 2})
            assert status == 503
            assert int(headers.get("Retry-After", 0)) >= 1

            for t in threads:
                t.join(timeout=600)
            assert not any(t.is_alive() for t in threads)

            # both faults actually happened: the migration died, then one
            # stage's mesh init killed rebuild attempt 1
            assert FAULTS.fired("engine.kv_migrate") == 1
            assert FAULTS.fired("engine.shard_init") == 1
            assert registry.get("paddlenlp_serving_engine_restarts_total").value() >= 1

            # zero stream loss, token-exact vs a solo disagg run
            assert len(results) == n_stream
            for i, (toks, finish) in results.items():
                assert finish == "length", (i, finish)
                assert len(toks) == GEN_LEN, (i, len(toks))
            solo = make_engine(model).generate(
                [[5, 6, 7]], SamplingParams(max_new_tokens=GEN_LEN))[0]
            np.testing.assert_array_equal(results[0][0], solo)

            # no KV leak in either pool: the shared block-id space is whole,
            # every requeued stream re-migrated on the rebuilt engine, and no
            # migration state is stranded
            eng = srv.loop.engine
            assert eng.mgr.num_free == eng.mgr.total_usable_blocks
            assert not eng._migrating and not eng._migrate_pending
            assert eng.stats()["backend"]["kind"] == "disagg"
            assert eng.backend.migration_stats["migrations"] >= n_stream
            # the migration series made it to the metrics plane
            assert registry.get("paddlenlp_serving_kv_migrations_total").value() >= n_stream
        finally:
            srv.shutdown(drain_timeout_s=10)

    def test_direct_engine_migrate_fault_partial_state_and_abort(self, model):
        """Engine-level view of the same fault: step() raises at the
        migration dispatch, the handoff stays QUEUED (pre-pop fire), a bare
        retry step completes it, and aborting instead leaks nothing."""
        eng = make_engine(model)
        FAULTS.arm("engine.kv_migrate", nth=1)
        rid = eng.add_request([5, 6, 7, 8], SamplingParams(max_new_tokens=4))
        eng.step()  # admit + prefill: first token sampled, migration queued
        with pytest.raises(Exception, match="injected fault"):
            while eng.has_work():
                eng.step()
        req = next(r for r in eng.slots if r is not None)
        assert req.kv_stage == "migrating"
        assert list(eng._migrate_pending) == [rid]  # handoff still queued
        # bare retry (the fault fires once): the queued migration completes
        while eng.has_work():
            eng.step()
        assert len(req.output_ids) == 4
        assert eng.mgr.num_free == eng.mgr.total_usable_blocks

        # abort-instead variant: release mid-migration, nothing leaks
        FAULTS.arm("engine.kv_migrate", nth=1)
        rid2 = eng.add_request([50, 51, 52, 53], SamplingParams(max_new_tokens=4))
        eng.step()
        with pytest.raises(Exception, match="injected fault"):
            while eng.has_work():
                eng.step()
        assert eng.abort(rid2) is not None
        assert not eng._migrating and not eng._migrate_pending
        assert eng.mgr.num_free == eng.mgr.total_usable_blocks
