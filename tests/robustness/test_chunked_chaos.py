"""Chunked-prefill chaos: a fault injected mid-chunk (``engine.prefill_chunk``,
request partially prefilled, NO token emitted yet) must triage through the
engine-loop supervisor like any step failure — token-exact retry after the
rebuild, no leaked KV blocks, restart/retry metrics incremented.

Real engine on CPU, tiny model — tier-1 speed."""

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import EngineLoop, MetricsRegistry, ServingMetrics, SupervisorPolicy
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS, InjectedFault


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine(model):
    return InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=128,
                           max_blocks_per_seq=32, decode_steps=4, prefill_chunk_tokens=8)


LONG_PROMPT = list(range(10, 40))  # 30 tokens -> 4 chunks of <=8
SHORT_PROMPT = [5, 6, 7]


class TestChunkedPrefillChaos:
    def test_fault_mid_chunk_triages_token_exact_no_leak(self, model):
        # solo reference runs (no faults) for exact-token comparison; one
        # engine serves both (state clears between generates, and a prefix-
        # cache hit on the repeat must be token-identical anyway)
        ref = make_engine(model)
        want_long = ref.generate([LONG_PROMPT], SamplingParams(max_new_tokens=6))[0]
        want_short = ref.generate([SHORT_PROMPT], SamplingParams(max_new_tokens=8))[0]

        registry = MetricsRegistry()
        engine = make_engine(model)
        loop = EngineLoop(
            engine, metrics=ServingMetrics(engine, registry),
            engine_factory=lambda: make_engine(model),
            policy=SupervisorPolicy(max_retries=2, backoff_base_s=0.01,
                                    backoff_max_s=0.05),
        ).start()
        try:
            # short request first so decode is mid-flight when the prompt chunks
            h_short = loop.submit(SHORT_PROMPT, SamplingParams(max_new_tokens=8))
            h_short.result(timeout=120)  # warm the jits; stream settled
            # 2nd mixed step = long request partially prefilled, nothing emitted
            FAULTS.arm("engine.prefill_chunk", nth=2, times=1)
            h_long = loop.submit(LONG_PROMPT, SamplingParams(max_new_tokens=6))
            h_chat = loop.submit(SHORT_PROMPT, SamplingParams(max_new_tokens=8))
            req_long = h_long.result(timeout=120)
            req_chat = h_chat.result(timeout=120)
            assert FAULTS.fired("engine.prefill_chunk") == 1
            # token-exact recovery for the half-prefilled request AND the
            # decode that was riding the same mixed steps
            assert list(h_long._streamed) == list(want_long)
            assert list(h_chat._streamed) == list(want_short)
            assert req_long.finish_reason in ("stop", "length")
            assert req_chat.finish_reason in ("stop", "length")
            assert registry.get("paddlenlp_serving_engine_restarts_total").value() == 1
            assert registry.get("paddlenlp_serving_request_retries_total").value() >= 1
            # no KV leak: every block back on the rebuilt engine's free list
            mgr = loop.engine.mgr
            assert mgr.num_free == mgr.total_usable_blocks
        finally:
            loop.stop(drain=False)

    def test_fault_mid_chunk_engine_state_consistent(self, model):
        """Direct (no supervisor) view: the injected fault leaves the request
        partially prefilled with no token emitted; freeing it leaks nothing."""
        engine = make_engine(model)
        engine.add_request(LONG_PROMPT, SamplingParams(max_new_tokens=4))
        engine.step()  # first chunk lands
        FAULTS.arm("engine.prefill_chunk", nth=1, times=1)
        with pytest.raises(InjectedFault):
            engine.step()
        req = next(r for r in engine.slots if r is not None)
        assert 0 < req.prefilled_len < len(req.prompt_ids)
        assert req.output_ids == [] and req.first_token_t is None
        engine.abort(req.req_id)
        assert engine.mgr.num_free == engine.mgr.total_usable_blocks
