"""Router failover chaos test (ISSUE 4 acceptance).

Two real in-process replicas behind the router, prefix affinity pinning all
traffic to one of them, and an ``engine.step`` fault armed on the pinned
replica (it is the only one stepping, so the process-global fault registry
hits it deterministically). With concurrent SSE clients mid-generation:

- **no client sees a raw 5xx** for a retryable request;
- the stream still waiting in the pinned replica's engine queue (zero tokens)
  **fails over** to the healthy replica and completes **token-exact** vs a
  solo run — the client cannot tell anything happened beyond a pause;
- streams with tokens already relayed finish **in-band** with
  ``finish_reason="replica_error"`` (regeneration would diverge the stream);
- ``paddlenlp_router_failovers_total`` and ``paddlenlp_router_replica_healthy``
  reflect the incident, and the pinned replica returns to HEALTHY (and to
  its prefix pin) once its supervisor rebuilds the engine.

CPU-only, tiny model — tier-1 speed."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import MetricsRegistry, SchedulerConfig, SupervisorPolicy
from paddlenlp_tpu.serving.router import (
    DEGRADED,
    DOWN,
    HEALTHY,
    PrefixAffinityPolicy,
    RouterServer,
    launch_fleet,
    launch_replicas,
)
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine_factory(model):
    def make_engine():
        return InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=128,
                               max_blocks_per_seq=32, decode_steps=4)
    return make_engine


def post_json(port, path, payload, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), dict(resp.getheaders())
    finally:
        conn.close()


def get_text(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def metric_value(text, name):
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            return float(line.rsplit(" ", 1)[1])
    raise AssertionError(f"metric {name} missing:\n{text}")


GEN_LEN = 32
PREFIX = [5, 6, 7]  # prefix_tokens=3 below: all PREFIX+tail prompts co-locate


class TestRouterFailoverChaos:
    def test_engine_fault_on_pinned_replica(self, model):
        n_stream = 5  # max_batch_size=4 -> exactly one stream waits token-less
        registry = MetricsRegistry()
        fleet = launch_replicas(
            2, make_engine_factory(model),
            scheduler_config=SchedulerConfig(max_inflight=16, default_timeout_s=600.0),
            # max_retries=0: the pinned replica fast-fails its in-flight work
            # with engine_error instead of recovering it locally — the point
            # here is exercising the ROUTER's failover, not PR 3's requeue
            supervisor_policy=SupervisorPolicy(max_retries=0, backoff_base_s=0.5,
                                               backoff_max_s=2.0))
        router = RouterServer(
            [(h, p, f"r{i}") for i, (h, p) in enumerate(fleet.endpoints())],
            policy=PrefixAffinityPolicy(prefix_tokens=3),
            registry=registry, poll_interval_s=0.05, max_attempts=3)
        fleet.router = router  # fleet.shutdown tears the router down first
        router.pool.poll_once()
        port = router.start_in_thread()
        fleet.router_port = port
        try:
            pinned = router.policy.select(
                router.pool.snapshots(), prompt=PREFIX + [0])[0].id
            healthy = next(s.id for s in router.pool.snapshots() if s.id != pinned)

            lock = threading.Lock()
            tokens = {i: [] for i in range(n_stream)}
            finishes = {}
            statuses = {}

            def stream_worker(i):
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
                conn.request("POST", "/v1/completions",
                             body=json.dumps({"prompt": PREFIX + [40 + i],
                                              "max_tokens": GEN_LEN, "stream": True}),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                statuses[i] = resp.status
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    line = line.strip()
                    if not line.startswith(b"data: "):
                        continue
                    data = line[len(b"data: "):]
                    if data == b"[DONE]":
                        break
                    ev = json.loads(data)
                    c = ev["choices"][0]
                    if c.get("finish_reason"):
                        finishes[i] = c["finish_reason"]
                    elif "token" in c:
                        with lock:
                            tokens[i].append(c["token"])
                conn.close()

            threads = [threading.Thread(target=stream_worker, args=(i,))
                       for i in range(n_stream)]
            for t in threads:
                t.start()

            # wait until 4 streams (= the batch slots) are visibly decoding;
            # the 5th is then token-less in the engine's waiting queue
            deadline = time.time() + 120
            while time.time() < deadline:
                with lock:
                    flowing = [i for i, ts in tokens.items() if ts]
                if len(flowing) >= n_stream - 1:
                    break
                time.sleep(0.002)
            assert len(flowing) >= n_stream - 1, f"streams never started: {flowing}"
            waiting = next(i for i in range(n_stream) if i not in flowing)

            # the fault fires on the pinned replica's very next step (the
            # healthy replica has no work, so it never steps); the first
            # rebuild attempt also fails to widen the degraded window
            FAULTS.arm("engine.step", nth=1)
            FAULTS.arm("engine.rebuild", nth=1)

            # ---- incident visible on the router's health plane ----
            deadline = time.time() + 30
            while time.time() < deadline:
                state = {s.id: s.state for s in router.pool.snapshots()}[pinned]
                if state in (DEGRADED, DOWN):
                    break
                time.sleep(0.005)
            assert state in (DEGRADED, DOWN), f"pinned replica never demoted ({state})"
            status, text = get_text(port, "/metrics")
            assert status == 200
            assert metric_value(
                text, f'paddlenlp_router_replica_healthy{{replica="{pinned}"}}') == 0.0
            assert metric_value(
                text, f'paddlenlp_router_replica_healthy{{replica="{healthy}"}}') == 1.0

            # ---- during the window: new pinned-prefix traffic still lands,
            # health-aware routing sends it to the healthy replica, and the
            # client never sees the pinned replica's 503 ----
            status, body, _ = post_json(port, "/v1/completions",
                                        {"prompt": PREFIX + [90], "max_tokens": 4})
            assert status == 200, body
            assert len(body["choices"][0]["token_ids"]) == 4
            assert body["replica"] == healthy

            for t in threads:
                t.join(timeout=600)
            assert not any(t.is_alive() for t in threads)

            # ---- zero raw 5xx on the SSE legs ----
            assert all(statuses[i] == 200 for i in range(n_stream)), statuses

            # ---- the token-less stream failed over token-exact ----
            assert finishes[waiting] == "length", finishes
            assert len(tokens[waiting]) == GEN_LEN
            solo = make_engine_factory(model)().generate(
                [PREFIX + [40 + waiting]], SamplingParams(max_new_tokens=GEN_LEN))[0]
            np.testing.assert_array_equal(tokens[waiting], solo)

            # ---- mid-stream streams finished in-band with replica_error ----
            for i in flowing:
                assert finishes[i] == "replica_error", (i, finishes)
                assert 1 <= len(tokens[i]) < GEN_LEN, (i, len(tokens[i]))

            # ---- metrics reflect the incident ----
            status, text = get_text(port, "/metrics")
            assert metric_value(text, "paddlenlp_router_failovers_total") >= 1
            assert metric_value(
                text,
                f'paddlenlp_router_requests_total{{replica="{pinned}",outcome="replica_error"}}'
            ) == n_stream - 1
            assert metric_value(
                text,
                f'paddlenlp_router_requests_total{{replica="{healthy}",outcome="ok"}}') >= 2

            # ---- recovery: supervisor rebuilds, poller re-promotes, and the
            # prefix pin returns home ----
            deadline = time.time() + 60
            while time.time() < deadline:
                if {s.id: s.state for s in router.pool.snapshots()}[pinned] == HEALTHY:
                    break
                time.sleep(0.01)
            assert {s.id: s.state for s in router.pool.snapshots()}[pinned] == HEALTHY
            status, body, _ = post_json(port, "/v1/completions",
                                        {"prompt": PREFIX + [91], "max_tokens": 4})
            assert status == 200
            assert body["replica"] == pinned  # affinity restored post-incident
        finally:
            fleet.shutdown(drain_timeout_s=5)

    def test_fleet_spreads_load_without_faults(self, model):
        """Sanity for the launcher + least-loaded policy: concurrent requests
        through the router land on both replicas and all succeed."""
        registry = MetricsRegistry()
        fleet = launch_fleet(2, make_engine_factory(model), policy="least_loaded",
                             router_registry=registry, poll_interval_s=0.1,
                             scheduler_config=SchedulerConfig(max_inflight=16,
                                                              default_timeout_s=600.0))
        try:
            results = {}

            def worker(i):
                results[i] = post_json(fleet.router_port, "/v1/completions",
                                       {"prompt": [10 + i, 11, 12], "max_tokens": 4})

            threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
            for t in threads:
                t.start()
                # small stagger: each decision must see the previous forward
                # in the router-side inflight accounting (the poller alone is
                # up to an interval stale)
                time.sleep(0.05)
            for t in threads:
                t.join(timeout=300)
            replicas_used = set()
            for i, (status, body, _) in results.items():
                assert status == 200, (i, body)
                assert len(body["choices"][0]["token_ids"]) == 4
                replicas_used.add(body["replica"])
            assert len(replicas_used) == 2, f"all requests pinned to {replicas_used}"
            req = registry.get("paddlenlp_router_requests_total")
            total = sum(req.value(replica=f"127.0.0.1:{p}", outcome="ok")
                        for p in fleet.ports)
            assert total == 6
        finally:
            fleet.shutdown(drain_timeout_s=5)
