"""Usage metering under chaos: exactly-once billing across a rebuild.

A fault in ``engine.step`` mid-stream triggers the supervisor: in-flight
requests are stashed, the engine rebuilds, the requests requeue with their
streamed tokens folded into the prompt and finish token-exact. Billing-wise
all of that must collapse to **exactly one usage record per request** — the
stash never books, the post-rebuild resolution books once, and the sealed
ledger (plus ``tools/usage_report.py``) shows one bill per trace id with the
full client-visible completion. The reconciliation gap under chaos is
one-sided: metered useful ≤ the counters' total, because the counters also
saw the dead engine's completed work per retried request (the documented
slack).

The companion torn-write case (kill between segment append and seal via the
``usage.seal`` fault point) lives in
``tests/observability/test_usage_ledger.py``.

CPU-only, tiny model — tier-1 speed."""

import http.client
import json
import os
import sys
import threading
import time

import pytest

from paddlenlp_tpu.experimental import InferenceEngine
from paddlenlp_tpu.observability.usage import load_ledger_dir
from paddlenlp_tpu.serving import (
    MetricsRegistry,
    SchedulerConfig,
    ServingServer,
    SupervisorPolicy,
)
from paddlenlp_tpu.serving.tenancy.metering import ENV_DIR
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import usage_report  # noqa: E402

GEN = 24


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine(model):
    return InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=128,
                           max_blocks_per_seq=32, decode_steps=4)


class SSEStream:
    def __init__(self, port, payload, timeout=300):
        self.conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        self.conn.request("POST", "/v1/completions", body=json.dumps(payload),
                          headers={"Content-Type": "application/json"})
        self.resp = self.conn.getresponse()
        self.status = self.resp.status

    def events(self):
        while True:
            line = self.resp.readline()
            if not line:
                return
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                return
            yield json.loads(data)

    def close(self):
        self.conn.close()


class TestUsageUnderChaos:
    def test_one_record_per_request_across_rebuild(self, model, tmp_path,
                                                   monkeypatch):
        ledger_dir = tmp_path / "ledger"
        monkeypatch.setenv(ENV_DIR, str(ledger_dir))
        n_stream, n_err = 6, 1
        registry = MetricsRegistry()
        srv = ServingServer(
            make_engine(model),
            engine_factory=lambda: make_engine(model),
            supervisor_policy=SupervisorPolicy(max_retries=2, backoff_base_s=0.25,
                                               backoff_max_s=1.0),
            scheduler_config=SchedulerConfig(max_inflight=16,
                                             default_timeout_s=600.0),
            registry=registry,
        )
        port = srv.start_in_thread()
        try:
            # fault on the 4th step: every stream admitted, none finished
            # (1 prefill + 3x4 decode tokens < GEN)
            FAULTS.arm("engine.step", nth=4)

            results, errors = {}, {}

            def worker(i, sink, extra):
                s = SSEStream(port, dict({"prompt": [5 + i % 40, 6 + i % 40,
                                                     7 + i % 40],
                                          "max_tokens": GEN, "stream": True,
                                          "tenant": ("acme", "globex")[i % 2]},
                                         **extra))
                assert s.status == 200
                toks, finish = [], None
                for ev in s.events():
                    c = ev["choices"][0]
                    if c.get("finish_reason"):
                        finish = c["finish_reason"]
                    elif "token" in c:
                        toks.append(c["token"])
                sink[i] = (toks, finish)
                s.close()

            threads = [threading.Thread(target=worker, args=(i, results, {}))
                       for i in range(n_stream)]
            threads += [threading.Thread(target=worker,
                                         args=(100 + i, errors,
                                               {"max_retries": 0}))
                        for i in range(n_err)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            assert not any(t.is_alive() for t in threads)

            assert srv.loop.metrics.engine_restarts.value() >= 1
            for i, (toks, finish) in results.items():
                assert finish == "length" and len(toks) == GEN, (i, finish)
            for i, (toks, finish) in errors.items():
                assert finish == "engine_error", (i, finish)

            usage = srv.usage()
            # the retried streams resolved ONCE each despite stash + requeue:
            # one record per request, none suppressed as duplicates (nothing
            # even attempted a double booking)
            assert usage["records"] == n_stream + n_err
            assert usage["duplicates_suppressed"] == 0
            retried = [r for r in srv.loop.recent_finished if r["retries"]]
            assert retried, "fault never forced a retry"
            for row in retried:
                # the bill covers the full client-visible completion, not
                # just post-rebuild work
                assert row["usage"]["completion_tokens"] == GEN

            exposition = registry.expose()
            counter_useful = 0.0
            for line in exposition.splitlines():
                if line.startswith("paddlenlp_serving_useful_tokens_total "):
                    counter_useful = float(line.split()[-1])
            metered_useful = usage["totals"]["useful_tokens"]
        finally:
            srv.shutdown(drain_timeout_s=10)

        # sealed ledger: exactly one record per request id, full bills
        records, report = load_ledger_dir(str(ledger_dir))
        assert report["open_segments"] == 0
        assert len(records) == n_stream + n_err
        assert len({r["record_id"] for r in records}) == n_stream + n_err
        by_reason = {}
        for r in records:
            by_reason[r["finish_reason"]] = by_reason.get(r["finish_reason"], 0) + 1
        assert by_reason == {"length": n_stream, "engine_error": n_err}
        retried_records = [r for r in records if r["retries"]]
        assert retried_records
        for r in retried_records:
            assert r["completion_tokens"] == GEN

        # one-sided reconciliation gap: the counters kept the dead engine's
        # completed work, the records only attribute surviving-engine work
        gap = counter_useful - metered_useful
        assert gap >= 0, (counter_useful, metered_useful)
        assert usage_report.reconcile(
            usage_report.aggregate(records), [counter_useful], slack=gap)["ok"]
        # ... and without slack the report flags the divergence (gap is only
        # zero if the fault raced ahead of any completed work, which nth=4
        # prevents)
        assert gap > 0
        assert usage_report.main([str(ledger_dir), "--useful-total",
                                  str(counter_useful)]) == 1
