"""Serving recovery chaos test (ISSUE 3 acceptance, serving side).

With 8 concurrent SSE streams and a fault injected into ``engine.step``:

- the API returns 503 (+ ``Retry-After``) while DEGRADED — never a
  connection reset;
- ``engine_restarts_total`` increments;
- retried requests complete with exactly the tokens an uninterrupted run
  produces (position-keyed sampling + recompute requeue);
- non-retryable requests (``max_retries: 0``) finish with
  ``finish_reason="engine_error"`` delivered in-band over SSE.

CPU-only, tiny model — tier-1 speed."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import (
    MetricsRegistry,
    SchedulerConfig,
    ServingServer,
    SupervisorPolicy,
)
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine(model):
    return InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=128,
                           max_blocks_per_seq=32, decode_steps=4)


def get_json(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), dict(resp.getheaders())
    finally:
        conn.close()


def post_json(port, path, payload, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), dict(resp.getheaders())
    finally:
        conn.close()


class SSEStream:
    def __init__(self, port, payload, timeout=300):
        self.conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        self.conn.request("POST", "/v1/completions", body=json.dumps(payload),
                          headers={"Content-Type": "application/json"})
        self.resp = self.conn.getresponse()
        self.status = self.resp.status

    def events(self):
        while True:
            line = self.resp.readline()
            if not line:
                return
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                return
            yield json.loads(data)

    def close(self):
        self.conn.close()


GEN_LEN = 24


class TestServingRecovery:
    def test_engine_fault_under_concurrent_sse_streams(self, model):
        n_stream, n_err = 8, 2
        registry = MetricsRegistry()
        srv = ServingServer(
            make_engine(model),
            engine_factory=lambda: make_engine(model),
            supervisor_policy=SupervisorPolicy(max_retries=2, backoff_base_s=0.75,
                                               backoff_max_s=2.0),
            scheduler_config=SchedulerConfig(max_inflight=16, default_timeout_s=600.0),
            registry=registry,
        )
        port = srv.start_in_thread()
        try:
            # fault on the 4th engine step: all streams admitted, none can have
            # finished (<= 1 prefill + 3x4 decode tokens < GEN_LEN); the first
            # rebuild attempt also fails so the DEGRADED window is wide enough
            # to probe deterministically
            FAULTS.arm("engine.step", nth=4)
            FAULTS.arm("engine.rebuild", nth=1)

            results, errors = {}, {}

            def stream_worker(i):
                s = SSEStream(port, {"prompt": [5 + i, 6 + i, 7 + i],
                                     "max_tokens": GEN_LEN, "stream": True})
                assert s.status == 200
                toks, finish = [], None
                for ev in s.events():
                    c = ev["choices"][0]
                    if c.get("finish_reason"):
                        finish = c["finish_reason"]
                    elif "token" in c:
                        toks.append(c["token"])
                results[i] = (toks, finish)
                s.close()

            def error_worker(i):
                s = SSEStream(port, {"prompt": [40 + i, 41 + i], "max_tokens": GEN_LEN,
                                     "stream": True, "max_retries": 0})
                assert s.status == 200
                toks, finish = [], None
                for ev in s.events():
                    c = ev["choices"][0]
                    if c.get("finish_reason"):
                        finish = c["finish_reason"]
                    elif "token" in c:
                        toks.append(c["token"])
                errors[i] = (toks, finish)
                s.close()

            threads = [threading.Thread(target=stream_worker, args=(i,)) for i in range(n_stream)]
            threads += [threading.Thread(target=error_worker, args=(i,)) for i in range(n_err)]
            for t in threads:
                t.start()

            # ---- while degraded: clean 503s, never connection resets ----
            deadline = time.time() + 60
            while time.time() < deadline and not srv.loop.degraded:
                time.sleep(0.01)
            assert srv.loop.degraded, "engine.step fault never tripped the supervisor"
            status, health, _ = get_json(port, "/health")
            assert status == 503 and health["status"] == "degraded"
            status, body, headers = post_json(
                port, "/v1/completions", {"prompt": [1, 2, 3], "max_tokens": 2})
            assert status == 503, body
            assert body["error"]["type"] == "engine_recovering"
            assert int(headers.get("Retry-After", 0)) >= 1

            for t in threads:
                t.join(timeout=600)
            assert not any(t.is_alive() for t in threads)

            # ---- retried streams: full budget, token-exact vs a solo run ----
            assert len(results) == n_stream
            for i, (toks, finish) in results.items():
                assert finish == "length", (i, finish)
                assert len(toks) == GEN_LEN, (i, len(toks))
            solo = make_engine(model).generate(
                [[5, 6, 7]], SamplingParams(max_new_tokens=GEN_LEN))[0]
            np.testing.assert_array_equal(results[0][0], solo)

            # ---- non-retryable: fast-cleared in-band with engine_error ----
            assert len(errors) == n_err
            for i, (toks, finish) in errors.items():
                assert finish == "engine_error", (i, finish)
                assert len(toks) < GEN_LEN

            # ---- metrics plane ----
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            text = resp.read().decode()
            conn.close()

            def metric_value(name):
                for line in text.splitlines():
                    if line.startswith(name + " ") or line.startswith(name + "{"):
                        return float(line.rsplit(" ", 1)[1])
                raise AssertionError(f"metric {name} missing:\n{text}")

            assert metric_value("paddlenlp_serving_engine_restarts_total") >= 1
            assert metric_value("paddlenlp_serving_request_retries_total") >= n_stream
            # goodput ledger: the requeue re-prefill of already-streamed work
            # on the rebuilt engine is booked as rework, and the rebuilt
            # engine's ledger stays exactly conserved through the incident
            assert metric_value(
                'paddlenlp_serving_wasted_tokens_total{kind="rework"}') >= 1
            assert srv.loop.engine.ledger.verify_conservation()
            assert srv.loop.engine.ledger.rework_by["requeue_refill"] >= 1
            assert 'paddlenlp_serving_requests_total{status="engine_error",priority="interactive",tenant="default"}' in text
            assert 'paddlenlp_serving_requests_total{status="length",priority="interactive",tenant="default"}' in text

            # ---- post-recovery health + fresh traffic ----
            status, health, _ = get_json(port, "/health")
            assert status == 200 and health["status"] == "ok"
            assert health["scheduler"]["rejected_degraded"] >= 1
            status, body, _ = post_json(port, "/v1/completions",
                                        {"prompt": [5, 6, 7], "max_tokens": 4})
            assert status == 200
            assert len(body["choices"][0]["token_ids"]) == 4
        finally:
            srv.shutdown(drain_timeout_s=5)

    def test_in_place_reset_recovery_without_factory(self, model):
        """No engine_factory: the supervisor recovers via engine.reset()."""
        registry = MetricsRegistry()
        srv = ServingServer(
            make_engine(model),
            supervisor_policy=SupervisorPolicy(backoff_base_s=0.05, backoff_max_s=0.2),
            scheduler_config=SchedulerConfig(max_inflight=8, default_timeout_s=600.0),
            registry=registry,
        )
        port = srv.start_in_thread()
        try:
            FAULTS.arm("engine.step", nth=2)
            status, body, _ = post_json(port, "/v1/completions",
                                        {"prompt": [5, 6, 7], "max_tokens": 8}, timeout=300)
            assert status == 200, body
            choice = body["choices"][0]
            assert choice["finish_reason"] == "length"
            # same engine object, identical continuation after reset
            solo = make_engine(model).generate([[5, 6, 7]], SamplingParams(max_new_tokens=8))[0]
            np.testing.assert_array_equal(choice["token_ids"], solo)
            assert registry.get("paddlenlp_serving_engine_restarts_total").value() >= 1
        finally:
            srv.shutdown(drain_timeout_s=5)
