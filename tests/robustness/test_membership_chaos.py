"""Elastic-fleet chaos (ISSUE 11 acceptance): scale-down under fire, the
hedge race, and quarantine-vs-rebuild, all against REAL in-process replicas
(tiny CPU model — tier-1 speed).

- **Drain under fire**: a replica is drained mid-traffic while ``engine.step``
  faults are armed on it. Every stream must finish token-exact (failover or
  completion), no client may see a 5xx, the pool's drain state machine must
  land on ``removed``, and neither replica may leak a KV block.
- **Hedge race (both respond)**: the pinned replica's steps are slowed past
  the hedge budget so a shadow forward races it; whichever leg wins, the
  client's stream is token-exact (greedy decoding makes the legs identical)
  and the loser is torn down invisibly.
- **Quarantine vs rebuild**: a poisoned request on a real engine triggers a
  slot quarantine — the healthy concurrent stream never pauses and is
  token-exact, and ``engine_restarts_total`` stays 0.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import (
    MetricsRegistry,
    SchedulerConfig,
    ServingServer,
    SupervisorPolicy,
)
from paddlenlp_tpu.serving.router import PrefixAffinityPolicy, launch_fleet
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine_factory(model):
    def make_engine():
        return InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=256,
                               max_blocks_per_seq=32, decode_steps=4)
    return make_engine


def post_json(port, path, payload, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def stream_request(port, prompt, max_tokens, out, key, timeout=600, **extra):
    """Collect one SSE stream into ``out[key]`` = (status, tokens, finish)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions",
                     body=json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                                      "stream": True, **extra}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        toks, finish = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                break
            ev = json.loads(data)
            c = ev["choices"][0]
            if c.get("finish_reason"):
                finish = c["finish_reason"]
            elif "token" in c:
                toks.append(c["token"])
        out[key] = (resp.status, toks, finish)
    finally:
        conn.close()


def assert_no_kv_leak(server):
    mgr = server.loop.engine.mgr
    assert mgr.num_free == mgr.total_usable_blocks, \
        f"KV leak: {mgr.total_usable_blocks - mgr.num_free} blocks still held"


GEN_LEN = 16
PREFIX = [5, 6, 7]  # prefix_tokens=3 below: all PREFIX+tail prompts co-locate


class TestDrainUnderFire:
    def test_drain_with_step_faults_zero_stream_loss(self, model):
        factory = make_engine_factory(model)
        fleet = launch_fleet(
            2, factory, policy=PrefixAffinityPolicy(prefix_tokens=3),
            poll_interval_s=0.05,
            scheduler_config=SchedulerConfig(max_inflight=16, default_timeout_s=600.0),
            supervisor_policy=SupervisorPolicy(backoff_base_s=0.1, backoff_max_s=0.5))
        router, port = fleet.router, fleet.router_port
        try:
            pinned = router.policy.select(
                router.pool.snapshots(), prompt=PREFIX + [0])[0].id
            survivor = next(s.id for s in router.pool.snapshots() if s.id != pinned)
            pinned_idx = next(i for i in range(2) if fleet.replica_id(i) == pinned)
            pinned_server = fleet.servers[pinned_idx]
            survivor_server = fleet.servers[1 - pinned_idx]

            n_stream = 3  # < max_batch_size: all decode concurrently on pinned
            results = {}
            threads = [threading.Thread(
                target=stream_request, args=(port, PREFIX + [40 + i], GEN_LEN,
                                             results, i))
                for i in range(n_stream)]
            for t in threads:
                t.start()
            deadline = time.time() + 120
            while time.time() < deadline and router._open_forwards_on(pinned) < n_stream:
                time.sleep(0.005)
            assert router._open_forwards_on(pinned) == n_stream

            # ---- drain the pinned replica while its streams are mid-flight
            router.pool.start_drain(pinned, deadline_s=60.0)
            # new pinned-prefix traffic immediately lands on the survivor
            status, body = post_json(port, "/v1/completions",
                                     {"prompt": PREFIX + [90], "max_tokens": 4})
            assert status == 200, body
            assert body["replica"] == survivor
            assert len(body["choices"][0]["token_ids"]) == 4

            # ---- now set the draining replica's engine on fire: its next
            # step fails; the supervisor must recover WITHOUT dropping the
            # draining streams (they are the only thing keeping it alive)
            FAULTS.arm("engine.step", nth=1)
            for t in threads:
                t.join(timeout=600)
            assert not any(t.is_alive() for t in threads)
            assert FAULTS.fired("engine.step") == 1

            # ---- zero stream loss, token-exact
            solo_engine = factory()
            for i in range(n_stream):
                status, toks, finish = results[i]
                assert status == 200, (i, results[i])
                assert finish == "length", (i, results[i])
                solo = solo_engine.generate(
                    [PREFIX + [40 + i]], SamplingParams(max_new_tokens=GEN_LEN))[0]
                np.testing.assert_array_equal(toks, solo)

            # ---- the drain completes, the replica leaves, state -> removed
            drained = fleet.drain_replica(pinned, deadline_s=30.0, wait_timeout_s=60.0)
            assert drained is True
            assert router.pool.drain_status(pinned)["state"] == "removed"
            assert len(router.pool) == 1

            # ---- traffic keeps flowing on the shrunken fleet
            status, body = post_json(port, "/v1/completions",
                                     {"prompt": PREFIX + [91], "max_tokens": 4})
            assert status == 200 and body["replica"] == survivor

            # ---- no KV block leaked on either replica
            assert_no_kv_leak(pinned_server)
            assert_no_kv_leak(survivor_server)
        finally:
            fleet.shutdown(drain_timeout_s=5)


class TestHedgeRaceChaos:
    def test_hedge_both_respond_token_exact(self, model):
        factory = make_engine_factory(model)
        fleet = launch_fleet(
            2, factory, policy=PrefixAffinityPolicy(prefix_tokens=3),
            poll_interval_s=0.05, hedge_after_s=0.2,
            scheduler_config=SchedulerConfig(max_inflight=16, default_timeout_s=600.0))
        router, port = fleet.router, fleet.router_port
        try:
            # warm BOTH replicas directly (jit compiles outside the race) with
            # the same prompt-length bucket and decode budget the race uses
            for i, p in enumerate(fleet.ports):
                status, _ = post_json(p, "/v1/completions",
                                      {"prompt": PREFIX + [90 + i],
                                       "max_tokens": GEN_LEN})
                assert status == 200

            # slow the next engine steps past the hedge budget: the pinned
            # replica's first step eats fire #1 (no first token inside 0.2s),
            # the shadow's first step eats fire #2 — BOTH legs then respond,
            # and the router serves whichever wins the race
            FAULTS.arm("engine.step", action="delay", delay_s=0.6, times=2)
            results = {}
            stream_request(port, PREFIX + [40], GEN_LEN, results, "race")
            status, toks, finish = results["race"]
            assert status == 200 and finish == "length"
            solo = factory().generate(
                [PREFIX + [40]], SamplingParams(max_new_tokens=GEN_LEN))[0]
            np.testing.assert_array_equal(toks, solo)

            reg = router.registry
            won = (reg.get("paddlenlp_router_hedges_total").value(outcome="hedge_won")
                   + reg.get("paddlenlp_router_hedges_total").value(outcome="primary_won"))
            assert won == 1, "exactly one leg must win the fired hedge race"
            assert reg.get("paddlenlp_router_hedges_total").value(outcome="failed") == 0
            # both replicas saw the request (the loser leg really ran)
            n_seen = sum(
                1 for s in fleet.servers
                if (s.registry.get("paddlenlp_serving_requests_total") is not None))
            assert n_seen == 2
        finally:
            fleet.shutdown(drain_timeout_s=5)


class TestQuarantineVsRebuild:
    def test_poisoned_request_quarantines_without_restarting_streams(self, model):
        factory = make_engine_factory(model)
        registry = MetricsRegistry()
        server = ServingServer(
            factory(), registry=registry, engine_factory=factory,
            scheduler_config=SchedulerConfig(max_inflight=8, default_timeout_s=600.0))
        port = server.start_in_thread()
        try:
            results = {}
            # the healthy stream decodes with a frequency penalty: its logits
            # READ the device-side counts, so a quarantine that left the
            # failed step's uncommitted count updates behind would make the
            # regenerated tokens diverge — this pins the resync_counts path
            t = threading.Thread(
                target=stream_request, args=(port, PREFIX + [1], 24, results, "healthy"),
                kwargs={"frequency_penalty": 0.6})
            t.start()
            # wait until the healthy stream is visibly decoding
            deadline = time.time() + 120
            flowing = False
            while time.time() < deadline and not flowing:
                flowing = any(r.get("output_tokens", 0) > 0
                              for r in server.loop.inflight_info())
                time.sleep(0.005)
            assert flowing, "healthy stream never started"

            # a poisoned request: its stream callback raises on its THIRD
            # token — i.e. inside a multi-token decode step it shares with
            # the healthy slot, after the healthy slot's earlier-in-sweep
            # emits, so the step dies with healthy tokens already counted on
            # device but never emitted (the exact replay-double-count case).
            # The long prompt lands in an uncompiled prefill bucket, so the
            # poison is installed long before its first token can fire.
            bad_prompt = [(3 + 7 * j) % 90 + 1 for j in range(40)]
            bad = server.scheduler.submit(bad_prompt,
                                          SamplingParams(max_new_tokens=8))
            seen = {"n": 0}
            orig = bad._on_token

            def boom(tok, done):
                if seen["n"] >= 2:
                    raise RuntimeError("poisoned stream callback")
                seen["n"] += 1
                orig(tok, done)

            bad._on_token = boom
            req = bad.result(timeout=120)
            assert req.finish_reason == "engine_error"

            # the healthy stream never paused, token-exact vs a solo run
            t.join(timeout=600)
            status, toks, finish = results["healthy"]
            assert status == 200 and finish == "length"
            solo = factory().generate(
                [PREFIX + [1]],
                SamplingParams(max_new_tokens=24, frequency_penalty=0.6))[0]
            np.testing.assert_array_equal(toks, solo)

            # quarantine, not rebuild: the loop never left running
            assert server.loop.state == "running"
            assert registry.get("paddlenlp_serving_slot_quarantines_total").value() == 1
            assert registry.get("paddlenlp_serving_engine_restarts_total").value() == 0

            # /health surfaces the quarantine count
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request("GET", "/health")
            resp = conn.getresponse()
            health = json.loads(resp.read())
            conn.close()
            assert resp.status == 200
            assert health["scheduler"]["slot_quarantines"] == 1

            # the poisoned slot's KV was released; nothing leaked
            assert_no_kv_leak(server)
        finally:
            server.shutdown(drain_timeout_s=5)
