"""Sharded-engine recovery chaos: mesh-init failure inside a supervisor
rebuild.

A sharded replica that crashes mid-step is rebuilt by the engine-loop
supervisor exactly like a single-chip one — but its rebuild replays mesh and
NamedSharding-layout construction, which gets its own deterministic fault
point (``engine.shard_init``). With concurrent SSE streams in flight and
``engine.step`` + ``engine.shard_init`` armed:

- the first rebuild attempt fails INSIDE ShardedBackend.__init__ → the
  DEGRADED window extends (503 + Retry-After), no crash-loop;
- the second attempt brings a fresh sharded engine up and every stream
  finishes token-exact vs a solo run — zero stream loss;
- no KV block leaks on the sharded pool across the rebuild.

Runs on the conftest's 8 virtual CPU devices (tp=2 keeps compiles cheap)."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import (
    MetricsRegistry,
    SchedulerConfig,
    ServingServer,
    SupervisorPolicy,
)
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS


def get_json(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), dict(resp.getheaders())
    finally:
        conn.close()


def post_json(port, path, payload, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), dict(resp.getheaders())
    finally:
        conn.close()


class SSEStream:
    def __init__(self, port, payload, timeout=300):
        self.conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        self.conn.request("POST", "/v1/completions", body=json.dumps(payload),
                          headers={"Content-Type": "application/json"})
        self.resp = self.conn.getresponse()
        self.status = self.resp.status

    def events(self):
        while True:
            line = self.resp.readline()
            if not line:
                return
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                return
            yield json.loads(data)

    def close(self):
        self.conn.close()


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model(eight_devices):
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=8,
                      max_position_embeddings=256, eos_token_id=None, pad_token_id=0,
                      use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine(model):
    return InferenceEngine(model, mesh_shape=(1, 2), max_batch_size=4, block_size=4,
                           num_blocks=128, max_blocks_per_seq=32, decode_steps=4)


GEN_LEN = 12


class TestShardedRecovery:
    def test_shard_init_fault_in_rebuild_zero_stream_loss(self, model):
        n_stream = 4
        registry = MetricsRegistry()
        srv = ServingServer(
            make_engine(model),
            engine_factory=lambda: make_engine(model),
            supervisor_policy=SupervisorPolicy(max_retries=2, backoff_base_s=0.5,
                                               backoff_max_s=1.5),
            scheduler_config=SchedulerConfig(max_inflight=16, default_timeout_s=600.0),
            registry=registry,
        )
        port = srv.start_in_thread()
        try:
            # armed AFTER the first engine exists: the next shard_init is the
            # supervisor's rebuild — it must fail exactly once, so the loop
            # degrades twice-over (step fault, then rebuild fault) and still
            # recovers on rebuild attempt 2
            FAULTS.arm("engine.step", nth=3)
            FAULTS.arm("engine.shard_init", nth=1)

            results = {}

            def stream_worker(i):
                s = SSEStream(port, {"prompt": [5 + i, 6 + i, 7 + i],
                                     "max_tokens": GEN_LEN, "stream": True})
                assert s.status == 200
                toks, finish = [], None
                for ev in s.events():
                    c = ev["choices"][0]
                    if c.get("finish_reason"):
                        finish = c["finish_reason"]
                    elif "token" in c:
                        toks.append(c["token"])
                results[i] = (toks, finish)
                s.close()

            threads = [threading.Thread(target=stream_worker, args=(i,))
                       for i in range(n_stream)]
            for t in threads:
                t.start()

            deadline = time.time() + 120
            while time.time() < deadline and not srv.loop.degraded:
                time.sleep(0.01)
            assert srv.loop.degraded, "engine.step fault never tripped the supervisor"
            status, health, _ = get_json(port, "/health")
            assert status == 503 and health["status"] == "degraded"
            status, body, headers = post_json(
                port, "/v1/completions", {"prompt": [1, 2, 3], "max_tokens": 2})
            assert status == 503
            assert int(headers.get("Retry-After", 0)) >= 1

            for t in threads:
                t.join(timeout=600)
            assert not any(t.is_alive() for t in threads)

            # the failed mesh init actually happened, then was retried
            assert FAULTS.fired("engine.shard_init") == 1
            assert registry.get("paddlenlp_serving_engine_restarts_total").value() >= 1

            # zero stream loss, token-exact vs a solo sharded run
            assert len(results) == n_stream
            for i, (toks, finish) in results.items():
                assert finish == "length", (i, finish)
                assert len(toks) == GEN_LEN, (i, len(toks))
            solo = make_engine(model).generate(
                [[5, 6, 7]], SamplingParams(max_new_tokens=GEN_LEN))[0]
            np.testing.assert_array_equal(results[0][0], solo)

            # the rebuilt engine's sharded pool is whole: no leaked blocks
            eng = srv.loop.engine
            assert eng.mgr.num_free == eng.mgr.total_usable_blocks
            assert eng.stats()["backend"]["kind"] == "sharded"
        finally:
            srv.shutdown(drain_timeout_s=10)
