"""Engine-loop supervisor unit tests on a scripted stub engine (no jax
compute): degrade → triage → rebuild → requeue, bounded retries,
engine_error fast-clear, the 503 circuit breaker, stop() join reporting,
and the deadline/completion race.

The stub emits position-keyed tokens (token at absolute generated position p
is ``p % 50``), mirroring the real engine's (seed, absolute position)
sampling contract — so a requeued request whose streamed tokens were folded
into the prompt continues with identical tokens, and the tests can assert
exact end-to-end streams across a rebuild."""

import dataclasses
import threading
import time
from collections import Counter, deque

import pytest

from paddlenlp_tpu.serving import (
    DegradedError,
    EngineLoop,
    MetricsRegistry,
    Scheduler,
    SchedulerConfig,
    ServingMetrics,
    SupervisorPolicy,
)
from paddlenlp_tpu.utils.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# a dataclass so dataclasses.replace works on the supervisor's requeue path
@dataclasses.dataclass
class Sampling:
    max_new_tokens: int = 4
    eos_after: int = 0  # stub-only: emit done=True (an "EOS") after N tokens


class StubMgr:
    def __init__(self, total=64):
        self.block_size = 4
        self.max_blocks_per_seq = 16
        self.total_usable_blocks = total
        self.num_free = total
        self.lengths = {}
        self.free_calls = Counter()

    def free_seq(self, req_id):
        self.free_calls[req_id] += 1
        self.lengths.pop(req_id, None)


class StubRequest:
    def __init__(self, req_id, prompt_ids, sampling, stream_cb, trace):
        self.req_id = req_id
        self.prompt_ids = list(prompt_ids)
        self.sampling = sampling or Sampling()
        self.stream_cb = stream_cb
        self.trace = trace
        self.output_ids = []
        self.done = False
        self.aborted = False
        self.finish_reason = None
        self.arrival_t = time.time()
        self.sched_t = None
        self.first_token_t = None
        self.finish_t = None
        self.queue_wait = None
        self.ttft = None
        self.decode_time = None


class StubEngine:
    """One token per active request per step; position-keyed token values."""

    def __init__(self, max_batch_size=4, fail_on_step=(), step_hook=None,
                 fail_after_stream_on_step=None):
        self.mgr = StubMgr()
        self.max_batch_size = max_batch_size
        self.waiting = deque()
        self.slots = [None] * max_batch_size
        self.spec_stats = {"drafted": 0, "accepted": 0}
        self.num_preemptions = 0
        self.step_count = 0
        self.fail_on_step = set(fail_on_step)
        # emit that step's tokens (incl. a possible done=True), THEN raise —
        # the stream-closed-but-crash-ate-the-finish race
        self.fail_after_stream_on_step = fail_after_stream_on_step
        self.step_hook = step_hook  # called at step start (blocking tests)
        self.abort_calls = []
        self._ids = iter(range(10_000))

    # ----------------------------------------------------------- engine api
    def add_request(self, prompt_ids, sampling=None, stream_cb=None, trace=None):
        req = StubRequest(next(self._ids), prompt_ids, sampling, stream_cb, trace)
        self.mgr.lengths[req.req_id] = len(req.prompt_ids)
        self.waiting.append(req)
        return req.req_id

    def has_work(self):
        return bool(self.waiting) or any(r is not None for r in self.slots)

    def abort(self, req_id):
        self.abort_calls.append(req_id)
        for i, req in enumerate(self.waiting):
            if req.req_id == req_id:
                del self.waiting[i]
                return self._finish_abort(req)
        for slot, req in enumerate(self.slots):
            if req is not None and req.req_id == req_id:
                self.slots[slot] = None
                return self._finish_abort(req)
        return None

    def _finish_abort(self, req):
        self.mgr.free_seq(req.req_id)
        req.done = True
        req.aborted = True
        req.finish_reason = "abort"
        req.finish_t = time.time()
        return req

    def release_request(self, req_id):
        """Slot-quarantine support, mirroring the real engine: drop the
        request + free its KV without touching finish fields."""
        for i, req in enumerate(self.waiting):
            if req.req_id == req_id:
                del self.waiting[i]
                return True
        for slot, req in enumerate(self.slots):
            if req is not None and req.req_id == req_id:
                self.mgr.free_seq(req_id)
                self.slots[slot] = None
                return True
        return False

    def stats(self):
        return {"queue_depth": len(self.waiting),
                "running": sum(1 for r in self.slots if r is not None),
                "free_blocks": self.mgr.num_free,
                "num_preemptions": self.num_preemptions}

    def reset(self):
        self.waiting.clear()
        self.slots = [None] * self.max_batch_size
        self.mgr = StubMgr()

    def step(self):
        self.step_count += 1
        if self.step_hook is not None:
            self.step_hook(self)
        if self.step_count in self.fail_on_step:
            raise RuntimeError(f"stub engine exploded at step {self.step_count}")
        finished = []
        for i in range(self.max_batch_size):
            if self.slots[i] is None and self.waiting:
                req = self.waiting.popleft()
                req.sched_t = time.time()
                self.slots[i] = req
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            pos = len(req.prompt_ids) + len(req.output_ids)  # absolute position
            tok = pos % 50
            if req.first_token_t is None:
                req.first_token_t = time.time()
            req.output_ids.append(tok)
            eos_after = getattr(req.sampling, "eos_after", 0)
            req.done = (len(req.output_ids) >= req.sampling.max_new_tokens
                        or (eos_after and len(req.output_ids) >= eos_after))
            if req.stream_cb is not None:
                try:
                    req.stream_cb(tok, req.done)
                except Exception as e:
                    # per-request attribution, mirroring the real engine's
                    # _emit: a poisoned callback names its request
                    if getattr(e, "req_id", None) is None:
                        e.req_id = req.req_id
                    raise
            if req.done:
                req.finish_reason = "length"
                req.finish_t = time.time()
                self.mgr.free_seq(req.req_id)
                self.slots[i] = None
                finished.append(req)
        if self.step_count == self.fail_after_stream_on_step:
            raise RuntimeError(f"stub engine exploded AFTER streaming at step {self.step_count}")
        return finished


def expected_tokens(prompt, n):
    return [(len(prompt) + i) % 50 for i in range(n)]


def make_loop(fail_on_step=(), factory_fails=0, policy=None, **kw):
    """Loop + factory that counts engines; engine #1 fails at the given steps."""
    made = []

    def factory():
        eng = StubEngine(fail_on_step=fail_on_step if not made else ())
        made.append(eng)
        return eng

    engine = factory()
    loop = EngineLoop(engine, metrics=ServingMetrics(engine, MetricsRegistry()),
                      engine_factory=factory,
                      policy=policy or SupervisorPolicy(backoff_base_s=0.02, backoff_max_s=0.1),
                      idle_wait_s=0.01, **kw)
    return loop, made


class TestSupervisor:
    def test_retry_across_rebuild_streams_identical_tokens(self):
        loop, made = make_loop(fail_on_step=(3,))
        loop.start()
        try:
            prompt = [7, 8, 9]
            h = loop.submit(prompt, Sampling(max_new_tokens=6))
            req = h.result(timeout=10)
            # 2 tokens streamed pre-crash + 4 post-rebuild == uninterrupted run
            assert req.output_ids == expected_tokens(prompt, 6)
            assert list(h._streamed) == expected_tokens(prompt, 6)
            assert req.finish_reason == "length"
            assert req.prompt_ids == prompt  # retry suffix unfolded
            assert h.retries == 1
            assert len(made) == 2  # original + rebuild
            assert loop.metrics.engine_restarts.value() == 1
            assert loop.metrics.request_retries.value() == 1
            assert loop.state == "running"
        finally:
            assert loop.stop(drain=False) is True

    def test_retry_budget_exhausted_fails_engine_error(self):
        # both the first AND second engines fail -> a max_retries=1 request
        # rides one rebuild then fast-clears on the second failure
        made = []

        def factory():
            eng = StubEngine(fail_on_step=(2,) if len(made) < 2 else ())
            made.append(eng)
            return eng

        engine = factory()
        registry = MetricsRegistry()
        loop = EngineLoop(engine, metrics=ServingMetrics(engine, registry),
                          engine_factory=factory,
                          policy=SupervisorPolicy(max_retries=1, backoff_base_s=0.02),
                          idle_wait_s=0.01)
        loop.start()
        try:
            h = loop.submit([1, 2], Sampling(max_new_tokens=8))
            req = h.result(timeout=10)
            assert req.finish_reason == "engine_error"
            assert h.retries == 1
            # whatever streamed before the final failure is preserved
            assert req.output_ids == list(h._streamed)
            assert registry.get("paddlenlp_serving_requests_total").value(status="engine_error", priority="interactive", tenant="default") == 1
        finally:
            loop.stop(drain=False)

    def test_max_retries_zero_fast_clears(self):
        loop, _made = make_loop(fail_on_step=(2,))
        loop.start()
        try:
            h_keep = loop.submit([1, 2, 3], Sampling(max_new_tokens=5))
            h_fail = loop.submit([4, 5, 6], Sampling(max_new_tokens=5), max_retries=0)
            req_fail = h_fail.result(timeout=10)
            req_keep = h_keep.result(timeout=10)
            assert req_fail.finish_reason == "engine_error"
            assert req_keep.finish_reason == "length"
            assert req_keep.output_ids == expected_tokens([1, 2, 3], 5)
        finally:
            loop.stop(drain=False)

    def test_degraded_circuit_breaker_503(self):
        FAULTS.arm("engine.rebuild", nth=1)  # first rebuild attempt fails
        loop, _ = make_loop(fail_on_step=(2,),
                            policy=SupervisorPolicy(backoff_base_s=0.3, backoff_max_s=1.0))
        sched = Scheduler(loop, SchedulerConfig(max_inflight=8))
        loop.start()
        try:
            h = sched.submit([1, 2], Sampling(max_new_tokens=8))
            deadline = time.time() + 5
            while not loop.degraded and time.time() < deadline:
                time.sleep(0.005)
            assert loop.degraded
            with pytest.raises(DegradedError) as ei:
                sched.submit([3, 4], Sampling(max_new_tokens=2))
            assert ei.value.retry_after_s > 0
            assert sched.stats()["rejected_degraded"] >= 1
            assert sched.stats()["engine_state"] == "degraded"
            # recovery completes the original request despite the failed rebuild
            req = h.result(timeout=10)
            assert req.finish_reason == "length"
            assert loop.state == "running"
            # and admission works again
            h2 = sched.submit([9], Sampling(max_new_tokens=2))
            assert h2.result(timeout=10).finish_reason == "length"
        finally:
            loop.stop(drain=False)

    def test_stream_closed_request_not_requeued_past_eos(self):
        """A request whose done=True (EOS) token streamed in the crashing step
        must resolve as finished — requeueing it would generate past the end
        of a completed sequence."""
        made = []

        def factory():
            eng = StubEngine(fail_after_stream_on_step=2 if not made else None)
            made.append(eng)
            return eng

        engine = factory()
        loop = EngineLoop(engine, metrics=ServingMetrics(engine, MetricsRegistry()),
                          engine_factory=factory,
                          policy=SupervisorPolicy(backoff_base_s=0.02), idle_wait_s=0.01)
        loop.start()
        try:
            # EOS after 2 tokens (mid-budget): the done token lands on exactly
            # the step that then explodes
            h = loop.submit([1, 2, 3], Sampling(max_new_tokens=10, eos_after=2))
            req = h.result(timeout=10)
            assert req.finish_reason == "stop"
            assert req.output_ids == expected_tokens([1, 2, 3], 2)  # nothing past EOS
            assert h.retries == 0
            # budget-exhausted variant of the same race resolves as "length"
            h2 = loop.submit([4, 5], Sampling(max_new_tokens=3))
            assert h2.result(timeout=10).finish_reason == "length"
        finally:
            loop.stop(drain=False)

    def test_cancel_racing_crash_resolves_as_abort(self):
        release = threading.Event()

        def hook(eng):
            if eng.step_count == 2:
                release.wait(timeout=5)  # hold step 2 open while we cancel
                raise RuntimeError("boom during the held step")

        engine = StubEngine(step_hook=hook)
        registry = MetricsRegistry()
        loop = EngineLoop(engine, metrics=ServingMetrics(engine, registry),
                          policy=SupervisorPolicy(backoff_base_s=0.02), idle_wait_s=0.01)
        loop.start()
        try:
            h = loop.submit([1, 2], Sampling(max_new_tokens=10))
            while not h._streamed:  # one token delivered
                time.sleep(0.005)
            loop.cancel(h)  # sets _cancelled synchronously; cmd never drains
            release.set()  # now the engine explodes with the cancel pending
            req = h.result(timeout=10)
            assert req.finish_reason == "abort" and req.aborted
            assert registry.get("paddlenlp_serving_requests_total").value(status="abort", priority="interactive", tenant="default") == 1
            assert registry.get("paddlenlp_serving_requests_total").value(status="engine_error", priority="interactive", tenant="default") == 0
        finally:
            loop.stop(drain=False)

    def test_retry_timing_spans_degraded_window(self):
        loop, _made = make_loop(fail_on_step=(3,),
                                policy=SupervisorPolicy(backoff_base_s=0.2, backoff_max_s=0.5))
        loop.start()
        try:
            h = loop.submit([7, 8, 9], Sampling(max_new_tokens=6))
            req = h.result(timeout=10)
            # timing anchors rebased to the ORIGINAL submission, so e2e/TTFT
            # include the pre-crash stint and the degraded window
            assert req.arrival_t == h.submitted_t
            assert req.first_token_t == h._first_token_t
            assert req.finish_t - req.arrival_t >= 0.2  # covers >= one backoff
        finally:
            loop.stop(drain=False)

    def test_stop_reports_failed_join_with_phase(self):
        release = threading.Event()

        def hook(_eng):
            release.wait(timeout=30)

        engine = StubEngine(step_hook=hook)
        loop = EngineLoop(engine, metrics=ServingMetrics(engine, MetricsRegistry()),
                          idle_wait_s=0.01)
        loop.start()
        h = loop.submit([1], Sampling(max_new_tokens=1))
        time.sleep(0.1)  # loop is now blocked inside engine.step
        assert loop.stop(drain=False, join_timeout_s=0.2) is False
        assert loop._phase == "step"  # last-known phase of the wedged thread
        release.set()
        h.result(timeout=10)
        assert loop.stop(drain=False, join_timeout_s=10.0) is True

    def test_stop_while_degraded_resolves_stash(self):
        # rebuild never succeeds -> requests sit in the requeue stash; stop()
        # must resolve them (result() returns None) instead of stranding clients
        made = []

        def bad_factory():
            made.append(1)
            raise RuntimeError("no engine for you")

        engine = StubEngine(fail_on_step=(2,))
        loop = EngineLoop(engine, metrics=ServingMetrics(engine, MetricsRegistry()),
                          engine_factory=bad_factory,
                          policy=SupervisorPolicy(backoff_base_s=0.02, backoff_max_s=0.05),
                          idle_wait_s=0.01)
        loop.start()
        h = loop.submit([1, 2], Sampling(max_new_tokens=8))
        deadline = time.time() + 5
        while not loop.degraded and time.time() < deadline:
            time.sleep(0.005)
        assert loop.stop(drain=False, join_timeout_s=10.0) is True
        assert h.result(timeout=1) is None


class TestDeadlineCompletionRace:
    def test_finish_and_deadline_same_iteration(self):
        """A request that finishes in the same loop iteration its deadline
        expires must resolve exactly once as finished — never double-finished,
        never a double KV free, never a post-finish abort."""
        def hook(_eng):
            time.sleep(0.15)  # deadline (0.06s) expires INSIDE this step

        engine = StubEngine(step_hook=hook)
        loop = EngineLoop(engine, metrics=ServingMetrics(engine, MetricsRegistry()),
                          idle_wait_s=0.01)
        loop.start()
        try:
            h = loop.submit([1, 2, 3], Sampling(max_new_tokens=1), deadline_s=0.06)
            req = h.result(timeout=10)
            # completion won the race: not clawed back by deadline enforcement
            assert req.finish_reason == "length"
            assert h.timed_out is False
            assert engine.mgr.free_calls[req.req_id] == 1  # KV freed exactly once
            assert engine.abort_calls == []  # no abort issued for a done request
            # a late cancel on the finished handle is also a no-op
            loop.cancel(h)
            time.sleep(0.1)
            assert engine.abort_calls == []
            assert engine.mgr.free_calls[req.req_id] == 1
        finally:
            loop.stop(drain=False)

    def test_deadline_wins_when_request_not_done(self):
        release = threading.Event()

        def hook(eng):
            # park the loop long enough for the deadline to expire before the
            # FIRST token is produced, then let it continue
            if eng.step_count == 1:
                release.wait(timeout=5)

        engine = StubEngine(step_hook=hook)
        loop = EngineLoop(engine, metrics=ServingMetrics(engine, MetricsRegistry()),
                          idle_wait_s=0.01)
        loop.start()
        try:
            h = loop.submit([1, 2, 3], Sampling(max_new_tokens=50), deadline_s=0.05)
            time.sleep(0.1)
            release.set()
            req = h.result(timeout=10)
            assert h.timed_out and req.aborted and req.finish_reason == "abort"
            assert engine.mgr.free_calls[req.req_id] <= 1
        finally:
            loop.stop(drain=False)


class TestSlotQuarantine:
    """Slot-level partial recovery (ISSUE 11): a failure the engine attributed
    to ONE request quarantines only that slot — unaffected streams never
    pause, the engine is never rebuilt, the 503 breaker never trips — with a
    bounded escalation ladder back to the full rebuild path."""

    @staticmethod
    def _poison(handle, after=0):
        """Make the handle's stream callback raise once ``after`` tokens have
        been delivered (the engine attributes the failure to this request)."""
        orig = handle._on_token
        seen = {"n": 0}

        def boom(tok, done):
            if seen["n"] >= after:
                raise RuntimeError("poisoned stream callback")
            seen["n"] += 1
            orig(tok, done)

        handle._on_token = boom

    def test_poisoned_request_quarantined_not_rebuilt(self):
        loop, made = make_loop()
        loop.start()
        try:
            healthy = loop.submit([1, 2], Sampling(max_new_tokens=6))
            bad = loop.submit([9], Sampling(max_new_tokens=6))
            self._poison(bad)
            # the poisoned request fails alone, in-band
            bad_req = bad.result(timeout=30)
            assert bad_req.finish_reason == "engine_error"
            # the healthy stream never paused: full token-exact output, no
            # requeue, no rebuild, loop still running
            req = healthy.result(timeout=30)
            assert req.finish_reason == "length"
            assert list(healthy.output_ids) == expected_tokens([1, 2], 6)
            assert healthy.retries == 0
            assert len(made) == 1  # the factory never ran again
            assert loop.state == "running"
            assert loop.slot_quarantines == 1
            reg = loop.metrics.registry
            assert reg.get("paddlenlp_serving_slot_quarantines_total").value() == 1
            assert reg.get("paddlenlp_serving_engine_restarts_total").value() == 0
            # engine-side: the poisoned slot + its KV were released
            eng = made[0]
            assert all(r is None for r in eng.slots)
            assert eng.mgr.lengths == {}
        finally:
            loop.stop(drain=False)

    def test_finished_request_swept_not_blamed(self):
        """A request that finished in the SAME step the poison killed must
        resolve as its completion (the crash only ate the bookkeeping)."""
        loop, made = make_loop()
        loop.start()
        try:
            done_h = loop.submit([1, 2, 3], Sampling(max_new_tokens=1))
            bad = loop.submit([9], Sampling(max_new_tokens=6))
            self._poison(bad)
            assert bad.result(timeout=30).finish_reason == "engine_error"
            req = done_h.result(timeout=30)
            assert req.finish_reason == "length"
            assert list(done_h.output_ids) == expected_tokens([1, 2, 3], 1)
            assert len(made) == 1 and loop.state == "running"
        finally:
            loop.stop(drain=False)

    def test_quarantine_budget_escalates_to_full_rebuild(self):
        loop, made = make_loop(policy=SupervisorPolicy(
            max_slot_quarantines=1, max_retries=0,
            backoff_base_s=0.02, backoff_max_s=0.1))
        loop.start()
        try:
            h1 = loop.submit([1], Sampling(max_new_tokens=4))
            self._poison(h1)
            assert h1.result(timeout=30).finish_reason == "engine_error"
            assert len(made) == 1 and loop.slot_quarantines == 1
            # second poison inside the window: budget spent -> full rebuild
            h2 = loop.submit([2], Sampling(max_new_tokens=4))
            self._poison(h2)
            assert h2.result(timeout=30).finish_reason == "engine_error"
            deadline = time.time() + 10
            while time.time() < deadline and not (len(made) == 2
                                                  and loop.state == "running"):
                time.sleep(0.01)
            assert len(made) == 2  # escalation really rebuilt the engine
            assert loop.slot_quarantines == 1  # no second quarantine
            reg = loop.metrics.registry
            assert reg.get("paddlenlp_serving_engine_restarts_total").value() == 1
        finally:
            loop.stop(drain=False)

    def test_slot_rebuild_fault_escalates(self):
        """engine.slot_rebuild armed: the quarantine itself fails (before KV
        release) and the supervisor falls back to the full rebuild path."""
        FAULTS.arm("engine.slot_rebuild", nth=1)
        loop, made = make_loop(policy=SupervisorPolicy(
            max_retries=0, backoff_base_s=0.02, backoff_max_s=0.1))
        loop.start()
        try:
            h = loop.submit([1], Sampling(max_new_tokens=4))
            self._poison(h)
            assert h.result(timeout=30).finish_reason == "engine_error"
            assert FAULTS.fired("engine.slot_rebuild") == 1
            deadline = time.time() + 10
            while time.time() < deadline and len(made) < 2:
                time.sleep(0.01)
            assert len(made) == 2  # escalated: engine rebuilt
            assert loop.slot_quarantines == 0  # the quarantine never landed
        finally:
            loop.stop(drain=False)

    def test_unaffected_stream_tokens_flow_during_quarantine(self):
        """Stream continuity: the healthy handle's token queue keeps draining
        while the poisoned slot is quarantined (no degraded pause, no 503)."""
        loop, made = make_loop()
        loop.start()
        scheduler = Scheduler(loop, SchedulerConfig(max_inflight=8))
        try:
            healthy = scheduler.submit([1, 2], Sampling(max_new_tokens=8))
            bad = scheduler.submit([9], Sampling(max_new_tokens=8))
            self._poison(bad, after=1)
            toks = list(healthy.tokens(timeout=30))
            assert toks == expected_tokens([1, 2], 8)
            assert bad.result(timeout=30).finish_reason == "engine_error"
            # the breaker never tripped: a new admission sails through
            extra = scheduler.submit([3], Sampling(max_new_tokens=2))
            assert extra.result(timeout=30).finish_reason == "length"
            assert scheduler.stats()["slot_quarantines"] == 1
            assert loop.state == "running"
        finally:
            loop.stop(drain=False)
