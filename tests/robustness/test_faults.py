"""Fault-injection harness unit tests: trigger determinism, actions, arming.

The harness is the foundation the chaos suite stands on — if nth/seed
semantics drift, every downstream chaos test silently stops testing what it
claims to. Stdlib-only (no jax, no engine)."""

import os
import time

import pytest

from paddlenlp_tpu.utils.faults import (
    CATALOG,
    FAULTS,
    FaultPoint,
    FaultRegistry,
    InjectedFault,
    _parse_spec,
)


@pytest.fixture(autouse=True)
def _clean_registry():
    FAULTS.reset()
    yield
    FAULTS.reset()


class TestTriggerSpecs:
    def test_unknown_name_rejected_at_construction(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            FaultPoint("no.such.point")
        with pytest.raises(ValueError, match="unknown fault point"):
            FAULTS.arm("no.such.point")

    def test_disarmed_fire_is_noop(self):
        FaultPoint("engine.step").fire()  # nothing armed: must not raise

    def test_nth_fires_on_exact_hit(self):
        FAULTS.arm("engine.step", nth=3)
        point = FaultPoint("engine.step")
        point.fire()
        point.fire()
        with pytest.raises(InjectedFault) as ei:
            point.fire()
        assert ei.value.hit == 3 and ei.value.point == "engine.step"
        # times=1 default: the 3rd hit fired, later hits pass through
        point.fire()
        assert FAULTS.hits("engine.step") == 4
        assert FAULTS.fired("engine.step") == 1

    def test_nth_list(self):
        FAULTS.arm("engine.step", nth=(1, 3), times=None)
        point = FaultPoint("engine.step")
        outcomes = []
        for _ in range(4):
            try:
                point.fire()
                outcomes.append(False)
            except InjectedFault:
                outcomes.append(True)
        assert outcomes == [True, False, True, False]

    def test_every_hit_with_times_cap(self):
        FAULTS.arm("engine.step", times=2)
        point = FaultPoint("engine.step")
        fired = 0
        for _ in range(5):
            try:
                point.fire()
            except InjectedFault:
                fired += 1
        assert fired == 2

    def test_probability_deterministic_under_seed(self):
        def run(seed):
            reg = FaultRegistry()
            reg._env_loaded = True
            reg.arm("engine.step", p=0.5, seed=seed, times=None)
            out = []
            for _ in range(32):
                try:
                    reg.fire("engine.step")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        a, b = run(7), run(7)
        assert a == b and 1 in a and 0 in a  # same seed, same chaos
        assert run(8) != a  # different seed, different chaos

    def test_delay_action_sleeps_without_raising(self):
        FAULTS.arm("engine.step", action="delay", delay_s=0.05)
        t0 = time.monotonic()
        FaultPoint("engine.step").fire()
        assert time.monotonic() - t0 >= 0.045

    def test_partial_action_truncates_then_raises(self, tmp_path):
        f = tmp_path / "shard.bin"
        f.write_bytes(b"x" * 1000)
        FAULTS.arm("ckpt.write_shard", action="partial")
        with pytest.raises(InjectedFault):
            FaultPoint("ckpt.write_shard").fire(file=str(f))
        assert f.stat().st_size == 500  # torn, not missing

    def test_nth_and_p_mutually_exclusive(self):
        with pytest.raises(ValueError, match="nth= OR p="):
            FAULTS.arm("engine.step", nth=2, p=0.5)


class TestArming:
    def test_spec_string_parsing(self):
        name, spec = _parse_spec("ckpt.write_shard:nth=2,5:action=partial:times=3")
        assert name == "ckpt.write_shard"
        assert spec.nth == (2, 5) and spec.action == "partial" and spec.times == 3
        with pytest.raises(ValueError):
            _parse_spec("x:badfield")
        with pytest.raises(ValueError):
            _parse_spec("x:what=1")

    def test_arm_from_spec_multiple(self):
        FAULTS.arm_from_spec("engine.step:nth=1; serving.submit:p=0.25:seed=3")
        assert FAULTS.armed("engine.step").nth == (1,)
        assert FAULTS.armed("serving.submit").p == 0.25

    def test_env_arming(self, monkeypatch):
        monkeypatch.setenv("PDNLP_TPU_FAULTS", "serving.submit:nth=1")
        reg = FaultRegistry()
        reg.load_env()
        assert reg.armed("serving.submit") is not None
        # idempotent: second load does not re-arm after a reset
        reg.reset()
        reg.load_env()
        assert reg.armed("serving.submit") is None

    def test_disarm_and_reset(self):
        FAULTS.arm("engine.step", nth=1)
        FAULTS.arm("serving.submit", nth=1)
        FAULTS.disarm("engine.step")
        assert FAULTS.armed("engine.step") is None
        assert FAULTS.armed("serving.submit") is not None
        FAULTS.reset()
        assert FAULTS.armed("serving.submit") is None
        assert not FAULTS._enabled

    def test_catalog_docs_nonempty(self):
        for name, doc in CATALOG.items():
            assert doc and len(doc) >= 20, name
