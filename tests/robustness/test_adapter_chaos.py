"""Multi-LoRA adapter chaos (ISSUE 16 acceptance, robustness side).

Two incidents against the multi-tenant serving stack:

- ``engine.adapter_load`` armed mid-stream: a poisoned adapter hot-load is
  attributed to the ONE request that asked for it — non-retryable requests
  resolve in-band with ``finish_reason="engine_error"``, retryable ones
  complete token-exact, every other tenant's stream decodes uninterrupted,
  and the pool never leaks a slot (the full-rebuild path stays cold);
- eviction under pressure: with every pool slot pinned by in-flight
  requests, a third adapter's admission defers (like KV pressure) instead
  of evicting an in-use adapter; the moment a pin drops it loads into the
  LRU-evicted slot and finishes token-exact.

CPU-only, tiny model — tier-1 speed."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import MetricsRegistry, SchedulerConfig, ServingServer
from paddlenlp_tpu.serving.tenancy import AdapterRegistry
from paddlenlp_tpu.serving.tenancy.adapters import adapter_dims_from_config
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS

GEN_LEN = 24
ENG_KW = dict(max_batch_size=4, block_size=4, num_blocks=128,
              max_blocks_per_seq=32, decode_steps=4)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def adapter_source(cfg, idx, rank=4):
    rng = np.random.default_rng(1000 + idx)
    return {proj: {"A": rng.standard_normal((cfg.num_hidden_layers, d_in, rank)).astype(np.float32) * 0.02,
                   "B": rng.standard_normal((cfg.num_hidden_layers, rank, d_out)).astype(np.float32) * 0.02}
            for proj, (d_in, d_out) in adapter_dims_from_config(cfg).items()}


def make_registry(cfg, ids, pool_slots):
    reg = AdapterRegistry(config=cfg, max_rank=4, pool_slots=pool_slots)
    for i, aid in enumerate(ids):
        reg.add(aid, adapter_source(cfg, i))
    return reg


def solo_tokens(model, registry, prompt, adapter_id, n=GEN_LEN):
    """Uncontended single-request run: the token-identity reference."""
    eng = InferenceEngine(model, adapter_registry=registry, **ENG_KW)
    rid = eng.add_request(list(prompt), SamplingParams(max_new_tokens=n),
                          adapter_id=adapter_id)
    done = {}
    while eng.has_work():
        for req in eng.step():
            done[req.req_id] = req
    return done[rid].output_ids


def assert_no_slot_leak(reg):
    st = reg.stats()
    assert st["pinned"] == 0, st
    assert st["free_slots"] + st["resident"] == st["pool_slots"], st


class Stream(threading.Thread):
    """One SSE completion; records tokens/finish and flags the first token."""

    def __init__(self, port, payload):
        super().__init__()
        self.port, self.payload = port, dict(payload)
        self.payload.setdefault("stream", True)
        self.tokens, self.finish, self.error = [], None, None
        self.first_token = threading.Event()

    def run(self):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", self.port, timeout=300)
            conn.request("POST", "/v1/completions", body=json.dumps(self.payload),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200, resp.read()
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data: ") or line == b"data: [DONE]":
                    if line == b"data: [DONE]":
                        break
                    continue
                c = json.loads(line[len(b"data: "):])["choices"][0]
                if c.get("finish_reason"):
                    self.finish = c["finish_reason"]
                elif "token" in c:
                    self.tokens.append(c["token"])
                    self.first_token.set()
            conn.close()
        except Exception as e:  # surfaced by the main thread's asserts
            self.error = e


class TestAdapterLoadFault:
    def test_poisoned_hot_load_quarantines_only_its_tenant(self, model):
        cfg = model.config
        registry = make_registry(cfg, ["ad-a", "ad-b"], pool_slots=4)
        metrics = MetricsRegistry()
        srv = ServingServer(
            InferenceEngine(model, adapter_registry=registry, **ENG_KW),
            scheduler_config=SchedulerConfig(max_inflight=8, default_timeout_s=600.0),
            registry=metrics)
        port = srv.start_in_thread()
        try:
            # two bystander tenants decoding BEFORE the fault arms: one on an
            # already-resident adapter, one on the base model
            bystanders = [
                Stream(port, {"prompt": [5, 6, 7], "max_tokens": GEN_LEN,
                              "adapter_id": "ad-b", "tenant": "globex"}),
                Stream(port, {"prompt": [8, 9, 10], "max_tokens": GEN_LEN,
                              "tenant": "base"}),
            ]
            for s in bystanders:
                s.start()
            for s in bystanders:
                assert s.first_token.wait(timeout=120), s.error

            # the NEXT adapter hot-load is poisoned; ad-a is not resident, so
            # the acme request below is the one that trips it
            FAULTS.arm("engine.adapter_load", nth=1)
            victim = Stream(port, {"prompt": [11, 12, 13], "max_tokens": GEN_LEN,
                                   "adapter_id": "ad-a", "tenant": "acme",
                                   "max_retries": 0})
            victim.start()
            victim.join(timeout=300)
            assert not victim.is_alive() and victim.error is None
            # in-band engine_error for the poisoned tenant, nobody else
            assert victim.finish == "engine_error", victim.finish
            assert len(victim.tokens) < GEN_LEN

            for s in bystanders:
                s.join(timeout=300)
                assert s.error is None
                assert s.finish == "length" and len(s.tokens) == GEN_LEN

            # slot-level quarantine, not a full engine rebuild
            assert metrics.get(
                "paddlenlp_serving_slot_quarantines_total").value() >= 1
            restarts = metrics.get("paddlenlp_serving_engine_restarts_total")
            assert restarts is None or (restarts.value() or 0) == 0
            assert not srv.loop.degraded

            # the fault consumed its one shot: the SAME adapter now loads and
            # finishes token-exact against an uncontended reference run
            retry = Stream(port, {"prompt": [11, 12, 13], "max_tokens": GEN_LEN,
                                  "adapter_id": "ad-a", "tenant": "acme"})
            retry.start()
            retry.join(timeout=300)
            assert retry.error is None and retry.finish == "length"
            np.testing.assert_array_equal(
                retry.tokens,
                solo_tokens(model, make_registry(cfg, ["ad-a", "ad-b"], 4),
                            [11, 12, 13], "ad-a"))
            # bystander token-identity: the incident next door changed nothing
            np.testing.assert_array_equal(
                bystanders[0].tokens,
                solo_tokens(model, make_registry(cfg, ["ad-a", "ad-b"], 4),
                            [5, 6, 7], "ad-b"))

            # tenant label lands on the failure accounting too
            text = metrics.expose()
            assert ('paddlenlp_serving_requests_total{status="engine_error",'
                    'priority="interactive",tenant="acme"}') in text
            assert ('paddlenlp_serving_requests_total{status="length",'
                    'priority="interactive",tenant="globex"}') in text

            assert_no_slot_leak(registry)
            free0 = srv.loop.engine.mgr.num_free
            assert free0 == srv.loop.engine.mgr.total_usable_blocks \
                or srv.loop.engine.prefix_cache_enabled
        finally:
            srv.shutdown(drain_timeout_s=5)


class TestEvictionUnderPressure:
    def test_pinned_adapters_survive_pool_pressure(self, model):
        cfg = model.config
        registry = make_registry(cfg, ["ad-a", "ad-b", "ad-c"], pool_slots=2)
        srv = ServingServer(
            InferenceEngine(model, adapter_registry=registry, **ENG_KW),
            scheduler_config=SchedulerConfig(max_inflight=8, default_timeout_s=600.0),
            registry=MetricsRegistry())
        port = srv.start_in_thread()
        try:
            pinned = [
                Stream(port, {"prompt": [5, 6, 7], "max_tokens": 32,
                              "adapter_id": "ad-a", "tenant": "acme"}),
                Stream(port, {"prompt": [8, 9, 10], "max_tokens": 32,
                              "adapter_id": "ad-b", "tenant": "globex"}),
            ]
            for s in pinned:
                s.start()
            for s in pinned:
                assert s.first_token.wait(timeout=120), s.error
            assert registry.stats()["pinned"] == 2

            # both slots pinned: ad-c's admission must DEFER (adapter
            # pressure), never evict an in-use adapter
            misses0 = registry.misses
            third = Stream(port, {"prompt": [11, 12, 13], "max_tokens": 8,
                                  "adapter_id": "ad-c", "tenant": "initech"})
            third.start()
            deadline = time.time() + 60
            while time.time() < deadline and registry.misses == misses0:
                time.sleep(0.005)
            assert registry.misses > misses0, "ad-c admission never attempted"
            if not (pinned[0].finish or pinned[1].finish):
                # pressure window still open: the residents must be the two
                # pinned adapters, untouched
                assert set(registry.resident()) == {"ad-a", "ad-b"}
                assert registry.stats()["evictions"] == 0

            for s in pinned + [third]:
                s.join(timeout=300)
                assert s.error is None
                assert s.finish == "length", (s.payload, s.finish)
            # the deferred adapter eventually evicted a RELEASED slot and ran
            assert registry.stats()["evictions"] >= 1
            assert "ad-c" in registry.resident()
            np.testing.assert_array_equal(
                third.tokens,
                solo_tokens(model, make_registry(cfg, ["ad-a", "ad-b", "ad-c"], 2),
                            [11, 12, 13], "ad-c", n=8))
            assert_no_slot_leak(registry)
        finally:
            srv.shutdown(drain_timeout_s=5)
