"""Checkpoint durability chaos tests (ISSUE 3 acceptance, trainer side).

A fault injected mid-optimizer-shard write must leave NO commit.json; resume
auto-discovery must skip the torn dir and restore the previous committed
step; rotation must never remove the fallback target or an uncommitted dir.

Exercised at the ``unified_checkpoint`` layer directly (the container's jax
lacks ``jax.sharding.AxisType``, so ``Trainer.__init__`` — which builds a
mesh — cannot run in tier-1; the protocol functions are mesh-free)."""

import json
import os
import time

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlenlp_tpu.trainer.trainer import TrainState
from paddlenlp_tpu.trainer.trainer_callback import TrainerState
from paddlenlp_tpu.trainer.unified_checkpoint import (
    COMMIT_MANIFEST,
    CorruptCheckpointError,
    get_last_committed_checkpoint,
    get_last_legacy_checkpoint,
    is_committed,
    join_pending_saves,
    load_unified_checkpoint,
    rotate_checkpoints,
    save_unified_checkpoint,
    validate_checkpoint,
)
from paddlenlp_tpu.utils.faults import FAULTS, InjectedFault
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=56,
                      num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
                      max_position_embeddings=32)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_state(model, step=0):
    opt_state = optax.adam(1e-3).init(model.params)
    return TrainState(params=model.params, opt_state=opt_state,
                      step=jnp.asarray(step, jnp.int32))


def save_step(tmp_path, model, step, **kw):
    ckpt = os.path.join(str(tmp_path), f"checkpoint-{step}")
    save_unified_checkpoint(ckpt, model=model, train_state=make_state(model, step),
                            trainer_state=TrainerState(global_step=step), **kw)
    return ckpt


class TestCommitProtocol:
    def test_roundtrip_commits_and_validates(self, tmp_path, model):
        ckpt = save_step(tmp_path, model, 2)
        assert validate_checkpoint(ckpt) is None and is_committed(ckpt)
        manifest = json.loads(open(os.path.join(ckpt, COMMIT_MANIFEST)).read())
        assert manifest["step"] == 2
        assert "optimizer.safetensors" in manifest["files"]
        assert all((tmp_path / f"checkpoint-2" / rel).stat().st_size == size
                   for rel, size in manifest["files"].items())
        # no staging litter after a clean commit
        assert not os.path.isdir(ckpt + ".tmp")
        state, trainer_state = load_unified_checkpoint(ckpt, model, make_state(model))
        assert int(np.asarray(state.step)) == 2 and trainer_state.global_step == 2
        for a, b in zip(np.asarray(model.params["model"]["embed_tokens"]["embedding"]).ravel()[:8],
                        np.asarray(state.params["model"]["embed_tokens"]["embedding"]).ravel()[:8]):
            np.testing.assert_allclose(a, b)

    def test_fault_mid_shard_write_leaves_no_committed_dir(self, tmp_path, model):
        """ISSUE 3 acceptance: kill the save mid-optimizer-shard → no
        commit.json anywhere, resume discovery falls back to the previous
        committed step, rotation keeps the fallback."""
        save_step(tmp_path, model, 2)

        FAULTS.arm("ckpt.write_shard", action="partial", nth=1)
        with pytest.raises(InjectedFault):
            save_step(tmp_path, model, 4)

        final = os.path.join(str(tmp_path), "checkpoint-4")
        staging = final + ".tmp"
        assert not os.path.isdir(final)  # rename never happened
        assert os.path.isdir(staging)  # torn staging left for diagnosis
        assert not os.path.isfile(os.path.join(staging, COMMIT_MANIFEST))
        # the torn optimizer shard really is torn (partial action truncates)
        opt = os.path.join(staging, "optimizer.safetensors")
        assert os.path.isfile(opt)

        # resume auto-discovery: the torn save is invisible, step 2 is the target
        fallback = get_last_committed_checkpoint(str(tmp_path))
        assert fallback == os.path.join(str(tmp_path), "checkpoint-2")
        state, trainer_state = load_unified_checkpoint(fallback, model, make_state(model))
        assert int(np.asarray(state.step)) == 2 and trainer_state.global_step == 2

        # rotation with limit=1 must NOT reap the fallback (it is the newest
        # committed checkpoint), even though a higher-numbered dir exists
        deleted = rotate_checkpoints(str(tmp_path), limit=1)
        assert deleted == []
        assert os.path.isdir(fallback)

    def test_crash_before_commit_manifest(self, tmp_path, model):
        """Crash between payload write and manifest: same guarantees."""
        save_step(tmp_path, model, 2)
        FAULTS.arm("ckpt.commit")
        with pytest.raises(InjectedFault):
            save_step(tmp_path, model, 4)
        assert not os.path.isdir(os.path.join(str(tmp_path), "checkpoint-4"))
        assert get_last_committed_checkpoint(str(tmp_path)).endswith("checkpoint-2")

    def test_next_save_reclaims_stale_staging(self, tmp_path, model):
        FAULTS.arm("ckpt.commit")
        with pytest.raises(InjectedFault):
            save_step(tmp_path, model, 4)
        FAULTS.reset()
        ckpt = save_step(tmp_path, model, 4)  # same step, fresh save
        assert is_committed(ckpt)
        assert not os.path.isdir(ckpt + ".tmp")

    def test_torn_committed_dir_detected_and_load_refuses(self, tmp_path, model):
        """A committed dir whose bytes no longer match the manifest (disk
        corruption, partial rsync) is not trusted: load raises, discovery
        skips it."""
        save_step(tmp_path, model, 2)
        ckpt4 = save_step(tmp_path, model, 4)
        opt = os.path.join(ckpt4, "optimizer.safetensors")
        with open(opt, "r+b") as f:
            f.truncate(os.path.getsize(opt) // 2)
        assert "size mismatch" in validate_checkpoint(ckpt4)
        with pytest.raises(CorruptCheckpointError):
            load_unified_checkpoint(ckpt4, model, make_state(model))
        assert get_last_committed_checkpoint(str(tmp_path)).endswith("checkpoint-2")

    def test_manifest_carries_content_hashes(self, tmp_path, model):
        ckpt = save_step(tmp_path, model, 2)
        manifest = json.loads(open(os.path.join(ckpt, COMMIT_MANIFEST)).read())
        assert manifest["version"] == 2
        assert set(manifest["sha256"]) == set(manifest["files"])
        assert all(len(h) == 64 for h in manifest["sha256"].values())

    def test_bit_rot_same_size_detected_by_hash(self, tmp_path, model):
        """Size validation cannot see a flipped byte; the sha256 pass must."""
        save_step(tmp_path, model, 2)
        ckpt4 = save_step(tmp_path, model, 4)
        opt = os.path.join(ckpt4, "optimizer.safetensors")
        size = os.path.getsize(opt)
        with open(opt, "r+b") as f:  # flip one payload byte, length unchanged
            f.seek(size - 1)
            byte = f.read(1)
            f.seek(size - 1)
            f.write(bytes([byte[0] ^ 0xFF]))
        assert os.path.getsize(opt) == size
        reason = validate_checkpoint(ckpt4)
        assert reason is not None and "content hash mismatch" in reason
        # sizes alone still pass — exactly the gap hashes exist to close
        assert validate_checkpoint(ckpt4, verify_hashes=False) is None
        with pytest.raises(CorruptCheckpointError):
            load_unified_checkpoint(ckpt4, model, make_state(model))
        assert get_last_committed_checkpoint(str(tmp_path)).endswith("checkpoint-2")

    def test_pre_hash_manifest_still_validates_with_warning(self, tmp_path, model, monkeypatch):
        """A version-1 manifest (sizes only) written by an older trainer keeps
        loading — integrity is size-only and says so."""
        ckpt = save_step(tmp_path, model, 2)
        path = os.path.join(ckpt, COMMIT_MANIFEST)
        manifest = json.loads(open(path).read())
        del manifest["sha256"]
        manifest["version"] = 1
        with open(path, "w") as f:
            json.dump(manifest, f)
        # the project logger bypasses caplog (propagate=False): intercept the
        # warning method itself
        from paddlenlp_tpu.trainer import unified_checkpoint as uc

        warnings = []
        monkeypatch.setattr(uc.logger, "warning",
                            lambda msg, *a, **k: warnings.append(str(msg)))
        assert validate_checkpoint(ckpt) is None
        assert any("no content hashes" in w for w in warnings)
        state, _ = load_unified_checkpoint(ckpt, model, make_state(model))
        assert int(np.asarray(state.step)) == 2

    def test_commit_stamps_metrics_plane(self, tmp_path, model):
        """The commit path must feed ckpt_last_commit_age_seconds."""
        from paddlenlp_tpu.trainer import integrations

        before = time.time()
        save_step(tmp_path, model, 2)
        assert integrations._LAST_COMMIT_T is not None
        assert integrations._LAST_COMMIT_T >= before
        assert integrations._ckpt_commit_age_seconds() >= 0.0

    def test_legacy_checkpoint_without_manifest_still_loads(self, tmp_path, model):
        ckpt = save_step(tmp_path, model, 2)
        os.unlink(os.path.join(ckpt, COMMIT_MANIFEST))
        state, _ = load_unified_checkpoint(ckpt, model, make_state(model))
        assert int(np.asarray(state.step)) == 2
        # but auto-discovery holds it to the committed standard
        assert get_last_committed_checkpoint(str(tmp_path)) is None
        # ... and the Trainer's legacy fallback finds it
        assert get_last_legacy_checkpoint(str(tmp_path)) == ckpt

    def test_legacy_fallback_skips_torn_committed_dirs(self, tmp_path, model):
        """The legacy fallback returns manifest-LESS dirs only: a dir with a
        manifest that fails validation is a torn save, and handing it to the
        loader would crash resume instead of using the older legacy state."""
        legacy = save_step(tmp_path, model, 2)
        os.unlink(os.path.join(legacy, COMMIT_MANIFEST))  # pre-protocol dir
        torn = save_step(tmp_path, model, 4)  # newer, committed...
        opt = os.path.join(torn, "optimizer.safetensors")
        with open(opt, "r+b") as f:  # ...then damaged on disk
            f.truncate(os.path.getsize(opt) // 2)
        assert get_last_committed_checkpoint(str(tmp_path)) is None
        assert get_last_legacy_checkpoint(str(tmp_path)) == legacy  # NOT checkpoint-4


class TestRotation:
    def test_rotates_only_committed_beyond_limit(self, tmp_path, model):
        for step in (2, 4, 6):
            save_step(tmp_path, model, step)
        deleted = rotate_checkpoints(str(tmp_path), limit=2)
        assert [os.path.basename(d) for d in deleted] == ["checkpoint-2"]
        assert sorted(d for d in os.listdir(tmp_path) if d.startswith("checkpoint-")) == \
            ["checkpoint-4", "checkpoint-6"]

    def test_best_checkpoint_guard_normalizes_paths(self, tmp_path, model):
        """The old guard compared raw strings — a relative
        best_model_checkpoint failed to protect the absolute dir."""
        for step in (2, 4, 6):
            save_step(tmp_path, model, step)
        rel_best = os.path.relpath(os.path.join(str(tmp_path), "checkpoint-2"))
        deleted = rotate_checkpoints(str(tmp_path), limit=1, best_model_checkpoint=rel_best)
        assert os.path.isdir(os.path.join(str(tmp_path), "checkpoint-2"))  # protected
        assert [os.path.basename(d) for d in deleted] == ["checkpoint-4"]

    def test_uncommitted_dir_never_deleted(self, tmp_path, model):
        for step in (4, 6, 8):
            save_step(tmp_path, model, step)
        torn = os.path.join(str(tmp_path), "checkpoint-2")
        os.makedirs(torn)
        (lambda p: open(p, "w").write("partial"))(os.path.join(torn, "optimizer.safetensors"))
        rotate_checkpoints(str(tmp_path), limit=1)
        assert os.path.isdir(torn)  # torn dir kept for diagnosis

    def test_async_save_commits_and_joins(self, tmp_path, model):
        from paddlenlp_tpu.trainer import unified_checkpoint as uc

        ckpt = save_step(tmp_path, model, 2, async_save=True)
        assert join_pending_saves(timeout=60.0) == 0
        assert uc._pending_saves == []  # finished writers reaped, not leaked
        assert is_committed(ckpt)

    def test_after_commit_hook_rotates_on_writer_thread(self, tmp_path, model):
        """Trainer wires rotation through after_commit so async saves stay
        async: the hook must run post-rename (the new checkpoint is committed
        and protected) on the writer thread."""
        for step in (2, 4):
            save_step(tmp_path, model, step)
        ckpt6 = os.path.join(str(tmp_path), "checkpoint-6")
        save_unified_checkpoint(
            ckpt6, model=model, train_state=make_state(model, 6),
            trainer_state=TrainerState(global_step=6), async_save=True,
            after_commit=lambda: rotate_checkpoints(str(tmp_path), limit=2))
        assert join_pending_saves(timeout=60.0) == 0
        assert is_committed(ckpt6)
        assert sorted(d for d in os.listdir(tmp_path) if d.startswith("checkpoint-")) == \
            ["checkpoint-4", "checkpoint-6"]

    def test_after_commit_skipped_when_save_fails(self, tmp_path, model):
        hook_ran = []
        FAULTS.arm("ckpt.commit")
        with pytest.raises(InjectedFault):
            save_unified_checkpoint(
                os.path.join(str(tmp_path), "checkpoint-2"), model=model,
                train_state=make_state(model, 2),
                after_commit=lambda: hook_ran.append(True))
        assert hook_ran == []  # never rotate on behalf of a save that died

    def test_async_save_failure_is_reaped_and_uncommitted(self, tmp_path, model):
        FAULTS.arm("ckpt.write_shard", nth=1)
        save_step(tmp_path, model, 4, async_save=True)
        assert join_pending_saves(timeout=60.0) == 0
        assert not os.path.isdir(os.path.join(str(tmp_path), "checkpoint-4"))
        assert get_last_committed_checkpoint(str(tmp_path)) is None
