"""Postmortem chaos (ISSUE 13 acceptance): under injected faults a bundle is
auto-dumped, is valid JSON, contains the poisoned request's decision trail,
and tools/postmortem.py reconstructs a monotonic cross-tier timeline.

Two scenarios:

- **disagg**: ``engine.kv_migrate`` + ``engine.step`` armed on a
  disaggregated (1,1) engine behind a supervised serving server — each fault
  trips a supervisor degrade that auto-dumps a bundle to
  ``PDNLP_TPU_POSTMORTEM_DIR``; after recovery an on-demand bundle carries
  the victim's full trail (admission → chunk grants → migration → requeue)
  and the offline analyzer renders it end to end;
- **router join**: a hedged fleet request and a failed-over request leave
  router-tier events (hedge_fire/commit/abort, failover) that join the
  replica's engine events (admit.accept) on ONE trace id in the router's
  bundle — the cross-tier decision trail the flight recorder exists for.
"""

import http.client
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams  # noqa: E402
from paddlenlp_tpu.observability import RECORDER  # noqa: E402
from paddlenlp_tpu.observability.postmortem import ENV_DIR  # noqa: E402
from paddlenlp_tpu.serving import (  # noqa: E402
    MetricsRegistry,
    SchedulerConfig,
    ServingServer,
    SupervisorPolicy,
)
from paddlenlp_tpu.serving.router import launch_fleet  # noqa: E402
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM  # noqa: E402
from paddlenlp_tpu.utils.faults import FAULTS  # noqa: E402
from tools.postmortem import (  # noqa: E402
    attribution_for,
    load_bundles,
    timeline_for,
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    FAULTS.reset()
    RECORDER.clear()
    RECORDER.set_enabled(True)
    yield
    FAULTS.reset()
    RECORDER.clear()


@pytest.fixture(scope="module")
def model(eight_devices):
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=8,
                      max_position_embeddings=256, eos_token_id=None, pad_token_id=0,
                      use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def post_json(port, path, payload, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


GEN_LEN = 10


class TestDisaggPostmortem:
    def test_bundles_auto_dumped_and_trail_reconstructed(self, model, tmp_path,
                                                         monkeypatch):
        """engine.kv_migrate kills a step whose victim already streamed its
        first token; after recovery engine.step kills another step. Each
        degrade auto-dumps a bundle; the analyzer reconstructs the victim's
        decision trail as one monotonic timeline with its attribution."""
        monkeypatch.setenv(ENV_DIR, str(tmp_path))
        registry = MetricsRegistry()

        def make_engine():
            return InferenceEngine(model, disagg_stages=(1, 1), max_batch_size=4,
                                   block_size=4, num_blocks=128,
                                   max_blocks_per_seq=32, decode_steps=4)

        srv = ServingServer(
            make_engine(), engine_factory=make_engine,
            supervisor_policy=SupervisorPolicy(max_retries=2, backoff_base_s=0.3,
                                               backoff_max_s=1.0),
            scheduler_config=SchedulerConfig(max_inflight=16, default_timeout_s=600.0),
            registry=registry)
        srv.loop.postmortem.min_interval_s = 0.0  # both incidents must dump
        port = srv.start_in_thread()
        try:
            FAULTS.arm("engine.kv_migrate", nth=1)
            results = {}

            def stream_worker(i):
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
                conn.request("POST", "/v1/completions",
                             body=json.dumps({"prompt": [5 + i, 6 + i, 7 + i],
                                              "max_tokens": GEN_LEN, "stream": True}),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200
                toks, finish = [], None
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    line = line.strip()
                    if not line.startswith(b"data: ") or line == b"data: [DONE]":
                        if line == b"data: [DONE]":
                            break
                        continue
                    ev = json.loads(line[len(b"data: "):])
                    c = ev["choices"][0]
                    if c.get("finish_reason"):
                        finish = c["finish_reason"]
                    elif "token" in c:
                        toks.append(c["token"])
                results[i] = (toks, finish)
                conn.close()

            threads = [threading.Thread(target=stream_worker, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            deadline = time.time() + 120
            while time.time() < deadline and srv.loop.postmortem.dumps < 1:
                time.sleep(0.01)
            assert srv.loop.postmortem.dumps >= 1, \
                "kv_migrate degrade never auto-dumped a bundle"
            for t in threads:
                t.join(timeout=600)
            assert FAULTS.fired("engine.kv_migrate") == 1

            # incident 2: a plain step fault after recovery — second bundle
            FAULTS.arm("engine.step", nth=1)
            t = threading.Thread(target=stream_worker, args=(50,))
            t.start()
            t.join(timeout=600)
            assert FAULTS.fired("engine.step") == 1

            # zero stream loss, token-exact vs a solo run (recovery honest)
            assert len(results) == 4
            for i, (toks, finish) in results.items():
                assert finish == "length" and len(toks) == GEN_LEN, (i, finish)
            solo = make_engine().generate([[5, 6, 7]],
                                          SamplingParams(max_new_tokens=GEN_LEN))[0]
            np.testing.assert_array_equal(results[0][0], solo)

            # ---- the auto-dumped bundles: valid JSON, right trigger, and the
            # first one carries the poisoned request's trail-so-far
            auto = sorted(p for p in os.listdir(tmp_path)
                          if p.startswith("postmortem-replica-supervisor_degraded-"))
            assert len(auto) >= 2, auto
            first = json.load(open(tmp_path / auto[0]))
            assert first["version"] == 1 and first["trigger"] == "supervisor_degraded"
            assert "kv_migrate" in first["detail"]["error"]
            ev_names = {e["name"] for e in first["events"]}
            assert "supervisor.degraded" in ev_names
            assert "admit.accept" in ev_names
            # the poisoned request (the migration fault fires on the first
            # admitted sequence's handoff) is identifiable in the bundle
            victims = {e.get("trace") for e in first["events"]
                       if e["name"] == "admit.accept"}
            assert len(victims) >= 1
            assert first["health"]["engine"]["backend"]["kind"] == "disagg"
            assert first["config"]["staged"] is True

            # ---- on-demand bundle after recovery: the analyzer reconstructs
            # one victim's FULL decision trail, monotonic, with attribution
            status, doc = post_json(port, "/debug/postmortem", {})
            assert status == 200
            bundles = load_bundles([doc["path"]])
            victim = sorted(victims)[0]
            entries = timeline_for(bundles, victim)
            names = [e["name"] for e in entries if e["kind"] == "event"]
            assert "admit.accept" in names
            assert "migrate.start" in names and "migrate.land" in names
            ts = [e["t"] for e in entries]
            assert ts == sorted(ts) and len(ts) >= 3  # monotonic timeline
            row = attribution_for(bundles, victim)
            assert row is not None and row["finish_reason"] == "length"
            attr = row["attribution"]
            e2e = row["finish_t"] - row["arrival_t"]
            assert abs(sum(attr.values()) - e2e) <= 0.05 * e2e
            assert attr["migration_wait"] > 0.0
        finally:
            srv.shutdown(drain_timeout_s=10)


class TestRouterJoinPostmortem:
    def test_hedge_and_failover_events_join_replica_events_on_trace(self, model,
                                                                    tmp_path):
        """Router hedge/failover events and replica engine events share one
        trace id in a single bundle (in-process fleet = shared recorder) and
        the analyzer joins them into one monotonic trail."""
        def make_engine():
            return InferenceEngine(model, max_batch_size=4, block_size=4,
                                   num_blocks=128, max_blocks_per_seq=32,
                                   decode_steps=4)

        fleet = launch_fleet(
            2, make_engine, router_registry=MetricsRegistry(),
            poll_interval_s=0.2, hedge_after_s=0.2,
            scheduler_config=SchedulerConfig(max_inflight=16))
        port = fleet.router_port
        try:
            # ---- hedge: delay the primary leg's forward past the budget so
            # the shadow fires and wins; the loser is torn down
            FAULTS.arm("router.forward", action="delay", delay_s=1.5, nth=1)
            status, doc = post_json(port, "/v1/completions",
                                    {"prompt": [5, 6, 7], "max_tokens": 4})
            assert status == 200
            hedged_rid = doc["id"]
            hedge_names = [e.name for e in RECORDER.snapshot(
                trace=hedged_rid, name_prefix="router.hedge_")]
            # fire first; the loser is torn down before the commit is booked
            assert hedge_names[0] == "router.hedge_fire"
            assert {"router.hedge_commit", "router.hedge_abort"} <= set(hedge_names)
            # the hedge_race phase landed in the shared histogram family
            hist = fleet.router.registry.get(
                "paddlenlp_serving_latency_attribution_seconds")
            assert hist.count(phase="hedge_race") == 1

            # ---- failover: the first accepting replica 500s the submission
            # (serving.submit fault) -> the router resubmits elsewhere
            FAULTS.reset()
            FAULTS.arm("serving.submit", nth=1)
            status, doc = post_json(port, "/v1/completions",
                                    {"prompt": [8, 9, 10], "max_tokens": 4})
            assert status == 200
            failed_rid = doc["id"]
            assert any(e.name == "router.failover" for e in
                       RECORDER.snapshot(trace=failed_rid))

            # ---- one router bundle joins both tiers on the trace ids
            status, pm = post_json(port, "/debug/postmortem", {})
            assert status == 200 and pm["tier"] == "router"
            bundles = load_bundles([pm["path"]])
            for rid, router_event in ((hedged_rid, "router.hedge_commit"),
                                      (failed_rid, "router.failover")):
                entries = timeline_for(bundles, rid)
                names = [e["name"] for e in entries if e["kind"] == "event"]
                tiers = {e["name"]: e["tier"] for e in entries
                         if e["kind"] == "event"}
                assert router_event in names, (rid, names)
                assert "admit.accept" in names, (rid, names)
                assert tiers[router_event] == "router"
                assert tiers["admit.accept"] == "engine"
                ts = [e["t"] for e in entries]
                assert ts == sorted(ts)  # joined timeline stays monotonic
        finally:
            fleet.shutdown(drain_timeout_s=10)
