"""tier-1 enforcement of fault-point catalog hygiene: tools/check_faults.py
must find every used fault point registered + documented and every catalog
entry wired to a call site (same pattern as test_check_metrics)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
TOOL = os.path.join(REPO, "tools", "check_faults.py")


class TestCheckFaults:
    def test_catalog_lints_clean(self):
        proc = subprocess.run(
            [sys.executable, TOOL], capture_output=True, text=True, timeout=120, cwd=REPO,
        )
        line = next((ln for ln in reversed(proc.stdout.strip().splitlines())
                     if ln.startswith("{")), None)
        assert line is not None, f"no JSON output (rc={proc.returncode}): {proc.stderr[-2000:]}"
        report = json.loads(line)
        assert proc.returncode == 0 and report["ok"], report["problems"]
        # the catalog covers the checkpoint writer, engine step, supervisor
        # rebuild, admission, and the router front tier
        assert report["catalog"] >= 7
        assert report["call_sites"] >= report["catalog"]

    def test_router_fault_points_registered(self):
        from paddlenlp_tpu.utils.faults import CATALOG

        assert "router.forward" in CATALOG
        assert "router.health_poll" in CATALOG

    def test_scan_flags_unregistered_use(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import check_faults
        finally:
            sys.path.pop(0)
        src = tmp_path / "mod.py"
        src.write_text('P = FaultPoint("made.up")\nFAULTS.arm("engine.step")\n')
        sites = check_faults.scan_call_sites(str(tmp_path))
        assert sites == {"made.up": [os.path.relpath(str(src), check_faults.ROOT)],
                         "engine.step": [os.path.relpath(str(src), check_faults.ROOT)]}
