"""Zero-downtime fleet weight rollout chaos (ISSUE 18 acceptance), against
REAL in-process replicas (tiny CPU model — tier-1 speed).

- **Clean rollout under live traffic**: a two-replica fleet rolls from v0 to
  v1 weights one replica at a time while SSE streams are mid-flight and a
  prober hammers the router. Zero downtime (every prober request answers
  200), zero stream loss (every pre-rollout stream finishes token-exact
  under the OLD weights — drain lets them complete before their replica
  swaps), and post-rollout outputs are token-exact against a fresh engine
  started on the NEW weights.
- **Swap fault mid-rollout**: ``engine.weight_swap`` armed to fire on the
  SECOND replica of a three-replica fleet, under 8 live streams. The faulted
  replica rolls itself back (all-or-nothing), the router aborts the rollout
  and rolls the already-swapped replica back from ``rollback_ckpt_dir``, and
  the fleet converges back on v0: zero stream loss, zero 5xx, every replica
  reporting v0 and generating v0 tokens, no KV block or parameter leak.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import SchedulerConfig, SupervisorPolicy
from paddlenlp_tpu.serving.engine_loop import CANARY_PROMPT_IDS, canary_digest
from paddlenlp_tpu.serving.router import launch_fleet
from paddlenlp_tpu.trainer.unified_checkpoint import save_unified_checkpoint
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS
from tools.rollout import main as rollout_main

CFG = dict(vocab_size=96, hidden_size=64, intermediate_size=112,
           num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
           max_position_embeddings=256, eos_token_id=None, pad_token_id=0,
           use_scan_layers=True)
ENG_KW = dict(max_batch_size=8, block_size=4, num_blocks=256,
              max_blocks_per_seq=32, decode_steps=4)
GEN_LEN = 24


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig(**CFG)


@pytest.fixture(scope="module")
def ckpts(cfg, tmp_path_factory):
    root = tmp_path_factory.mktemp("rollout")
    save_unified_checkpoint(str(root / "v0"),
                            LlamaForCausalLM.from_config(cfg, seed=0), None)
    save_unified_checkpoint(str(root / "v1"),
                            LlamaForCausalLM.from_config(cfg, seed=1), None)
    return root


@pytest.fixture(scope="module")
def solo_old(cfg):
    return InferenceEngine(LlamaForCausalLM.from_config(cfg, seed=0), **ENG_KW)


@pytest.fixture(scope="module")
def solo_new(cfg):
    return InferenceEngine(LlamaForCausalLM.from_config(cfg, seed=1), **ENG_KW)


def make_engine_factory(cfg):
    """Every replica gets its OWN model instance — the single-device backend
    installs swapped params by rebinding ``model.params``, so a shared model
    would leak one replica's swap into its neighbors."""
    def make_engine():
        return InferenceEngine(LlamaForCausalLM.from_config(cfg, seed=0),
                               **ENG_KW)
    return make_engine


def post_json(port, path, payload, timeout=300):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def get_json(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def stream_request(port, prompt, max_tokens, out, key, timeout=600):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions",
                     body=json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                                      "stream": True}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        toks, finish = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                break
            ev = json.loads(data)
            c = ev["choices"][0]
            if c.get("finish_reason"):
                finish = c["finish_reason"]
            elif "token" in c:
                toks.append(c["token"])
        out[key] = (resp.status, toks, finish)
    finally:
        conn.close()


class Prober:
    """Background zero-downtime witness: keeps firing small completions at
    the router and records every status code until stopped."""

    def __init__(self, port):
        self.port = port
        self.statuses = []
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        i = 0
        while not self._stop.is_set():
            try:
                status, _ = post_json(self.port, "/v1/completions",
                                      {"prompt": [60, 61, 62, (63 + i) % 90 + 1],
                                       "max_tokens": 4}, timeout=120)
                self.statuses.append(status)
            except OSError as e:  # a transport error IS downtime
                self.statuses.append(repr(e))
            i += 1
            time.sleep(0.05)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join(timeout=120)


def assert_no_kv_leak(server):
    mgr = server.loop.engine.mgr
    assert mgr.num_free == mgr.total_usable_blocks, \
        f"KV leak: {mgr.total_usable_blocks - mgr.num_free} blocks still held"


def launch(n, cfg):
    return launch_fleet(
        n, make_engine_factory(cfg), poll_interval_s=0.05,
        scheduler_config=SchedulerConfig(max_inflight=16, default_timeout_s=600.0),
        supervisor_policy=SupervisorPolicy(backoff_base_s=0.1, backoff_max_s=0.5))


class TestCleanRollout:
    def test_rolling_swap_zero_downtime_token_exact(
            self, cfg, ckpts, solo_old, solo_new, capsys):
        fleet = launch(2, cfg)
        router, port = fleet.router, fleet.router_port
        try:
            # warm both replicas (jit compiles outside the measured window)
            for p in fleet.ports:
                status, _ = post_json(p, "/v1/completions",
                                      {"prompt": [9, 8, 7], "max_tokens": GEN_LEN})
                assert status == 200

            expected = canary_digest(
                solo_new.generate([list(CANARY_PROMPT_IDS)], None)[0])
            n_stream = 4
            results = {}
            threads = [threading.Thread(
                target=stream_request,
                args=(port, [20 + i, 21, 22, 23], GEN_LEN, results, i))
                for i in range(n_stream)]
            with Prober(port) as prober:
                for t in threads:
                    t.start()
                time.sleep(0.2)  # streams in flight before the rollout starts
                # drive the rollout through the operator CLI: submit, follow
                # to terminal, one JSONL decision line per transition, rc 0
                rc = rollout_main(["--router", f"127.0.0.1:{port}",
                                   "--ckpt-dir", str(ckpts / "v1"),
                                   "--rollback-ckpt-dir", str(ckpts / "v0"),
                                   "--canary-digest", expected,
                                   "--drain-deadline", "60",
                                   "--rejoin-timeout", "60"])
                assert rc == 0
                log = [json.loads(line) for line
                       in capsys.readouterr().out.splitlines() if line.strip()]
                assert log[0]["event"] == "submitted"
                assert sum(e["event"] == "replica_done" for e in log) == 2
                assert log[-1]["event"] == "terminal"
                assert log[-1]["status"] == "done"
                status, doc = get_json(port, "/admin/weights/rollout")
                assert status == 200
                rollout = doc["rollout"]
                assert rollout["status"] == "done"
                assert sorted(rollout["completed"]) == sorted(
                    fleet.replica_id(i) for i in range(2))
                assert rollout["skipped"] == [] and rollout["abort_reason"] is None
                for t in threads:
                    t.join(timeout=600)
                # the fleet answers on the new weights before the prober stops
                status, body = post_json(port, "/v1/completions",
                                         {"prompt": [5, 4, 3], "max_tokens": 8})
                assert status == 200

            # ---- zero downtime: every prober request answered 200
            assert prober.statuses, "prober never ran"
            assert all(s == 200 for s in prober.statuses), \
                [s for s in prober.statuses if s != 200][:5]

            # ---- zero stream loss: pre-rollout streams finished token-exact
            # under the OLD weights (drain let them complete before the swap)
            for i in range(n_stream):
                status, toks, finish = results[i]
                assert status == 200 and finish == "length", (i, results[i])
                want = solo_old.generate(
                    [[20 + i, 21, 22, 23]], SamplingParams(max_new_tokens=GEN_LEN))[0]
                np.testing.assert_array_equal(toks, want)

            # ---- post-rollout: token-exact vs a fresh engine on NEW weights
            want = solo_new.generate([[5, 4, 3]], SamplingParams(max_new_tokens=8))[0]
            np.testing.assert_array_equal(body["choices"][0]["token_ids"], want)

            # ---- every replica converged: health + pool + metrics agree
            for i, p in enumerate(fleet.ports):
                status, health = get_json(p, "/health")
                assert status == 200 and health["weights_version"] == "v1"
                assert fleet.servers[i].loop.weights_version == "v1"
            status, reps = get_json(port, "/replicas")
            assert status == 200
            assert all(r["weights_version"] == "v1" for r in reps["replicas"])
            assert reps["rollout"]["status"] == "done"

            # ---- nothing leaked on either replica
            for server in fleet.servers:
                assert_no_kv_leak(server)
        finally:
            fleet.shutdown(drain_timeout_s=5)


class TestFaultedRolloutRollsBack:
    def test_swap_fault_on_second_replica_fleet_rolls_back(
            self, cfg, ckpts, solo_old, capsys):
        fleet = launch(3, cfg)
        router, port = fleet.router, fleet.router_port
        try:
            for p in fleet.ports:
                status, _ = post_json(p, "/v1/completions",
                                      {"prompt": [9, 8, 7], "max_tokens": GEN_LEN})
                assert status == 200

            # the fault point fires inside the quiesced swap, BEFORE
            # sync_params: hit 1 = first replica's swap (passes), hit 2 =
            # second replica's swap (fails -> replica-side rollback ->
            # router-side abort). The faults registry is process-global, so
            # the in-process fleet shares one hit counter.
            FAULTS.arm("engine.weight_swap", nth=(2,))

            n_stream = 8
            results = {}
            threads = [threading.Thread(
                target=stream_request,
                args=(port, [30 + i, 31, 32, 33], GEN_LEN, results, i))
                for i in range(n_stream)]
            with Prober(port) as prober:
                for t in threads:
                    t.start()
                time.sleep(0.2)
                # the CLI contract under fire: rc 1 when the rollout aborts
                # and rolls back, with the abort visible in the decision log
                rc = rollout_main(["--router", f"127.0.0.1:{port}",
                                   "--ckpt-dir", str(ckpts / "v1"),
                                   "--rollback-ckpt-dir", str(ckpts / "v0"),
                                   "--drain-deadline", "60",
                                   "--rejoin-timeout", "60"])
                assert rc == 1
                log = [json.loads(line) for line
                       in capsys.readouterr().out.splitlines() if line.strip()]
                assert log[-1]["event"] == "terminal"
                assert log[-1]["status"] == "aborted"
                assert log[-1]["abort_reason"] == "swap_failed"
                status, doc = get_json(port, "/admin/weights/rollout")
                assert status == 200
                rollout = doc["rollout"]
                assert rollout["status"] == "aborted"
                assert rollout["abort_reason"] == "swap_failed"
                for t in threads:
                    t.join(timeout=600)

            assert FAULTS.fired("engine.weight_swap") == 1

            # ---- exactly one replica had swapped; it was rolled back
            assert len(rollout["completed"]) == 1
            assert rollout["rolled_back"] == rollout["completed"]
            assert rollout["rollback_failed"] == []

            # ---- zero stream loss, zero 5xx: every live stream finished
            # token-exact under the OLD weights
            for i in range(n_stream):
                status, toks, finish = results[i]
                assert status == 200 and finish == "length", (i, results[i])
                want = solo_old.generate(
                    [[30 + i, 31, 32, 33]], SamplingParams(max_new_tokens=GEN_LEN))[0]
                np.testing.assert_array_equal(toks, want)
            assert all(s == 200 for s in prober.statuses), \
                [s for s in prober.statuses if s != 200][:5]

            # ---- the fleet converged BACK: every replica reports v0, routes
            # traffic, generates v0 tokens, and holds the v0 parameters
            deadline = time.time() + 30
            while time.time() < deadline and not all(
                    s.weights_version == "v0" and not s.draining
                    for s in router.pool.snapshots()):
                time.sleep(0.05)
            for i, p in enumerate(fleet.ports):
                status, health = get_json(p, "/health")
                assert status == 200 and health["weights_version"] == "v0"
                status, body = post_json(p, "/v1/completions",
                                         {"prompt": [50, 51, 52], "max_tokens": 8})
                assert status == 200, (fleet.replica_id(i), body)
            want = solo_old.generate([[50, 51, 52]],
                                     SamplingParams(max_new_tokens=8))[0]
            np.testing.assert_array_equal(body["choices"][0]["token_ids"], want)

            # ---- no parameter leak: every replica's resident tree is the v0
            # tree again, bit-for-bit (the retained new tree was dropped)
            v0_leaf = np.asarray(
                next(iter(_leaves(solo_old.model.params))))
            for server in fleet.servers:
                leaf = np.asarray(next(iter(_leaves(server.engine.model.params))))
                np.testing.assert_array_equal(leaf, v0_leaf)
                assert_no_kv_leak(server)

            # ---- the router still takes a fresh rollout after the abort
            # (the in-progress guard was released)
            status, reps = get_json(port, "/replicas")
            assert status == 200 and reps["rollout"]["status"] == "aborted"
        finally:
            fleet.shutdown(drain_timeout_s=5)


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)
