"""Closed-loop fleet chaos (ISSUE 14 acceptance): the full reflex arc against
REAL in-process replicas (tiny CPU model — tier-1 speed).

- **Kill → replace**: a replica takes an ``engine.step`` fault (its stream
  recovers token-exact through the supervisor), then its whole HTTP plane
  dies mid-run — the crashed-process case the supervisor cannot absorb. The
  health poller demotes it to DOWN, and the running autoscaler force-removes
  the tombstone and provisions + joins a replacement, while concurrent
  streams on the survivor finish token-exact (zero stream loss, zero client
  5xx) and the fleet's availability burn stays bounded.
- **Max-envelope hold → brownout handoff**: an autoscaler pinned at its max
  envelope under overload cannot scale; it must record ``scale.hold
  {max_envelope}`` and push a brownout floor to the replicas, after which
  best-effort traffic sheds with a clean 503 + Retry-After while interactive
  requests keep completing — the fleet degrades selectively instead of
  timing out uniformly.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from paddlenlp_tpu.experimental import InferenceEngine, SamplingParams
from paddlenlp_tpu.serving import MetricsRegistry, SchedulerConfig, SupervisorPolicy
from paddlenlp_tpu.serving.router import PrefixAffinityPolicy, launch_fleet
from paddlenlp_tpu.serving.router.autoscaler import (
    Autoscaler,
    AutoscalerPolicy,
    InProcessProvisioner,
)
from paddlenlp_tpu.serving.router.pool import DOWN
from paddlenlp_tpu.transformers import LlamaConfig, LlamaForCausalLM
from paddlenlp_tpu.utils.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=112,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=256,
                      eos_token_id=None, pad_token_id=0, use_scan_layers=True)
    return LlamaForCausalLM.from_config(cfg, seed=0)


def make_engine_factory(model):
    def make_engine():
        return InferenceEngine(model, max_batch_size=4, block_size=4, num_blocks=256,
                               max_blocks_per_seq=32, decode_steps=4)
    return make_engine


def post_json(port, path, payload, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def get_json(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def stream_request(port, prompt, max_tokens, out, key, timeout=600, **extra):
    """Collect one SSE stream into ``out[key]`` = (status, tokens, finish)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions",
                     body=json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                                      "stream": True, **extra}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        toks, finish = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                break
            ev = json.loads(data)
            c = ev["choices"][0]
            if c.get("finish_reason"):
                finish = c["finish_reason"]
            elif "token" in c:
                toks.append(c["token"])
        out[key] = (resp.status, toks, finish)
    finally:
        conn.close()


def prefix_pinned_to(router, replica_id, avoid=()):
    """A 3-token prefix the affinity ring pins to ``replica_id``."""
    for k in range(8, 200):
        prefix = [k, k + 1, 7]
        if tuple(prefix) in avoid:
            continue
        pin = router.policy.select(router.pool.snapshots(), prompt=prefix)[0].id
        if pin == replica_id:
            return prefix
    raise AssertionError(f"no prefix pins to {replica_id}")


GEN_LEN = 16


class TestKillAndReplace:
    def test_dead_replica_replaced_with_zero_stream_loss(self, model):
        factory = make_engine_factory(model)
        fleet = launch_fleet(
            2, factory, policy=PrefixAffinityPolicy(prefix_tokens=3),
            poll_interval_s=0.05,
            scheduler_config=SchedulerConfig(max_inflight=16, default_timeout_s=600.0),
            supervisor_policy=SupervisorPolicy(backoff_base_s=0.1, backoff_max_s=0.5))
        router, port = fleet.router, fleet.router_port
        provisioner = InProcessProvisioner(
            factory, replica_kw=dict(
                scheduler_config=SchedulerConfig(max_inflight=16,
                                                 default_timeout_s=600.0)))
        # min == max pins the envelope at 2: the ONLY thing this loop may do
        # is replace the dead replica (up/down thresholds set unreachable so
        # CPU-speed TTFT noise cannot trigger a surprise scale action)
        scaler = Autoscaler(
            ("127.0.0.1", port), provisioner,
            policy=AutoscalerPolicy(
                min_replicas=2, max_replicas=2,
                scale_up_kv_utilization=2.0, scale_up_queue_depth=1e9,
                scale_up_burn_rate=1e18, brownout_push_level=0,
                provision_backoff_base_s=0.1),
            registry=MetricsRegistry(), interval_s=0.1)
        try:
            victim = fleet.replica_id(0)
            survivor = fleet.replica_id(1)
            victim_server, survivor_server = fleet.servers[0], fleet.servers[1]
            victim_prefix = prefix_pinned_to(router, victim)
            survivor_prefix = prefix_pinned_to(router, survivor,
                                               avoid=(tuple(victim_prefix),))

            # ---- the incident starts as an engine fault on the victim: its
            # in-flight stream rides the supervisor rebuild token-exact (the
            # recovery ladder below a process death)
            FAULTS.arm("engine.step", nth=1)
            results = {}
            stream_request(port, victim_prefix + [40], GEN_LEN, results, "victim")
            assert FAULTS.fired("engine.step") == 1
            solo_engine = factory()
            status, toks, finish = results["victim"]
            assert status == 200 and finish == "length"
            np.testing.assert_array_equal(
                toks, solo_engine.generate([victim_prefix + [40]],
                                           SamplingParams(max_new_tokens=GEN_LEN))[0])

            scaler.start()
            # the control loop observes a healthy fleet first: no actions
            time.sleep(0.3)
            assert not [e for e in scaler.events if e[1] != "hold"]

            # ---- concurrent streams on the survivor, in flight through the
            # kill + replacement window
            threads = [threading.Thread(
                target=stream_request,
                args=(port, survivor_prefix + [50 + i], GEN_LEN, results, i))
                for i in range(3)]
            for t in threads:
                t.start()
            deadline = time.time() + 120
            while time.time() < deadline and router._open_forwards_on(survivor) < 3:
                time.sleep(0.005)
            assert router._open_forwards_on(survivor) == 3

            # ---- now the victim's whole HTTP plane dies (crashed process:
            # the supervisor can't absorb this one) -> poller demotes to DOWN
            victim_host_port = f"127.0.0.1:{fleet.ports[0]}"
            victim_server._httpd.shutdown()
            victim_server._httpd.server_close()  # refuse, don't hang, probes
            deadline = time.time() + 30
            while time.time() < deadline:
                rows = {s.id: s.state for s in router.pool.snapshots()}
                if rows.get(victim) == DOWN or victim not in rows:
                    break
                time.sleep(0.02)

            # ---- the autoscaler force-removes the tombstone and provisions
            # + joins a replacement: fleet back at 2 live replicas
            deadline = time.time() + 60
            while time.time() < deadline:
                ids = {s.id for s in router.pool.snapshots()}
                if (victim not in ids and len(ids) == 2
                        and any(a == "provisioned" for _t, a, _d in scaler.events)):
                    break
                time.sleep(0.05)
            ids = {s.id for s in router.pool.snapshots()}
            assert victim not in ids and len(ids) == 2, ids
            assert any(r["id"] == victim_host_port
                       for r in router.pool.removed())
            acted = [a for _t, a, _d in scaler.events]
            assert "replace" in acted and "provisioned" in acted, scaler.events
            assert scaler.metrics.decisions.value(action="replace") == 1.0
            replacement = next(iter(ids - {survivor}))
            assert (replacement.split(":")[0], int(replacement.split(":")[1])) \
                in provisioner.servers

            # ---- zero stream loss: every survivor stream finished 200 and
            # token-exact (no 5xx, no replica_error, no truncation)
            for t in threads:
                t.join(timeout=600)
            assert not any(t.is_alive() for t in threads)
            for i in range(3):
                status, toks, finish = results[i]
                assert status == 200 and finish == "length", (i, results[i])
                np.testing.assert_array_equal(
                    toks, solo_engine.generate(
                        [survivor_prefix + [50 + i]],
                        SamplingParams(max_new_tokens=GEN_LEN))[0])

            # ---- the replacement actually serves: the victim's old prefix
            # re-pins somewhere live and completes
            status, _h, body = post_json(port, "/v1/completions",
                                         {"prompt": victim_prefix + [41],
                                          "max_tokens": 4})
            assert status == 200 and len(body["choices"][0]["token_ids"]) == 4

            # ---- bounded SLO burn: the incident produced no client-visible
            # errors, so the shortest-window availability burn stays below
            # the page-now threshold
            status, slo = get_json(port, "/fleet/slo")
            assert status == 200
            shortest = slo["windows"][min(slo["windows"],
                                          key=lambda w: int(w.rstrip("s")))]
            assert shortest["availability_burn_rate"] < 10.0, slo

            # ---- no KV block leaked on the replicas that served
            for server in (survivor_server, *provisioner.servers.values()):
                mgr = server.loop.engine.mgr
                assert mgr.num_free == mgr.total_usable_blocks
        finally:
            scaler.stop()
            fleet.shutdown(drain_timeout_s=5)
            provisioner.close()


class TestMaxEnvelopeBrownoutHandoff:
    def test_hold_pushes_brownout_and_sheds_best_effort_only(self, model):
        factory = make_engine_factory(model)
        fleet = launch_fleet(
            1, factory, poll_interval_s=0.05,
            scheduler_config=SchedulerConfig(max_inflight=16, default_timeout_s=600.0))
        router, port = fleet.router, fleet.router_port
        provisioner = InProcessProvisioner(factory)
        # queue threshold 0.0 makes every observation "overloaded"; the
        # envelope is pinned at 1, so the only legal reflex is the hold +
        # brownout handoff
        scaler = Autoscaler(
            ("127.0.0.1", port), provisioner,
            policy=AutoscalerPolicy(
                min_replicas=1, max_replicas=1, hysteresis_up=1,
                scale_up_queue_depth=0.0, brownout_push_level=1,
                brownout_push_ttl_s=60.0),
            registry=MetricsRegistry())
        try:
            summary = scaler.evaluate_once()
            assert summary["overloaded"] is True
            assert ("hold", {"reason": "max_envelope"}) in summary["actions"]
            pushed = [a for a in summary["actions"] if a[0] == "brownout_push"]
            assert pushed and pushed[0][1]["replicas"] == 1
            assert scaler.metrics.brownout_pushes.value() == 1.0
            assert len(router.pool) == 1  # hold means HOLD: no scale action

            # the replica is now floored at level 1: best-effort sheds with a
            # clean 503 + Retry-After ...
            status, headers, doc = post_json(
                port, "/v1/completions",
                {"prompt": [5, 6, 7], "max_tokens": 4, "priority": "best_effort"})
            assert status == 503, doc
            assert doc["error"]["type"] in ("overloaded_shed", "no_replica_available")
            assert int(headers.get("Retry-After", "1")) >= 1
            # ... while interactive traffic keeps completing
            status, _h, doc = post_json(
                port, "/v1/completions",
                {"prompt": [5, 6, 7], "max_tokens": 4, "priority": "interactive"})
            assert status == 200, doc
            assert len(doc["choices"][0]["token_ids"]) == 4
            # the replica advertises its level through the health poller
            replica_server = fleet.servers[0]
            assert replica_server.scheduler.brownout.level >= 1
            assert replica_server.scheduler.rejected_shed >= 1

            # pushes refresh per tick while the condition persists
            scaler.evaluate_once()
            assert scaler.metrics.brownout_pushes.value() == 2.0

            # ---- condition clears: the floor lifts (level-0 push), traffic
            # classes equalize again
            ok = scaler.admin.push_brownout("127.0.0.1", fleet.ports[0], 0,
                                            reason="slo_fast_burn")
            assert ok
            assert replica_server.scheduler.brownout.level == 0
            status, _h, doc = post_json(
                port, "/v1/completions",
                {"prompt": [5, 6, 7], "max_tokens": 4, "priority": "best_effort"})
            assert status == 200, doc
        finally:
            fleet.shutdown(drain_timeout_s=5)
            provisioner.close()
